"""Durable metrics history: an append-only on-disk segment ring.

Every serve/route process with ``--history-dir`` set appends a periodic
snapshot of its metrics registry to ``seg-<n>.jsonl`` segments. The
encoding is delta-based so a quiet process costs bytes proportional to
what actually changed:

* each segment opens with a **header** line pinning the format version
  and a schema hash — a reader from an incompatible build refuses with a
  typed ``DataError`` instead of silently misdecoding;
* the first snapshot in a segment is a **base** record carrying absolute
  values (and histogram bucket *bounds*), so every segment decodes
  independently of its predecessors — retention can drop whole segments
  without orphaning state;
* subsequent snapshots are **delta** records: counter increments,
  changed gauges, and raw non-cumulative histogram bucket-count deltas.
  Histograms are reconstructed through ``Histogram.merge_counts`` — the
  same primitive the multihost aggregator uses — never by pre-summing
  into lossy percentiles.

Crash-safety mirrors the mutable index's WAL tail contract
(serve/artifact.py): a torn final line of the *last* segment is the
expected signature of a crash mid-append and is tolerated and repaired
in place (atomic tmp+rename); a torn or corrupt line anywhere else is
real damage and raises ``DataError``.

The recorder also keeps a bounded in-memory ring of absolute samples —
that ring backs the live ``GET /debug/history`` endpoint and feeds the
alert engine's evaluation cadence (obs/alerts.py) through ``on_sample``.
With ``history_dir=None`` the recorder runs memory-only: alert rules
without durable history construct no files at all.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from knn_tpu import obs
from knn_tpu.obs.metrics import Histogram
from knn_tpu.resilience.errors import DataError

#: Bump on any incompatible change to the segment encoding.
HISTORY_FORMAT = 1

#: Structural schema the hash pins: the set of record/entry fields a
#: reader must understand. Computed over a canonical JSON form so the
#: hash changes exactly when the wire format does.
_SCHEMA = {
    "history": HISTORY_FORMAT,
    "record": ["t", "d", "m"],
    "entry": ["n", "k", "l", "v", "b", "c", "s", "ct"],
    "kinds": ["c", "g", "h"],
}

SCHEMA_HASH = hashlib.sha256(
    json.dumps(_SCHEMA, sort_keys=True).encode("utf-8")
).hexdigest()[:32]

_SEGMENT_RE = re.compile(r"^seg-(\d+)\.jsonl$")

#: Live-ring hard cap — retention/interval bounds it in practice; this
#: protects against pathological flag combos (1h retention @ 1ms).
_RING_MAX = 8192

_KIND_CODE = {"counter": "c", "gauge": "g", "histogram": "h"}


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def parse_window(raw) -> float:
    """``"300"``/``"300s"``/``"5m"``/``"1h"`` -> seconds (float > 0)."""
    if isinstance(raw, (int, float)) and not isinstance(raw, bool):
        secs = float(raw)
    else:
        text = str(raw).strip().lower()
        mult = 1.0
        if text.endswith("h"):
            mult, text = 3600.0, text[:-1]
        elif text.endswith("m"):
            mult, text = 60.0, text[:-1]
        elif text.endswith("s"):
            text = text[:-1]
        try:
            secs = float(text) * mult
        except ValueError:
            raise ValueError(f"bad window {raw!r}: want e.g. 300, 300s, 5m, 1h")
    if not secs > 0:
        raise ValueError(f"bad window {raw!r}: must be > 0 seconds")
    return secs


# ---------------------------------------------------------------------------
# Sample state: the decoded, absolute view of one snapshot instant.
# key -> ("c"|"g", name, labels, value)
#      | ("h", name, labels, bounds, counts, sum, count)


def _state_from_snapshot(records: List[dict]) -> Dict[tuple, tuple]:
    """Absolute state from an ``aggregate.snapshot_registry()`` listing."""
    state: Dict[tuple, tuple] = {}
    for rec in records:
        kind = _KIND_CODE.get(rec.get("kind"))
        if kind is None:
            continue
        labels = dict(rec.get("labels") or {})
        key = (rec["name"], _label_key(labels))
        if kind == "h":
            state[key] = ("h", rec["name"], labels,
                          tuple(float(b) for b in rec["buckets"]),
                          [int(c) for c in rec["counts"]],
                          float(rec["sum"]), int(rec["count"]))
        else:
            state[key] = (kind, rec["name"], labels, float(rec["value"]))
    return state


def _value_of(entry: tuple) -> float:
    """Scalar view of a state entry: counter/gauge value; histogram COUNT
    (alert rules on histograms alert on observation count)."""
    return float(entry[6] if entry[0] == "h" else entry[3])


class HistoryRecorder:
    """Periodic snapshot writer + live ring. All disk I/O happens on the
    sampling thread (or the caller of ``sample_once`` in tests)."""

    def __init__(self, history_dir: Optional[str], *,
                 interval_s: float = 5.0,
                 retention_s: float = 3600.0,
                 source: str = "serve",
                 sample_fn: Callable[[], List[dict]],
                 on_sample: Optional[Callable[[float, "HistoryRecorder"], None]] = None,
                 clock: Callable[[], float] = time.time,
                 autostart: bool = True):
        if not interval_s > 0:
            raise ValueError("history interval must be > 0 seconds")
        if retention_s < interval_s:
            raise ValueError("history retention must be >= the interval")
        self.history_dir = history_dir
        self.interval_s = float(interval_s)
        self.retention_s = float(retention_s)
        self.source = source
        self.sample_fn = sample_fn
        self.on_sample = on_sample
        self.clock = clock
        # Segments rotate on age so retention (which drops whole segments)
        # has sane granularity: ~8 live segments, never shorter than one
        # interval.
        self.rotate_s = max(self.interval_s, self.retention_s / 8.0)

        self._lock = threading.Lock()
        ring_len = min(_RING_MAX, max(8, int(retention_s / interval_s) + 4))
        self._ring: deque = deque(maxlen=ring_len)
        self._file = None
        self._segment = 0
        self._segment_t0: Optional[float] = None
        self._segments_last_ts: Dict[int, float] = {}
        self._prev: Dict[tuple, tuple] = {}
        self._snapshots = 0
        self._pruned = 0

        if history_dir is not None:
            os.makedirs(history_dir, exist_ok=True)
            self._segment = self._boot_scan(history_dir)

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if autostart:
            name = "knn-history" if history_dir is not None else "knn-alerts"
            self._thread = threading.Thread(
                target=self._loop, name=name, daemon=True)
            self._thread.start()

    # -- boot ----------------------------------------------------------------

    def _boot_scan(self, history_dir: str) -> int:
        """Repair a torn tail left by a crashed predecessor and pick the
        next segment number. Pre-existing segments stay on disk (subject
        to retention); this process always opens a fresh segment so its
        header reflects *this* boot's source/interval."""
        numbers = _list_segments(history_dir)
        if not numbers:
            return 0
        last = numbers[-1]
        path = _segment_path(history_dir, last)
        lines, torn = _read_segment_lines(path, tolerate_torn=True)
        if torn:
            _repair_segment(path, lines)
        # Seed retention bookkeeping so old segments prune promptly.
        for n in numbers:
            try:
                recs = _decode_segment(_segment_path(history_dir, n),
                                       tolerate_torn=(n == last))
                if recs:
                    self._segments_last_ts[n] = recs[-1][0]
            except DataError:
                # A damaged *older* segment must not brick the writer —
                # the post-mortem reader is where strictness matters.
                continue
        return last

    # -- sampling ------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                obs.counter_add("knn_history_errors_total",
                                help="History snapshots that raised.")

    def sample_once(self) -> float:
        """Take one snapshot now. Returns the sample timestamp."""
        ts = float(self.clock())
        state = _state_from_snapshot(self.sample_fn())
        with self._lock:
            self._ring.append((ts, state))
            if self.history_dir is not None:
                self._write_sample(ts, state)
            self._snapshots += 1
        obs.counter_add("knn_history_snapshots_total",
                        help="Metrics-history snapshots taken.")
        if self.on_sample is not None:
            try:
                self.on_sample(ts, self)
            except Exception:
                obs.counter_add("knn_history_errors_total",
                                help="History snapshots that raised.")
        return ts

    def _write_sample(self, ts: float, state: Dict[tuple, tuple]) -> None:
        rotate = (self._file is None
                  or (self._segment_t0 is not None
                      and ts - self._segment_t0 >= self.rotate_s))
        if rotate:
            self._open_segment(ts)
            record = _encode_base(ts, state)
        else:
            record = _encode_delta(ts, state, self._prev)
        self._prev = state
        self._segments_last_ts[self._segment] = ts
        if record is not None:
            try:
                self._file.write(
                    json.dumps(record, separators=(",", ":")) + "\n")
                self._file.flush()
            except (OSError, ValueError):
                pass  # a full disk must never take down serving
        self._prune(ts)

    def _open_segment(self, ts: float) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        self._segment += 1
        self._segment_t0 = ts
        path = _segment_path(self.history_dir, self._segment)
        self._file = open(path, "a", buffering=1, encoding="utf-8")
        header = {"history": HISTORY_FORMAT, "segment": self._segment,
                  "schema_hash": SCHEMA_HASH, "source": self.source,
                  "interval_s": self.interval_s, "created_unix": round(ts, 3)}
        self._file.write(json.dumps(header, separators=(",", ":")) + "\n")
        self._file.flush()
        obs.gauge_set("knn_history_segment", self._segment,
                      help="Current history segment number.")

    def _prune(self, now: float) -> None:
        cutoff = now - self.retention_s
        for n in sorted(self._segments_last_ts):
            if n == self._segment:
                continue
            if self._segments_last_ts[n] < cutoff:
                try:
                    os.unlink(_segment_path(self.history_dir, n))
                except OSError:
                    pass
                del self._segments_last_ts[n]
                self._pruned += 1
                obs.counter_add("knn_history_pruned_total",
                                help="History segments dropped by retention.")

    # -- live queries (the /debug/history + alert-engine view) ---------------

    def samples(self) -> List[Tuple[float, Dict[tuple, tuple]]]:
        with self._lock:
            return list(self._ring)

    def latest(self) -> Optional[Tuple[float, Dict[tuple, tuple]]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def query(self, *, metric=None, labels=None, window_s=None) -> dict:
        return query_samples(self.samples(), metric=metric, labels=labels,
                             window_s=window_s)

    def status(self) -> dict:
        with self._lock:
            return {
                "dir": self.history_dir,
                "interval_s": self.interval_s,
                "retention_s": self.retention_s,
                "segment": self._segment,
                "snapshots": self._snapshots,
                "pruned_segments": self._pruned,
                "ring_points": len(self._ring),
            }

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # One final snapshot so the on-disk record extends to shutdown.
        try:
            self.sample_once()
        except Exception:
            pass
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# ---------------------------------------------------------------------------
# Encoding


def _encode_base(ts: float, state: Dict[tuple, tuple]) -> dict:
    entries = []
    for key in sorted(state):
        e = state[key]
        if e[0] == "h":
            entries.append({"n": e[1], "k": "h", "l": e[2],
                            "b": list(e[3]), "c": list(e[4]),
                            "s": e[5], "ct": e[6]})
        else:
            entries.append({"n": e[1], "k": e[0], "l": e[2], "v": e[3]})
    return {"t": round(ts, 3), "d": 0, "m": entries}


def _encode_delta(ts: float, state: Dict[tuple, tuple],
                  prev: Dict[tuple, tuple]) -> Optional[dict]:
    entries = []
    for key in sorted(state):
        e = state[key]
        p = prev.get(key)
        if e[0] == "h":
            if p is None or p[0] != "h" or p[3] != e[3]:
                # New histogram (or rebuilt with different bounds):
                # absolute entry, bounds included.
                entries.append({"n": e[1], "k": "h", "l": e[2],
                                "b": list(e[3]), "c": list(e[4]),
                                "s": e[5], "ct": e[6]})
                continue
            dc = [a - b for a, b in zip(e[4], p[4])]
            dcount = e[6] - p[6]
            if dcount or any(dc):
                entries.append({"n": e[1], "k": "h", "l": e[2], "c": dc,
                                "s": round(e[5] - p[5], 9), "ct": dcount})
        elif e[0] == "c":
            base = p[3] if p is not None and p[0] == "c" else 0.0
            dv = e[3] - base
            if dv:
                entries.append({"n": e[1], "k": "c", "l": e[2], "v": dv})
        else:  # gauge: absolute, only when changed
            if p is None or p[0] != "g" or p[3] != e[3]:
                entries.append({"n": e[1], "k": "g", "l": e[2], "v": e[3]})
    if not entries:
        return {"t": round(ts, 3), "d": 1, "m": []}
    return {"t": round(ts, 3), "d": 1, "m": entries}


# ---------------------------------------------------------------------------
# Reading (post-mortem + CLI)


def _segment_path(history_dir: str, n: int) -> str:
    return os.path.join(history_dir, f"seg-{n}.jsonl")


def _list_segments(history_dir: str) -> List[int]:
    out = []
    try:
        names = os.listdir(history_dir)
    except OSError:
        return out
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _read_segment_lines(path: str, tolerate_torn: bool
                        ) -> Tuple[List[dict], bool]:
    """Parse a segment's JSON lines. A bad FINAL line is the crash
    signature and returns ``(good_lines, True)`` when tolerated; a bad
    line anywhere else — or an intolerable final line — is ``DataError``."""
    with open(path, "r", encoding="utf-8") as f:
        raw = f.read().split("\n")
    if raw and raw[-1] == "":
        raw.pop()
    out: List[dict] = []
    for i, line in enumerate(raw):
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except ValueError:
            if tolerate_torn and i == len(raw) - 1:
                return out, True
            raise DataError(
                f"{path}:{i + 1}: corrupt history record "
                "(only a torn final line of the last segment is repairable)")
        out.append(rec)
    return out, False


def _repair_segment(path: str, lines: List[dict]) -> None:
    """Atomically rewrite a segment minus its torn tail (WAL idiom:
    write tmp, fsync, rename over)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        for rec in lines:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _decode_segment(path: str, tolerate_torn: bool
                    ) -> List[Tuple[float, Dict[tuple, tuple]]]:
    lines, _torn = _read_segment_lines(path, tolerate_torn)
    if not lines:
        return []
    header = lines[0]
    if header.get("history") != HISTORY_FORMAT:
        raise DataError(
            f"{path}: unsupported history format {header.get('history')!r} "
            f"(this build reads format {HISTORY_FORMAT})")
    if header.get("schema_hash") != SCHEMA_HASH:
        raise DataError(
            f"{path}: schema hash {header.get('schema_hash')!r} != "
            f"{SCHEMA_HASH} — segment written by an incompatible build")
    samples: List[Tuple[float, Dict[tuple, tuple]]] = []
    # Reconstruction registry: one Histogram per key, folded through
    # merge_counts exactly like the multihost aggregator.
    hists: Dict[tuple, Histogram] = {}
    state: Dict[tuple, tuple] = {}
    for i, rec in enumerate(lines[1:], start=2):
        try:
            ts = float(rec["t"])
            delta = int(rec.get("d", 0))
            entries = rec["m"]
        except (KeyError, TypeError, ValueError):
            raise DataError(f"{path}:{i}: malformed history record")
        if delta == 0:
            state, hists = {}, {}
        for ent in entries:
            try:
                _apply_entry(ent, delta, state, hists)
            except (KeyError, TypeError, ValueError, IndexError) as exc:
                raise DataError(f"{path}:{i}: bad history entry: {exc}")
        samples.append((ts, _freeze_state(state, hists)))
    return samples


def _apply_entry(ent: dict, delta: int, state: Dict[tuple, tuple],
                 hists: Dict[tuple, Histogram]) -> None:
    kind = ent["k"]
    labels = dict(ent.get("l") or {})
    key = (ent["n"], _label_key(labels))
    if kind == "h":
        if "b" in ent or key not in hists:
            bounds = ent["b"]
            h = Histogram(ent["n"], _label_key(labels), buckets=bounds)
            h.merge_counts(ent["c"], float(ent["s"]), int(ent["ct"]))
            hists[key] = h
            state[key] = ("h", ent["n"], labels)
        else:
            hists[key].merge_counts(ent["c"], float(ent["s"]), int(ent["ct"]))
    elif kind == "c":
        base = 0.0
        if delta and key in state and state[key][0] == "c":
            base = state[key][3]
        state[key] = ("c", ent["n"], labels, base + float(ent["v"]))
    elif kind == "g":
        state[key] = ("g", ent["n"], labels, float(ent["v"]))
    else:
        raise ValueError(f"unknown instrument kind {kind!r}")


def _freeze_state(state: Dict[tuple, tuple],
                  hists: Dict[tuple, Histogram]) -> Dict[tuple, tuple]:
    out: Dict[tuple, tuple] = {}
    for key, e in state.items():
        if e[0] == "h":
            h = hists[key]
            out[key] = ("h", e[1], e[2], h.buckets, h.bucket_counts(),
                        h.sum, h.count)
        else:
            out[key] = e
    return out


class History:
    """Decoded on-disk history: ordered absolute samples across segments."""

    def __init__(self, history_dir: str,
                 samples: List[Tuple[float, Dict[tuple, tuple]]],
                 segments: List[int], repaired: bool):
        self.history_dir = history_dir
        self.samples = samples
        self.segments = segments
        self.repaired = repaired

    def query(self, *, metric=None, labels=None, window_s=None) -> dict:
        return query_samples(self.samples, metric=metric, labels=labels,
                             window_s=window_s)


def load_history(history_dir: str, *, repair: bool = True) -> History:
    """Read every segment under ``history_dir``. The final segment's torn
    tail is tolerated (and repaired in place when ``repair`` and the
    directory is writable); damage anywhere else raises ``DataError``."""
    if not os.path.isdir(history_dir):
        raise DataError(f"{history_dir}: not a history directory")
    numbers = _list_segments(history_dir)
    if not numbers:
        raise DataError(f"{history_dir}: no history segments (seg-*.jsonl)")
    repaired = False
    samples: List[Tuple[float, Dict[tuple, tuple]]] = []
    for n in numbers:
        path = _segment_path(history_dir, n)
        is_last = n == numbers[-1]
        if is_last and repair:
            lines, torn = _read_segment_lines(path, tolerate_torn=True)
            if torn:
                try:
                    _repair_segment(path, lines)
                    repaired = True
                except OSError:
                    pass  # read-only dir: still tolerated, just not repaired
        samples.extend(_decode_segment(path, tolerate_torn=is_last))
    samples.sort(key=lambda s: s[0])
    return History(history_dir, samples, numbers, repaired)


# ---------------------------------------------------------------------------
# Queries (shared by the live ring, the CLI, and the report generator)


def query_samples(samples, *, metric=None, labels=None, window_s=None,
                  t_from=None, t_to=None) -> dict:
    """Series view over absolute samples. ``labels`` is a subset match;
    ``window_s`` is trailing from the newest sample (ignored when an
    explicit ``t_from``/``t_to`` range is given)."""
    if samples:
        hi = t_to if t_to is not None else samples[-1][0]
        if t_from is not None:
            lo = t_from
        elif window_s is not None:
            lo = hi - float(window_s)
        else:
            lo = samples[0][0]
    else:
        lo = hi = 0.0
    want = dict(labels or {})
    series: Dict[tuple, dict] = {}
    for ts, state in samples:
        if ts < lo or ts > hi:
            continue
        for key, e in state.items():
            if metric is not None and e[1] != metric:
                continue
            if want and any(e[2].get(k) != v for k, v in want.items()):
                continue
            s = series.get(key)
            if s is None:
                s = series[key] = {"name": e[1],
                                   "kind": {"c": "counter", "g": "gauge",
                                            "h": "histogram"}[e[0]],
                                   "labels": e[2], "points": []}
            if e[0] == "h":
                s["points"].append([round(ts, 3), e[6], round(e[5], 6)])
                s["buckets"] = list(e[3])
                s["counts"] = list(e[4])
            else:
                s["points"].append([round(ts, 3), e[3]])
    out = [series[k] for k in sorted(series)]
    return {"metric": metric, "labels": want,
            "window": {"from": round(lo, 3), "to": round(hi, 3)},
            "series": out}
