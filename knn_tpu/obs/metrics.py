"""Metrics registry: counters, gauges, fixed-bucket histograms.

Prometheus-shaped without the dependency: metric names follow the
``snake_case`` + ``_total``/unit-suffix conventions, label sets are frozen
per instrument, and exposition comes in two forms —

- :meth:`MetricsRegistry.to_json`      — nested dict for ``--metrics-out``;
- :meth:`MetricsRegistry.to_prometheus` — the text exposition format
  (``# HELP``/``# TYPE`` headers, ``name{label="v"} value`` samples,
  cumulative ``_bucket{le=...}`` histogram series ending at ``+Inf``);
- :meth:`MetricsRegistry.to_openmetrics` — the OpenMetrics 1.0 text
  format, which additionally carries histogram **exemplars**: each bucket
  links the most recent observation that landed in it to its trace
  (``... # {trace_id="..."} value timestamp``), so a p99 spike in
  ``knn_serve_request_ms`` resolves directly to a ``/debug/requests``
  timeline. Exemplar capture is opt-in per observation
  (``observe(v, exemplar={...})``) and costs one tuple store.

Instruments are get-or-create by ``(name, labels)``: calling
``registry.counter("knn_queries_total", backend="tpu")`` twice returns the
same :class:`Counter`, so instrumented call sites never need module-level
instrument caches. All mutation is lock-protected; instruments are cheap
enough that the sharded paths update them per predict call.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

# Default histogram bucket ladder (milliseconds-flavored: spans sub-ms
# dispatches through multi-minute compiles).
DEFAULT_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self._lock = threading.Lock()


class Counter(_Instrument):
    """Monotonically-increasing value. ``add`` rejects negative deltas —
    a decreasing counter is always an instrumentation bug."""

    kind = "counter"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0

    def add(self, delta=1) -> None:
        if delta < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (delta={delta})"
            )
        with self._lock:
            self._value += delta

    inc = add

    @property
    def value(self):
        return self._value


class Gauge(_Instrument):
    """Point-in-time value (set/add both allowed)."""

    kind = "gauge"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def set(self, value) -> None:
        with self._lock:
            self._value = value

    def add(self, delta) -> None:
        with self._lock:
            self._value += delta

    @property
    def value(self):
        return self._value


class Histogram(_Instrument):
    """Fixed-bucket histogram: ``buckets`` are the finite upper bounds (an
    implicit ``+Inf`` bucket catches the overflow). Bucket counts are
    stored non-cumulative internally; exposition emits the Prometheus
    cumulative form."""

    kind = "histogram"

    def __init__(self, name, labels, buckets: Optional[Iterable[float]] = None,
                 help: str = ""):
        super().__init__(name, labels, help)
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bs:
            raise ValueError("histogram needs at least one finite bucket")
        if len(set(bs)) != len(bs):
            raise ValueError(f"duplicate bucket bounds in {bs}")
        if math.isinf(bs[-1]):
            raise ValueError("+Inf bucket is implicit; pass finite bounds")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # last = +Inf overflow
        self._exemplars: List[Optional[tuple]] = [None] * (len(bs) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value, exemplar: Optional[dict] = None) -> None:
        """Record ``value``. ``exemplar`` is an optional label dict (e.g.
        ``{"trace_id": ...}``) stored as the bucket's most recent exemplar
        for OpenMetrics exposition — last write wins per bucket."""
        value = float(value)
        # First bucket whose upper bound admits the value (le semantics).
        lo, hi = 0, len(self.buckets)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.buckets[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self._counts[lo] += 1
            self._sum += value
            self._count += 1
            if exemplar:
                self._exemplars[lo] = (
                    tuple(sorted((k, str(v)) for k, v in exemplar.items())),
                    value, time.time(),
                )

    def merge_counts(self, counts, sum_, count) -> None:
        """Fold another histogram's raw (non-cumulative) bucket counts into
        this one — the multihost aggregation primitive (obs/aggregate.py):
        a process-0 merge registry reconstructs each remote histogram from
        its snapshot instead of replaying observations. ``counts`` must
        match this instrument's bucket count (+1 for +Inf)."""
        counts = list(counts)
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram {self.name}: cannot merge {len(counts)} bucket "
                f"counts into {len(self._counts)} buckets"
            )
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += int(c)
            self._sum += float(sum_)
            self._count += int(count)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def bucket_counts(self) -> List[int]:
        """Non-cumulative per-bucket counts; index ``len(buckets)`` is the
        ``+Inf`` overflow bucket."""
        with self._lock:
            return list(self._counts)

    def exemplars(self) -> List[Optional[tuple]]:
        """Per-bucket ``(labels, value, unix_ts)`` exemplars (None where a
        bucket never captured one); index ``len(buckets)`` is ``+Inf``."""
        with self._lock:
            return list(self._exemplars)

    def cumulative(self) -> List[Tuple[float, int]]:
        """Prometheus-style ``(le, cumulative_count)`` pairs ending at
        ``(inf, count)``."""
        out, run = [], 0
        counts = self.bucket_counts()
        for b, c in zip(self.buckets, counts):
            run += c
            out.append((b, run))
        out.append((math.inf, run + counts[-1]))
        return out


class MetricsRegistry:
    """Get-or-create instrument registry keyed on ``(name, labels)``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                _Instrument] = {}

    def _get(self, cls, name: str, labels: dict, help: str, **kw):
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, key[1], help=help, **kw)
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}"
                )
            elif kw.get("buckets") is not None:
                # A second call site declaring a DIFFERENT ladder must not
                # have its observations silently coarse-bucketed.
                want = tuple(sorted(float(b) for b in kw["buckets"]))
                if want != inst.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with buckets "
                        f"{inst.buckets}, conflicting with {want}"
                    )
        return inst

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, labels, help)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, labels, help)

    def histogram(self, name: str, buckets=None, help: str = "",
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, help, buckets=buckets)

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._instruments.values())

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()

    # -- exposition --------------------------------------------------------

    def to_json(self) -> dict:
        """``{name: [{"labels": {...}, ...value fields...}, ...]}``."""
        out: Dict[str, list] = {}
        for inst in self.instruments():
            rec = {"labels": dict(inst.labels), "kind": inst.kind}
            if isinstance(inst, Histogram):
                rec.update(
                    count=inst.count,
                    sum=inst.sum,
                    buckets=[
                        {"le": le if math.isfinite(le) else "+Inf",
                         "count": c}
                        for le, c in inst.cumulative()
                    ],
                )
            else:
                rec["value"] = inst.value
            out.setdefault(inst.name, []).append(rec)
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        by_name: Dict[str, List[_Instrument]] = {}
        for inst in self.instruments():
            by_name.setdefault(inst.name, []).append(inst)
        lines: List[str] = []
        for name in sorted(by_name):
            group = by_name[name]
            help_text = next((i.help for i in group if i.help), "")
            if help_text:
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {group[0].kind}")
            for inst in group:
                if isinstance(inst, Histogram):
                    for le, c in inst.cumulative():
                        le_s = "+Inf" if math.isinf(le) else _fmt_num(le)
                        lines.append(
                            f"{name}_bucket"
                            f"{_labels(inst.labels + (('le', le_s),))} {c}"
                        )
                    lines.append(
                        f"{name}_sum{_labels(inst.labels)} "
                        f"{_fmt_num(inst.sum)}"
                    )
                    lines.append(
                        f"{name}_count{_labels(inst.labels)} {inst.count}"
                    )
                else:
                    lines.append(
                        f"{name}{_labels(inst.labels)} {_fmt_num(inst.value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def to_openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition (the only text format that
        carries exemplars). Differences from :meth:`to_prometheus`: the
        counter *family* name drops the ``_total`` suffix (samples keep
        it), histogram ``_bucket`` samples may carry a
        ``# {labels} value timestamp`` exemplar, and the document ends
        with ``# EOF``. Serve it under
        ``application/openmetrics-text; version=1.0.0``."""
        by_name: Dict[str, List[_Instrument]] = {}
        for inst in self.instruments():
            by_name.setdefault(inst.name, []).append(inst)
        lines: List[str] = []
        for name in sorted(by_name):
            group = by_name[name]
            kind = group[0].kind
            family = name
            if kind == "counter" and family.endswith("_total"):
                family = family[: -len("_total")]
            om_kind = {"counter": "counter", "gauge": "gauge",
                       "histogram": "histogram"}.get(kind, "unknown")
            lines.append(f"# TYPE {family} {om_kind}")
            help_text = next((i.help for i in group if i.help), "")
            if help_text:
                lines.append(f"# HELP {family} {_escape_help(help_text)}")
            for inst in group:
                if isinstance(inst, Histogram):
                    exemplars = inst.exemplars()
                    run = 0
                    counts = inst.bucket_counts()
                    bounds = list(inst.buckets) + [math.inf]
                    for i, le in enumerate(bounds):
                        run += counts[i]
                        le_s = "+Inf" if math.isinf(le) else _fmt_num(le)
                        line = (f"{family}_bucket"
                                f"{_labels(inst.labels + (('le', le_s),))} "
                                f"{run}")
                        ex = exemplars[i]
                        if ex is not None:
                            ex_labels, ex_value, ex_ts = ex
                            line += (f" # {_labels(ex_labels) or '{}'} "
                                     f"{_fmt_num(ex_value)} {ex_ts:.3f}")
                        lines.append(line)
                    lines.append(f"{family}_sum{_labels(inst.labels)} "
                                 f"{_fmt_num(inst.sum)}")
                    lines.append(f"{family}_count{_labels(inst.labels)} "
                                 f"{inst.count}")
                elif isinstance(inst, Counter):
                    lines.append(f"{family}_total{_labels(inst.labels)} "
                                 f"{_fmt_num(inst.value)}")
                else:
                    lines.append(f"{family}{_labels(inst.labels)} "
                                 f"{_fmt_num(inst.value)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _labels(pairs: Tuple[Tuple[str, str], ...]) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{k}={json.dumps(str(v))}' for k, v in pairs)
    return "{" + body + "}"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_num(v) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)
