"""Declarative alert rules evaluated on the history-snapshot cadence.

``--alert-rules rules.json`` loads a list of rules; each is a small
state machine with hysteresis::

    ok --cond--> pending --held for `for_s`--> FIRING
    firing --!cond--> resolving --held for `resolve_for_s`--> ok

A condition flap while resolving snaps back to firing without emitting a
second fire event — the fire/resolve audit pair is the unit operators
reason about, so it must not chatter.

Rule types:

* ``threshold`` — instantaneous comparison against a metric (counters
  and gauges compare their value; histograms compare their observation
  count). When several series match the metric+label selector the most
  alarming one decides (max for ``>``/``>=``, min for ``<``/``<=``).
* ``burn_rate`` — multi-window SLO burn (obs/slo.py): fires when the
  burn exceeds ``threshold`` in EVERY listed window simultaneously (the
  SRE-workbook multi-window guard against blips).
* ``absence`` — the selector matches nothing: the signal you depend on
  stopped being exported at all.
* ``derivative`` — rate of change per second over a trailing
  ``window_s``, computed from the recorder's ring.

Fires and resolves are **typed events**: a bounded in-memory audit ring,
a line-buffered ``alerts.jsonl`` under the history dir, optional fleet
event-log entries, and ``knn_alerts_*`` instruments. Optional per-rule
``actions`` close the forensics loop with machinery that already exists:

* ``capture``  — arm a workload-capture window (obs/workload.py),
* ``profile``  — grab a blocking device-profile capture (obs/devprof.py),
* ``command``  — run an operator hook, same audited off-thread contract
  as the autoscaler's ``--scale-cmd`` (argv + event + alert name,
  checked exit, hard timeout, output discarded).

Actions run on a short-lived daemon thread so evaluation (and the
history sampling thread driving it) never blocks on a capture, a
profile sleep, or a slow subprocess. Every action outcome is audited,
including raises — a broken action must never take down serving.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from knn_tpu import obs
from knn_tpu.obs import history as history_mod
from knn_tpu.resilience.errors import DataError

RULE_TYPES = ("threshold", "burn_rate", "absence", "derivative")
ACTION_KINDS = ("capture", "profile", "command")
_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

_AUDIT_RING = 256


def _req(rule: dict, field: str, where: str):
    if field not in rule:
        raise DataError(f"alert rule {where}: missing required field {field!r}")
    return rule[field]


def _num(value, field: str, where: str, *, positive=False) -> float:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise DataError(f"alert rule {where}: {field} must be a number")
    v = float(value)
    if positive and not v > 0:
        raise DataError(f"alert rule {where}: {field} must be > 0")
    return v


def parse_rules(doc) -> List[dict]:
    """Validate and normalize a rules document (either ``[rule, ...]`` or
    ``{"rules": [rule, ...]}``). Raises typed ``DataError`` on any shape
    problem so the CLI can map it to exit 2 before anything boots."""
    if isinstance(doc, dict):
        doc = doc.get("rules")
    if not isinstance(doc, list) or not doc:
        raise DataError("alert rules: want a non-empty list of rule objects")
    out: List[dict] = []
    seen = set()
    for i, raw in enumerate(doc):
        where = f"#{i}"
        if not isinstance(raw, dict):
            raise DataError(f"alert rule {where}: not an object")
        name = _req(raw, "name", where)
        if not isinstance(name, str) or not name.strip():
            raise DataError(f"alert rule {where}: name must be a non-empty string")
        name = name.strip()
        where = name
        if name in seen:
            raise DataError(f"alert rule {name!r}: duplicate name")
        seen.add(name)
        rtype = _req(raw, "type", where)
        if rtype not in RULE_TYPES:
            raise DataError(
                f"alert rule {name!r}: unknown type {rtype!r} "
                f"(want one of {', '.join(RULE_TYPES)})")
        rule = {"name": name, "type": rtype,
                "severity": str(raw.get("severity", "page")),
                "for_s": 0.0, "resolve_for_s": 0.0}
        if "for_s" in raw:
            rule["for_s"] = _num(raw["for_s"], "for_s", where)
            if rule["for_s"] < 0:
                raise DataError(f"alert rule {name!r}: for_s must be >= 0")
        rule["resolve_for_s"] = rule["for_s"]
        if "resolve_for_s" in raw:
            rule["resolve_for_s"] = _num(raw["resolve_for_s"],
                                         "resolve_for_s", where)
            if rule["resolve_for_s"] < 0:
                raise DataError(
                    f"alert rule {name!r}: resolve_for_s must be >= 0")
        labels = raw.get("labels", {})
        if not isinstance(labels, dict):
            raise DataError(f"alert rule {name!r}: labels must be an object")
        rule["labels"] = {str(k): str(v) for k, v in labels.items()}

        if rtype == "threshold" or rtype == "derivative":
            metric = _req(raw, "metric", where)
            if not isinstance(metric, str) or not metric:
                raise DataError(f"alert rule {name!r}: metric must be a string")
            rule["metric"] = metric
            op = raw.get("op", ">")
            if op not in _OPS:
                raise DataError(
                    f"alert rule {name!r}: op {op!r} not in {sorted(_OPS)}")
            rule["op"] = op
            rule["value"] = _num(_req(raw, "value", where), "value", where)
            if rtype == "derivative":
                rule["window_s"] = _num(_req(raw, "window_s", where),
                                        "window_s", where, positive=True)
        elif rtype == "burn_rate":
            rule["objective"] = str(raw.get("objective", "availability"))
            rule["threshold"] = _num(_req(raw, "threshold", where),
                                     "threshold", where, positive=True)
            windows = raw.get("windows")
            if windows is not None:
                if (not isinstance(windows, list) or not windows
                        or not all(isinstance(w, str) for w in windows)):
                    raise DataError(
                        f"alert rule {name!r}: windows must be a non-empty "
                        "list of window labels (e.g. [\"5m\", \"1h\"])")
            rule["windows"] = windows
        elif rtype == "absence":
            metric = _req(raw, "metric", where)
            if not isinstance(metric, str) or not metric:
                raise DataError(f"alert rule {name!r}: metric must be a string")
            rule["metric"] = metric

        actions_raw = raw.get("actions", [])
        if not isinstance(actions_raw, list):
            raise DataError(f"alert rule {name!r}: actions must be a list")
        actions = []
        for j, act in enumerate(actions_raw):
            if not isinstance(act, dict):
                raise DataError(f"alert rule {name!r}: action #{j} not an object")
            do = act.get("do")
            if do not in ACTION_KINDS:
                raise DataError(
                    f"alert rule {name!r}: action #{j} do={do!r} "
                    f"(want one of {', '.join(ACTION_KINDS)})")
            norm = {"do": do}
            if do == "capture":
                if "window_s" in act:
                    norm["window_s"] = _num(act["window_s"], "window_s",
                                            where, positive=True)
                if "max_requests" in act:
                    mr = act["max_requests"]
                    if isinstance(mr, bool) or not isinstance(mr, int) or mr <= 0:
                        raise DataError(
                            f"alert rule {name!r}: max_requests must be "
                            "a positive integer")
                    norm["max_requests"] = mr
                if "window_s" not in norm and "max_requests" not in norm:
                    norm["window_s"] = 10.0
            elif do == "profile":
                norm["ms"] = _num(act.get("ms", 200), "ms", where, positive=True)
            elif do == "command":
                cmd = act.get("cmd")
                if not isinstance(cmd, str) or not cmd.strip():
                    raise DataError(
                        f"alert rule {name!r}: command action needs a "
                        "non-empty cmd string")
                norm["cmd"] = cmd.strip()
            actions.append(norm)
        rule["actions"] = actions
        out.append(rule)
    return out


def load_rules(path: str) -> List[dict]:
    """Read + parse a rules file; all failures are ``DataError`` (exit 2)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as exc:
        raise DataError(f"--alert-rules {path}: {exc}")
    except ValueError as exc:
        raise DataError(f"--alert-rules {path}: not valid JSON: {exc}")
    return parse_rules(doc)


class AlertEngine:
    """Evaluates rules against recorder samples; owns the audit trail."""

    def __init__(self, rules: List[dict], *,
                 slo=None, workload=None, recorder=None, events=None,
                 history_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 command_timeout_s: float = 10.0):
        # Environment validation up front: a rule that can never run its
        # action (or never evaluate) is a boot-time config error, not a
        # 3am surprise.
        for rule in rules:
            if rule["type"] == "burn_rate" and slo is None:
                raise DataError(
                    f"alert rule {rule['name']!r}: burn_rate rules need the "
                    "SLO tracker (serve only; routers have no request SLOs)")
            for act in rule["actions"]:
                if act["do"] == "capture" and workload is None:
                    raise DataError(
                        f"alert rule {rule['name']!r}: capture action "
                        "requires --capture-dir")
                if act["do"] == "profile" and history_dir is None:
                    raise DataError(
                        f"alert rule {rule['name']!r}: profile action "
                        "requires --history-dir (profiles land there)")
        self.rules = rules
        self.slo = slo
        self.workload = workload
        self.recorder = recorder
        self.events = events
        self.history_dir = history_dir
        self.clock = clock
        self.command_timeout_s = float(command_timeout_s)

        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=_AUDIT_RING)
        self._threads: List[threading.Thread] = []
        self._state: Dict[str, dict] = {
            r["name"]: {"phase": "ok", "since": None, "value": None,
                        "last_fire": None, "last_resolve": None, "fires": 0}
            for r in rules}
        self.audit_path = None
        self._audit_file = None
        if history_dir is not None:
            os.makedirs(history_dir, exist_ok=True)
            self.audit_path = os.path.join(history_dir, "alerts.jsonl")
            self._audit_file = open(self.audit_path, "a", buffering=1,
                                    encoding="utf-8")
        for rule in rules:
            obs.gauge_set("knn_alerts_firing", 0, alert=rule["name"],
                          help="1 while the named alert is firing.")

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, ts: float, view) -> None:
        """One evaluation pass at time ``ts`` against ``view`` (a
        HistoryRecorder — or anything with ``latest()``/``samples()``).
        Called from the recorder's ``on_sample`` hook; an injectable
        clock plus a manual ``sample_once`` makes this fully
        deterministic in tests."""
        latest = view.latest()
        state = latest[1] if latest is not None else {}
        for rule in self.rules:
            try:
                cond, value = self._eval_rule(rule, ts, state, view)
            except Exception as exc:
                self._audit({"ts": round(ts, 3), "alert": rule["name"],
                             "event": "eval-error", "error": repr(exc)})
                continue
            self._transition(rule, ts, cond, value)

    def _eval_rule(self, rule, ts, state, view):
        rtype = rule["type"]
        if rtype == "burn_rate":
            burns = self.slo.burn_rates()
            per_window = burns.get(rule["objective"], {})
            windows = rule["windows"] or sorted(per_window)
            if not windows:
                return False, None
            vals = [per_window.get(w) for w in windows]
            if any(v is None for v in vals):
                raise ValueError(
                    f"objective {rule['objective']!r} has no window(s) "
                    f"{[w for w, v in zip(windows, vals) if v is None]}")
            # Multi-window AND: every window must burn past the threshold.
            return min(vals) > rule["threshold"], max(vals)
        matches = [e for e in state.values()
                   if e[1] == rule["metric"]
                   and all(e[2].get(k) == v for k, v in rule["labels"].items())]
        if rtype == "absence":
            return not matches, float(len(matches))
        if not matches:
            return False, None  # no data: threshold/derivative rules stay ok
        values = [history_mod._value_of(e) for e in matches]
        agg = max(values) if rule["op"] in (">", ">=") else min(values)
        if rtype == "threshold":
            return _OPS[rule["op"]](agg, rule["value"]), agg
        # derivative: rate vs the newest sample at least window_s old.
        past = None
        for s_ts, s_state in reversed(view.samples()):
            if s_ts <= ts - rule["window_s"]:
                past = (s_ts, s_state)
                break
        if past is None:
            return False, None  # not enough history yet
        old = [history_mod._value_of(e) for e in past[1].values()
               if e[1] == rule["metric"]
               and all(e[2].get(k) == v for k, v in rule["labels"].items())]
        if not old:
            return False, None
        old_agg = max(old) if rule["op"] in (">", ">=") else min(old)
        rate = (agg - old_agg) / max(ts - past[0], 1e-9)
        return _OPS[rule["op"]](rate, rule["value"]), rate

    def _transition(self, rule, ts, cond, value) -> None:
        st = self._state[rule["name"]]
        st["value"] = value
        phase = st["phase"]
        if phase in ("ok", "pending"):
            if cond:
                if phase == "ok":
                    st["phase"], st["since"] = "pending", ts
                if ts - st["since"] >= rule["for_s"]:
                    self._fire(rule, ts, value)
            else:
                st["phase"], st["since"] = "ok", None
        else:  # firing | resolving
            if cond:
                # Flap while resolving: back to firing, NO second event.
                st["phase"], st["since"] = "firing", None
            else:
                if phase == "firing":
                    st["phase"], st["since"] = "resolving", ts
                if ts - st["since"] >= rule["resolve_for_s"]:
                    self._resolve(rule, ts, value)

    def _fire(self, rule, ts, value) -> None:
        st = self._state[rule["name"]]
        st.update(phase="firing", since=None, last_fire=ts)
        st["fires"] += 1
        self._emit(rule, "fire", ts, value)
        obs.gauge_set("knn_alerts_firing", 1, alert=rule["name"],
                      help="1 while the named alert is firing.")
        self._dispatch(rule, "fire", ts)

    def _resolve(self, rule, ts, value) -> None:
        st = self._state[rule["name"]]
        st.update(phase="ok", since=None, last_resolve=ts)
        self._emit(rule, "resolve", ts, value)
        obs.gauge_set("knn_alerts_firing", 0, alert=rule["name"],
                      help="1 while the named alert is firing.")
        self._dispatch(rule, "resolve", ts)

    def _emit(self, rule, event, ts, value) -> None:
        obs.counter_add("knn_alerts_transitions_total", alert=rule["name"],
                        event=event, help="Alert fire/resolve transitions.")
        entry = {"ts": round(ts, 3), "alert": rule["name"], "event": event,
                 "severity": rule["severity"], "type": rule["type"],
                 "value": None if value is None else round(float(value), 6)}
        if event == "fire" and rule["actions"]:
            entry["actions"] = [a["do"] for a in rule["actions"]]
        self._audit(entry)
        if self.events is not None:
            try:
                self.events.emit(f"alert-{event}", alert=rule["name"],
                                 severity=rule["severity"],
                                 value=entry["value"])
            except Exception:
                pass

    # -- actions -------------------------------------------------------------

    def _dispatch(self, rule, event, ts) -> None:
        todo = [a for a in rule["actions"]
                if event == "fire" or a["do"] == "command"]
        dump_forensics = (event == "fire" and self.recorder is not None
                          and self.history_dir is not None)
        if not todo and not dump_forensics:
            return
        t = threading.Thread(
            target=self._run_actions, args=(rule, event, ts, todo,
                                            dump_forensics),
            name=f"knn-alert-action-{rule['name']}", daemon=True)
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()

    def _run_actions(self, rule, event, ts, todo, dump_forensics) -> None:
        if dump_forensics:
            try:
                self._dump_forensics(rule, ts)
            except Exception as exc:
                self._audit_action(rule, event, ts, "forensics",
                                   f"error: {exc!r}")
        for act in todo:
            try:
                detail = self._run_action(rule, event, ts, act)
                outcome = "ok"
            except Exception as exc:
                detail, outcome = f"{exc!r}", "error"
            obs.counter_add("knn_alerts_actions_total", action=act["do"],
                            outcome=outcome,
                            help="Alert action dispatches by outcome.")
            self._audit_action(rule, event, ts, act["do"],
                               outcome if outcome == "ok" else
                               f"{outcome}: {detail}", detail=detail)

    def _run_action(self, rule, event, ts, act) -> str:
        if act["do"] == "capture":
            self.workload.start(reason=f"alert:{rule['name']}",
                                window_s=act.get("window_s"),
                                max_requests=act.get("max_requests"))
            return "armed"
        if act["do"] == "profile":
            from knn_tpu.obs import devprof
            trace = devprof.capture_for(act["ms"])
            pdir = os.path.join(self.history_dir, "profiles")
            os.makedirs(pdir, exist_ok=True)
            path = os.path.join(
                pdir, f"profile-{rule['name']}-{int(ts * 1000)}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(trace, f)
            return path
        # command: same audited contract as the autoscaler's --scale-cmd —
        # argv-split hook + event + alert name, checked exit, hard
        # timeout, output discarded (the hook owns its own logging).
        argv = [*act["cmd"].split(), event, rule["name"]]
        subprocess.run(argv, check=True, timeout=self.command_timeout_s,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return " ".join(argv)

    def _dump_forensics(self, rule, ts) -> None:
        """Freeze the flight recorder's slowest-K at fire time — by the
        time a human looks, the reservoir has moved on."""
        fdir = os.path.join(self.history_dir, "forensics")
        os.makedirs(fdir, exist_ok=True)
        path = os.path.join(
            fdir, f"slowest-{rule['name']}-{int(ts * 1000)}.json")
        doc = {"alert": rule["name"], "ts": round(ts, 3),
               "slowest": self.recorder.slowest()}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        self._audit_action(rule, "fire", ts, "forensics", "ok", detail=path)

    def _audit_action(self, rule, event, ts, action, outcome,
                      detail=None) -> None:
        entry = {"ts": round(ts, 3), "alert": rule["name"], "event": "action",
                 "on": event, "action": action, "outcome": outcome}
        if detail is not None:
            entry["detail"] = str(detail)[:500]
        self._audit(entry)

    def _audit(self, entry: dict) -> None:
        with self._lock:
            self._ring.append(entry)
            if self._audit_file is not None:
                try:
                    self._audit_file.write(
                        json.dumps(entry, separators=(",", ":")) + "\n")
                except (OSError, ValueError):
                    pass

    # -- introspection -------------------------------------------------------

    def export(self) -> dict:
        with self._lock:
            recent = list(self._ring)[-50:]
        rules = []
        for rule in self.rules:
            st = self._state[rule["name"]]
            rules.append({
                "name": rule["name"], "type": rule["type"],
                "severity": rule["severity"], "state": st["phase"],
                "for_s": rule["for_s"], "resolve_for_s": rule["resolve_for_s"],
                "value": st["value"], "fires": st["fires"],
                "last_fire": st["last_fire"],
                "last_resolve": st["last_resolve"],
                "actions": [a["do"] for a in rule["actions"]],
            })
        return {"rules": rules,
                "firing": [r["name"] for r in rules
                           if r["state"] in ("firing", "resolving")],
                "recent": recent, "audit_path": self.audit_path}

    def drain_actions(self, timeout_s: float = 5.0) -> None:
        """Join outstanding action threads (tests + orderly shutdown)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def close(self) -> None:
        self.drain_actions()
        with self._lock:
            if self._audit_file is not None:
                try:
                    self._audit_file.close()
                except OSError:
                    pass
                self._audit_file = None
