"""Saturation & headroom: how far is this replica from its load knee?

The cost layer (:mod:`knn_tpu.obs.accounting`) says what each request
paid; this module says what the replica has LEFT. It watches four things
the batcher already knows —

- **arrival / served rate rings** (requests and rows per second, reusing
  :class:`knn_tpu.obs.slo.SecondRing` — the SLO tracker's per-second
  machinery) over a trailing observation window;
- **worker duty cycle** — the fraction of wall the single dispatch worker
  spent inside a dispatch vs idle in the coalescing window: the most
  direct "how busy is this replica" scalar (1.0 = the worker never waits,
  the queue is the buffer);
- **batch occupancy** — ``rows / compiled-shape rows`` per dispatch
  (``knn_capacity_batch_occupancy`` histogram): how full the dispatched
  bucket runs — under a ``--batch-buckets`` ladder the denominator is
  the bucket the batch padded to, so the signal prices the shapes the
  device really swept, per-batch at the live (possibly OOM-halved)
  policy snapshot;
- **an affine dispatch-cost model** ``w(r) ≈ a + b·r`` (ms per dispatch of
  ``r`` rows) fitted by least squares over the window's observed
  ``(rows, wall)`` pairs, seeded at warmup with two post-compile timed
  dispatches (1 row and ``max_batch`` rows) so the model exists before
  traffic does.

From those, the **headroom model** (docs/OBSERVABILITY.md §Cost &
capacity): a saturated worker dispatches full batches back to back, so the
sustainable row rate is ``max_batch / w(max_batch)`` and the sustainable
request rate divides by the observed rows-per-request mix. Headroom is
that sustainable QPS over the current arrival QPS; a Little's-law estimate
(``L = λ·W``: served rate × mean request wall — admitted load, since a
rejected request never enters the system) reports the concurrency the
replica is carrying. ``scripts/capacity_probe.py`` (`make
capacity-probe`) ramps a live server to its measured knee and cross-checks
this model against reality — the tolerance band is documented there.

All of it exports as ``knn_capacity_*`` gauges refreshed at scrape
(:meth:`CapacityTracker.export`), joined with the per-class cost totals in
``GET /debug/capacity`` and summarized in the ``/healthz`` capacity block.
Absent unless ``--cost-accounting`` is on: one ``is None`` predicate per
call site, zero instruments while off.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from knn_tpu import obs
from knn_tpu.obs.slo import SecondRing

#: Default trailing observation window (seconds) for rates/duty/occupancy.
DEFAULT_WINDOW_S = 60

#: Batch-occupancy histogram ladder (rows / max_batch per dispatch).
OCCUPANCY_BUCKETS = (0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875,
                     1.0)


class CapacityTracker:
    """Arrival/served/dispatch telemetry + the headroom model.

    ``note_arrival`` runs on submitting threads, ``note_dispatch`` /
    ``note_served`` on the batcher worker, ``seed_dispatch_model`` on the
    warmup path, ``export`` on scrape threads — ring mutation is O(1)
    under the rings' own locks; the seed list has its own.
    """

    def __init__(self, max_batch: int, window_s: int = DEFAULT_WINDOW_S):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if window_s < 1:
            raise ValueError(f"window_s must be >= 1, got {window_s}")
        self.max_batch = int(max_batch)
        self.window_s = int(window_s)
        # Fields: [requests, rows]
        self._arrivals = SecondRing(2, self.window_s)
        # Fields: [requests, rows, request_ms_sum]
        self._served = SecondRing(3, self.window_s)
        # Fields: [dispatches, busy_ms, rows, padded_rows, fit_rows^2,
        # fit_rows*ms, occupancy_sum, fit_n, fit_busy_ms, fit_rows] —
        # the fit_* fields are the sufficient statistics for the affine
        # dispatch-cost fit, restricted to SINGLE-chunk dispatches (a
        # post-OOM chunked re-dispatch pays the intercept once per chunk,
        # which would bias the model — see note_dispatch); occupancy is
        # summed at each dispatch's OWN max_batch (OOM halving changes it
        # mid-window, so a scrape-time rescale would misread old
        # dispatches).
        self._dispatches = SecondRing(10, self.window_s)
        self._lock = threading.Lock()
        self._seeds: list = []  # [(rows, wall_ms)] from warmup
        self._started = time.monotonic()

    # -- recording (O(1)) --------------------------------------------------

    def note_arrival(self, rows: int) -> None:
        """One OFFERED request of ``rows`` query rows — admitted or
        rejected. Offered (not admitted) load is what the headroom ratio
        must divide by: admitted load saturates at the service rate under
        overload, which would pin the ratio near 1 exactly when it should
        be reading well below it."""
        self._arrivals.add(1, int(rows))

    def note_served(self, rows: int, request_ms: float) -> None:
        """One successfully answered request and its enqueue->answer wall
        (the Little's-law ``W``)."""
        self._served.add(1, int(rows), float(request_ms))

    def note_dispatch(self, wall_ms: float, rows: int, padded_rows: int,
                      max_batch: int, compiled: bool = True) -> None:
        """One completed worker dispatch: its wall (the duty-cycle busy
        time), actual and compiled-shape rows, and the ``max_batch`` in
        force — the LIVE per-batch snapshot (OOM recovery shrinks it
        mid-run; occupancy must track the policy each batch really
        dispatched under, never the boot value, so the metric can
        neither read > 1 nor understate after a halving).
        ``compiled=False`` marks a HOST-rung dispatch (ivf/oracle): no
        compiled shape exists there, so occupancy keeps the
        coalescing-efficiency meaning ``rows / max_batch`` instead of
        reading a vacuous 1.0 from ``padded == rows``."""
        self.max_batch = max(1, int(max_batch))
        rows = int(rows)
        padded_rows = int(padded_rows)
        # When an OOM halves max_batch MID-batch, the re-dispatch arrives
        # here as one (rows > new max_batch) record covering several
        # chunked device calls. Each chunk ran full, so the honest
        # occupancy is 1.0 (not rows/new_cap > 1) — and the point is
        # excluded from the dispatch-cost fit: its wall paid the model's
        # intercept once PER CHUNK, which w(r) = a + b·r cannot express.
        chunked = rows > self.max_batch
        # Occupancy = how full the COMPILED batch shape ran (the
        # docs/OBSERVABILITY.md definition): rows over the dispatched
        # bucket's compiled-shape rows. Under a bucket ladder the
        # denominator is the bucket the batch actually padded to; under
        # the legacy single quantum it is the padded quantum shape —
        # either way the shape the device swept, clamped so a
        # denominator surprise can never read past 1.0.
        if compiled and padded_rows >= rows > 0:
            denom = padded_rows
        else:
            denom = self.max_batch
        occ = min(1.0, rows / max(1, denom))
        if chunked:
            occ = 1.0
        self._dispatches.add(1, float(wall_ms), rows, padded_rows,
                             0 if chunked else rows * rows,
                             0.0 if chunked else rows * float(wall_ms),
                             occ,
                             0 if chunked else 1,
                             0.0 if chunked else float(wall_ms),
                             0 if chunked else rows)
        obs.histogram_observe(
            "knn_capacity_batch_occupancy", occ,
            buckets=OCCUPANCY_BUCKETS,
            help="rows / compiled-shape rows per dispatched micro-batch "
                 "(how full the dispatched bucket runs)",
        )

    def seed_dispatch_model(self, rows: int, wall_ms: float) -> None:
        """A post-compile timed dispatch from the warmup path: two seeds at
        different row counts give the affine model a two-point fit before
        any traffic arrives (`ServeApp.warm`). Re-seeded on hot reload —
        a new index has a new cost curve."""
        with self._lock:
            self._seeds.append((int(rows), float(wall_ms)))
            if len(self._seeds) > 16:
                self._seeds = self._seeds[-16:]

    def reset_seeds(self) -> None:
        with self._lock:
            self._seeds = []

    # -- the dispatch-cost model -------------------------------------------

    def _fit(self, disp) -> Tuple[Optional[float], Optional[float], str]:
        """``(a_ms, b_ms_per_row, source)`` for ``w(r) = a + b·r``.

        Preference order: least squares over the window's observed
        SINGLE-chunk dispatches (the fit_* ring fields — chunked post-OOM
        re-dispatches pay the intercept per chunk and are excluded) when
        the row counts actually vary (otherwise the system is singular),
        else the warmup seeds' two-point fit, else the observed mean wall
        over ALL dispatches as a flat model. Negative intercepts/slopes
        from noise are clamped to 0 — a dispatch cannot get cheaper with
        more rows."""
        rows_sq, rxw = disp[4], disp[5]
        n, busy, rows = disp[7], disp[8], disp[9]
        if n >= 4:
            var = n * rows_sq - rows * rows
            if var > n:  # row spread beyond degenerate single-size traffic
                b = (n * rxw - rows * busy) / var
                a = (busy - b * rows) / n
                return max(0.0, a), max(0.0, b), "observed"
        with self._lock:
            seeds = list(self._seeds)
        by_rows: dict = {}
        for r, w in seeds:  # best-of per row count: noise only adds
            by_rows[r] = min(w, by_rows.get(r, w))
        if len(by_rows) >= 2:
            pts = sorted(by_rows.items())
            (r1, w1), (r2, w2) = pts[0], pts[-1]
            b = (w2 - w1) / (r2 - r1)
            a = w1 - b * r1
            return max(0.0, a), max(0.0, b), "seed"
        if disp[0] > 0:  # flat fallback: mean wall over ALL dispatches
            return disp[1] / disp[0], 0.0, "mean"
        if by_rows:
            (r1, w1), = list(by_rows.items())[:1]
            return w1, 0.0, "seed"
        return None, None, "none"

    # -- reporting (scrape-time) -------------------------------------------

    def export(self) -> dict:
        """Compute the capacity summary over the trailing window, refresh
        the ``knn_capacity_*`` gauges, and return the dict that
        ``/debug/capacity`` and the ``/healthz`` capacity block embed."""
        w = self.window_s
        now = time.monotonic()
        wall_s = max(1e-9, min(float(w), now - self._started))
        arr_reqs, arr_rows = self._arrivals.window_sums(w)
        srv_reqs, srv_rows, srv_ms = self._served.window_sums(w)
        disp = self._dispatches.window_sums(w)
        n_disp, busy_ms, d_rows, d_pad = disp[0], disp[1], disp[2], disp[3]

        duty = min(1.0, (busy_ms / 1e3) / wall_s)
        arrival_qps = arr_reqs / wall_s
        arrival_rows_per_s = arr_rows / wall_s
        served_qps = srv_reqs / wall_s
        served_rows_per_s = srv_rows / wall_s
        occupancy_mean = disp[6] / n_disp if n_disp else 0.0
        dispatch_rows_per_s = (d_rows / (busy_ms / 1e3)
                               if busy_ms > 0 else 0.0)
        mean_request_ms = srv_ms / srv_reqs if srv_reqs else None
        # Little's-law lambda is the ADMITTED rate: a rejected request
        # never enters the system and carries no in-flight time, so under
        # shed the offered rate would inflate the estimate exactly when an
        # operator reads it (the arrival rings still feed headroom, where
        # offered load IS the right denominator).
        concurrency = (served_qps * (mean_request_ms / 1e3)
                       if mean_request_ms is not None else 0.0)
        rows_per_request = (srv_rows / srv_reqs if srv_reqs
                            else (arr_rows / arr_reqs if arr_reqs else 1.0))
        rows_per_request = max(1.0, rows_per_request)
        waste = (d_pad - d_rows) / d_pad if d_pad > 0 else 0.0

        a, b, model_source = self._fit(disp)
        sustainable_qps = sustainable_rows_per_s = None
        if a is not None:
            full_wall_ms = a + b * self.max_batch
            if full_wall_ms > 0:
                sustainable_rows_per_s = (
                    self.max_batch / (full_wall_ms / 1e3))
                sustainable_qps = sustainable_rows_per_s / rows_per_request
        headroom = (sustainable_qps / arrival_qps
                    if sustainable_qps is not None and arrival_qps > 0
                    else None)
        utilization = (arrival_rows_per_s / sustainable_rows_per_s
                       if sustainable_rows_per_s else None)

        out = {
            "window_s": w,
            "max_batch": self.max_batch,
            "duty_cycle": round(duty, 4),
            "arrival_qps": round(arrival_qps, 3),
            "arrival_rows_per_s": round(arrival_rows_per_s, 3),
            "served_qps": round(served_qps, 3),
            "served_rows_per_s": round(served_rows_per_s, 3),
            "occupancy_mean": round(occupancy_mean, 4),
            "padded_row_waste_ratio": round(waste, 4),
            "dispatch_rows_per_s": round(dispatch_rows_per_s, 1),
            "mean_request_ms": (round(mean_request_ms, 3)
                                if mean_request_ms is not None else None),
            "littles_law_concurrency": round(concurrency, 3),
            "rows_per_request": round(rows_per_request, 2),
            "dispatch_model": {
                "a_ms": round(a, 4) if a is not None else None,
                "b_ms_per_row": round(b, 6) if b is not None else None,
                "source": model_source,
            },
            "sustainable_qps": (round(sustainable_qps, 2)
                                if sustainable_qps is not None else None),
            "sustainable_rows_per_s": (
                round(sustainable_rows_per_s, 1)
                if sustainable_rows_per_s is not None else None),
            "headroom_ratio": (round(headroom, 3)
                               if headroom is not None else None),
            "utilization": (round(utilization, 4)
                            if utilization is not None else None),
        }
        for name, value, help_text in (
            ("knn_capacity_duty_cycle", duty,
             "fraction of wall the batcher worker spent in dispatch over "
             "the observation window (1.0 = saturated)"),
            ("knn_capacity_arrival_qps", arrival_qps,
             "offered requests/s (admitted + rejected) over the "
             "observation window"),
            ("knn_capacity_arrival_rows_per_s", arrival_rows_per_s,
             "offered query rows/s (admitted + rejected) over the "
             "observation window"),
            ("knn_capacity_served_qps", served_qps,
             "answered requests/s over the observation window"),
            ("knn_capacity_served_rows_per_s", served_rows_per_s,
             "answered query rows/s over the observation window"),
            ("knn_capacity_occupancy_mean", occupancy_mean,
             "mean rows / compiled-shape rows per dispatch over the "
             "window"),
            ("knn_capacity_padded_row_waste_ratio", waste,
             "fraction of compiled-shape rows that were padding over the "
             "window"),
            ("knn_capacity_dispatch_rows_per_s", dispatch_rows_per_s,
             "rows retrieved per second of dispatch busy time (the service "
             "rate under load)"),
            ("knn_capacity_concurrency", concurrency,
             "Little's-law in-flight estimate: served rate x mean "
             "request wall"),
        ):
            obs.gauge_set(name, round(value, 4), help=help_text)
        if sustainable_qps is not None:
            # Both gauges exist iff the dispatch model does, and both
            # refresh at every scrape while it does: a gauge left at its
            # last loaded value after traffic moves away would keep a
            # near-knee alert firing on an idle replica (the PR 7
            # stale-gauge rule). No arrivals = effectively unbounded
            # headroom, exported as the documented 1e6 cap.
            obs.gauge_set(
                "knn_capacity_sustainable_qps", round(sustainable_qps, 2),
                help="modeled saturated request rate: max_batch/w(max_batch) "
                     "dispatches at the fitted affine dispatch cost, over "
                     "the observed rows-per-request mix",
            )
            obs.gauge_set(
                "knn_capacity_headroom_ratio",
                round(min(headroom if headroom is not None else 1e6,
                          1e6), 3),
                help="sustainable QPS / offered arrival QPS (<1 = past "
                     "the modeled knee; capped at 1e6 = no recent "
                     "arrivals)",
            )
        return out
