"""Per-request device-cost attribution — the serving economics layer.

The serving histograms (``knn_serve_dispatch_ms`` et al.) answer "what did
a batch cost"; they cannot answer "which request paid for it". Because the
micro-batcher coalesces many requests into ONE device dispatch, per-request
cost is an *attribution*, not a measurement: this module splits each
dispatch's measured wall (and transferred bytes) across the batch's
requests **proportional to query rows**, tagged with a **request class**
(``x-knn-class`` header / ``submit(request_class=...)``; default
``interactive``), so ``/metrics`` can answer "how much device time did bulk
traffic burn vs interactive" and ``/debug/requests?id=...`` can answer
"what did THIS request cost".

Attribution contract (docs/OBSERVABILITY.md §Cost & capacity, pinned by
tests/test_accounting.py):

- **conservation** — the per-request shares of one dispatch sum to the
  measured dispatch wall: shares are computed proportional-to-rows with
  the float residual folded into the last request, so the running totals
  ``knn_cost_device_ms_total`` (summed over every ``{class, rung}``) and
  ``knn_cost_dispatch_wall_ms_total`` agree to float precision — device
  time can neither be created nor destroyed by attribution;
- **per-attempt, not per-batch** — every degradation-ladder rung attempt
  is attributed separately under its own ``rung`` label (a failed fast
  dispatch is real device time the surviving requests paid for); a request
  whose deadline expires mid-fallback is attributed ONLY the attempts it
  rode — never the rung that answered after it was already failed;
- **padding is waste, measured** — ``knn_cost_padded_rows_total`` counts
  the rows the compiled shape forced beyond the batch's actual rows
  (XLA pads queries to the dispatched bucket — the installed
  ``--batch-buckets`` ladder, or the 128-row quantum without one — and
  the stripe kernel to its block grid): the price of the compiled batch
  shapes, and the number shape-bucketed batching shrank from the 0.955
  single-quantum baseline.

Like every obs layer, the accountant is **absent by default** (the
``--cost-accounting`` serve flag constructs it): call sites pay one
``is None`` predicate, and no ``knn_cost_*`` instrument ever exists while
it is off (pinned by scripts/check_disabled_overhead.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from knn_tpu import obs

#: The class every untagged request lands in.
DEFAULT_CLASS = "interactive"
#: Bound for client-supplied class names (they become Prometheus labels).
MAX_CLASS_LEN = 32
_CLASS_CHARS = frozenset("abcdefghijklmnopqrstuvwxyz0123456789_.-")
#: Distinct classes one accountant will track; the rest fold into
#: :data:`OVERFLOW_CLASS`. Classes mint Prometheus series and per-class
#: table slots, so a client inventing a fresh class per request must hit
#: a ceiling, not grow the scrape payload without bound.
MAX_CLASSES = 64
#: Where requests land once :data:`MAX_CLASSES` distinct values exist.
OVERFLOW_CLASS = "other"


def valid_request_class(cls: str) -> bool:
    """Client-supplied request classes go straight into metric labels and
    log lines, so the alphabet is tight: 1-32 chars of ``[a-z0-9_.-]``.
    Anything else is a 400 at the front door, never a label explosion."""
    if not cls or len(cls) > MAX_CLASS_LEN:
        return False
    return all(c in _CLASS_CHARS for c in cls)


def padded_query_rows(engine: str, rows: int, num_features: int = 1,
                      k: int = 5) -> int:
    """Compiled-shape query rows for ONE engine dispatch of ``rows`` actual
    rows — the rows the device really sweeps. XLA pads queries to the
    installed bucket ladder's smallest bucket >= rows (or the 128-row
    quantum while no ladder is set) — resolved from
    ``models/knn.query_padded_rows``, THE definition the pad and the
    executable-cache key also use, so waste metrics reflect the real
    dispatched bucket and can never silently diverge (the PR-8 hardening
    contract); the stripe kernel pads to its resolved ``block_q`` grid;
    host engines (oracle/native) pad nothing."""
    rows = int(rows)
    if rows <= 0:
        return 0
    if engine == "xla":
        from knn_tpu.models.knn import query_padded_rows

        return query_padded_rows(rows)
    if engine == "stripe":
        from knn_tpu.ops.pallas_knn import stripe_block_sizes

        block_q, _ = stripe_block_sizes(
            None, None, rows, k, d_pad=((num_features + 7) // 8) * 8,
        )
        return -(-rows // block_q) * block_q
    return rows


def padded_candidate_rows(rows: int) -> int:
    """Compiled-shape candidate rows for one device IVF gather+score
    dispatch of ``rows`` gathered candidates per query — resolved from
    ``models/knn.candidate_padded_rows``, THE definition the segment
    scorer's pad and its executable-cache key also use (the same
    one-definition contract :func:`padded_query_rows` holds for the
    query axis), so the ``knn_ivf_padded_candidate_rows_total`` waste
    counter reflects the bucket really dispatched."""
    from knn_tpu.models.knn import candidate_padded_rows

    return candidate_padded_rows(rows)


def resolved_retrieval_engine(model) -> str:
    """The candidate engine the model's fast serving rung resolves to —
    mirrors ``models._kneighbors_arrays``'s auto selection so padded-row
    accounting keys on the executable that really runs."""
    from knn_tpu.models.knn import KNNClassifier

    engine = (model._retrieval_engine() if isinstance(model, KNNClassifier)
              else model.engine)
    if engine == "auto":
        from knn_tpu.ops.pallas_knn import stripe_auto_eligible

        if model.metric in (None, "euclidean") and stripe_auto_eligible(
            "exact", model.train_.num_features, model.k
        ):
            return "stripe"
        return "xla"
    return engine


def dispatch_padded_rows(model, rung: str, rows: int, cap: int) -> int:
    """Compiled-shape rows for one serving-ladder dispatch of ``rows``
    rows, summed over the ``max_batch`` chunking the batcher applies
    (``MicroBatcher._call_rung``): each chunk pads to its engine's quantum
    independently."""
    if rung in ("oracle", "ivf"):
        # Host rungs pad nothing: the oracle scans numpy directly, and
        # the ivf rung gathers exact candidate sets on host
        # (knn_tpu/index/ivf.py) — rows in == rows swept.
        engine = rung
    elif rung == "xla":
        engine = "xla"
    else:  # the model's own fast rung
        engine = resolved_retrieval_engine(model)
    nf, k = model.train_.num_features, model.k
    rows, cap = int(rows), max(1, int(cap))
    if rows <= cap:
        return padded_query_rows(engine, rows, nf, k)
    total, s = 0, 0
    while s < rows:
        total += padded_query_rows(engine, min(cap, rows - s), nf, k)
        s += cap
    return total


class CostAccountant:
    """Attributes measured dispatch cost across coalesced requests.

    :meth:`attribute` is called by the batcher worker once per ladder-rung
    attempt with the requests that were live for it; :meth:`note_outcome`
    is called at every terminal outcome (success, expiry, rejection,
    error) so class labels survive the 4xx/5xx paths too. :meth:`export`
    is the scrape/report side (``GET /debug/capacity``).

    Thread model: ``attribute`` runs on the single batcher worker;
    ``note_outcome``/``export`` may run on handler threads — all state is
    under one lock, and the registry instruments carry their own.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._classes: dict = {}
        self._known_classes: set = {DEFAULT_CLASS, OVERFLOW_CLASS}
        self._dispatch_wall_ms = 0.0
        self._attributed_ms = 0.0
        self._dispatches = 0
        self._actual_rows = 0
        self._padded_rows = 0

    def admit_class(self, cls: str) -> str:
        """The canonical class a request is accounted under: ``cls``
        itself while fewer than :data:`MAX_CLASSES` distinct values have
        been seen (or it already has), else :data:`OVERFLOW_CLASS` — the
        cardinality ceiling for client-supplied label values. Called at
        admission (``MicroBatcher.submit``) so every downstream counter,
        slot, and cost block agrees on the label."""
        with self._lock:
            if cls in self._known_classes:
                return cls
            if len(self._known_classes) < MAX_CLASSES:
                self._known_classes.add(cls)
                return cls
        return OVERFLOW_CLASS

    def _class_slot(self, cls: str) -> dict:
        slot = self._classes.get(cls)
        if slot is None:
            slot = self._classes[cls] = {
                "device_ms": 0.0, "rows": 0, "bytes": 0, "requests": 0,
                "outcomes": {}, "rungs": {},
            }
        return slot

    # -- recording ---------------------------------------------------------

    def note_outcome(self, request_class: Optional[str],
                     outcome: str) -> None:
        """One terminal request outcome, by class — the counter that makes
        a class's 429/504/500 traffic visible next to its device spend."""
        cls = request_class or DEFAULT_CLASS
        obs.counter_add(
            "knn_cost_requests_total", 1,
            help="serving requests by class and terminal outcome (the "
                 "per-class denominator for the knn_cost_* spend counters)",
            outcome=outcome, **{"class": cls},
        )
        with self._lock:
            slot = self._class_slot(cls)
            slot["requests"] += 1
            slot["outcomes"][outcome] = slot["outcomes"].get(outcome, 0) + 1

    def attribute(self, requests, wall_ms: float, *, rung: str, rows: int,
                  padded_rows: int, nbytes: int = 0,
                  ok: bool = True) -> None:
        """Split one measured rung attempt across ``requests``.

        ``requests`` are the batch's live requests (objects with ``rows``,
        ``request_class``, ``meta``, ``trace``); ``wall_ms`` is the
        attempt's measured wall; ``rows``/``padded_rows`` the actual and
        compiled-shape query rows; ``nbytes`` the host<->device payload
        (counted on the answering attempt only). Shares are proportional
        to each request's rows with the float residual folded into the
        last request, so the shares sum EXACTLY to ``wall_ms`` as summed
        left-to-right — the conservation contract."""
        n = len(requests)
        if n == 0 or wall_ms < 0:
            return
        total_rows = sum(r.rows for r in requests)
        if total_rows <= 0:
            return
        pad_overhead = max(0, int(padded_rows) - int(rows))
        # Residual-to-last shares: exact conservation by construction.
        ms_shares, byte_shares, ms_run, byte_run = [], [], 0.0, 0
        for i, r in enumerate(requests):
            if i == n - 1:
                ms_shares.append(wall_ms - ms_run)
                byte_shares.append(int(nbytes) - byte_run)
            else:
                s = wall_ms * (r.rows / total_rows)
                b = int(nbytes * r.rows / total_rows)
                ms_shares.append(s)
                byte_shares.append(b)
                ms_run += s
                byte_run += b
        obs.counter_add(
            "knn_cost_dispatch_wall_ms_total", wall_ms,
            help="measured serving dispatch wall ms (the conservation "
                 "anchor: per-request knn_cost_device_ms_total attributions "
                 "sum to this)",
        )
        if pad_overhead:
            obs.counter_add(
                "knn_cost_padded_rows_total", pad_overhead,
                help="query rows the compiled dispatch shape forced beyond "
                     "the batch's actual rows (what shape-bucketed batching "
                     "would save — ROADMAP #2)",
            )
        # Pre-aggregate per class: a max_batch=256 batch of 1-row requests
        # must cost O(classes) registry lookups, not O(requests), on the
        # single worker thread that is the serving throughput bottleneck.
        per_class: dict = {}  # cls -> [ms, bytes, rows]
        classes = []
        for r, ms_share, byte_share in zip(requests, ms_shares, byte_shares):
            cls = r.request_class or DEFAULT_CLASS
            classes.append(cls)
            agg = per_class.setdefault(cls, [0.0, 0, 0])
            agg[0] += ms_share
            if ok:
                agg[1] += byte_share
                agg[2] += r.rows
        for cls, (cls_ms, cls_bytes, cls_rows) in per_class.items():
            obs.counter_add(
                "knn_cost_device_ms_total", cls_ms,
                help="device/dispatch wall ms attributed per request class "
                     "and answering rung, proportional to query rows "
                     "(conserves the measured dispatch wall exactly)",
                rung=rung, **{"class": cls},
            )
            if ok:
                obs.counter_add(
                    "knn_cost_rows_total", cls_rows,
                    help="query rows served, by request class",
                    **{"class": cls},
                )
                if cls_bytes:
                    obs.counter_add(
                        "knn_cost_bytes_total", cls_bytes,
                        help="host<->device payload bytes attributed per "
                             "request class (features up, candidates down)",
                        **{"class": cls},
                    )
        # One lock section for totals + class slots: a /debug/capacity
        # reader mid-update must never see attributed_ms ahead of the
        # per-class sums.
        with self._lock:
            self._dispatch_wall_ms += wall_ms
            # Sum the shares ACTUALLY minted (left-to-right, == wall_ms by
            # the residual construction) — never wall_ms itself, or the
            # export-level conservation checks (the probe, bench's
            # cost_conservation_ok) would be tautologies that no share
            # bug could ever fail.
            self._attributed_ms += sum(ms_shares)
            self._dispatches += 1
            self._actual_rows += int(rows)
            self._padded_rows += int(padded_rows)
            for cls, (cls_ms, cls_bytes, cls_rows) in per_class.items():
                slot = self._class_slot(cls)
                slot["device_ms"] += cls_ms
                slot["rungs"][rung] = slot["rungs"].get(rung, 0.0) + cls_ms
                if ok:
                    slot["rows"] += cls_rows
                    slot["bytes"] += cls_bytes
        for r, cls, ms_share, byte_share in zip(requests, classes,
                                                ms_shares, byte_shares):
            # The per-request cost block: accumulated across the attempts
            # this request rode, embedded in the future's meta and the
            # flight-recorder timeline (/debug/requests?id=... shows it).
            block = r.meta.get("cost")
            if block is None:
                block = r.meta["cost"] = {
                    "class": cls, "rows": int(r.rows), "device_ms": 0.0,
                    "bytes": 0, "padded_rows_share": 0.0, "rungs": {},
                }
            block["device_ms"] += ms_share
            block["rungs"][rung] = round(
                block["rungs"].get(rung, 0.0) + ms_share, 6)
            if ok:
                block["bytes"] += byte_share
            if pad_overhead:
                block["padded_rows_share"] += pad_overhead * (
                    r.rows / total_rows)
            if r.trace is not None:
                r.trace.annotate(cost={
                    **block,
                    "device_ms": round(block["device_ms"], 6),
                    "padded_rows_share": round(
                        block["padded_rows_share"], 3),
                    "rungs": dict(block["rungs"]),
                })

    # -- reporting ---------------------------------------------------------

    def export(self) -> dict:
        """The per-class cost join for ``GET /debug/capacity``: device-ms /
        rows / bytes / outcomes per class, per-(class, rung) spend, and the
        conservation totals (``attributed_ms`` vs ``dispatch_wall_ms`` —
        equal to float precision by construction, and the probe checks)."""
        with self._lock:
            classes = {
                cls: {
                    "device_ms": round(s["device_ms"], 6),
                    "rows": s["rows"],
                    "bytes": s["bytes"],
                    "requests": s["requests"],
                    "outcomes": dict(s["outcomes"]),
                    "rungs": {r: round(v, 6) for r, v in s["rungs"].items()},
                }
                for cls, s in self._classes.items()
            }
            padded = self._padded_rows
            totals = {
                "dispatch_wall_ms": round(self._dispatch_wall_ms, 6),
                "attributed_ms": round(self._attributed_ms, 6),
                "dispatches": self._dispatches,
                "rows": self._actual_rows,
                "padded_rows": padded,
                "padded_row_waste_ratio": (
                    round((padded - self._actual_rows) / padded, 6)
                    if padded > 0 else 0.0
                ),
            }
        return {"classes": classes, "totals": totals}
