"""One-command incident reports: ``knn_tpu report --history DIR``.

Stitches everything a post-mortem needs out of artifacts that already
exist on disk — no live process required:

* the durable metrics history (obs/history.py segments),
* alert fire/resolve pairs and action outcomes (``alerts.jsonl``),
* flight-recorder slowest-K dumps frozen at fire time (``forensics/``),
* alert-armed device profiles (``profiles/``),
* workload-capture manifests (``--captures DIR``, the serve process's
  ``--capture-dir``) — alert-armed captures carry ``reason=alert:<name>``,
* access-log error lines (``--access-log FILE``),

into a single JSON document plus a markdown rendering with ONE merged
timeline. Generation is deterministic: every timestamp comes from the
artifacts, never from the wall clock, so the same inputs always produce
byte-identical output (testable, diffable, attachable to a ticket).
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional, Tuple

from knn_tpu.obs import history as history_mod
from knn_tpu.resilience.errors import DataError

#: Error access-log lines kept on the timeline (the log itself is the
#: full record; the report is a summary).
_MAX_ERROR_LINES = 100


def _read_jsonl_tolerant(path: str) -> List[dict]:
    """Audit-log reader with the WAL-tail rule: a torn FINAL line is a
    crash signature and is dropped; garbage anywhere else is damage."""
    with open(path, "r", encoding="utf-8") as f:
        raw = f.read().split("\n")
    if raw and raw[-1] == "":
        raw.pop()
    out: List[dict] = []
    for i, line in enumerate(raw):
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except ValueError:
            if i == len(raw) - 1:
                break
            raise DataError(f"{path}:{i + 1}: corrupt record")
        out.append(rec)
    return out


def _scan_captures(captures_dir: str) -> List[dict]:
    out = []
    try:
        names = sorted(os.listdir(captures_dir))
    except OSError:
        return out
    for name in names:
        manifest = os.path.join(captures_dir, name, "manifest.json")
        if not (name.startswith("workload-") and os.path.isfile(manifest)):
            continue
        try:
            with open(manifest, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        out.append({"path": os.path.join(captures_dir, name),
                    "reason": doc.get("reason"),
                    "t0_unix": doc.get("t0_unix"),
                    "records": doc.get("records"),
                    "stop_reason": doc.get("stop_reason")})
    return out


def _scan_dumps(dirpath: str, pattern: str) -> List[dict]:
    """Forensics/profile artifacts named ``<kind>-<alert>-<ms>.json``."""
    out = []
    rx = re.compile(pattern)
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for name in names:
        m = rx.match(name)
        if m:
            out.append({"path": os.path.join(dirpath, name),
                        "alert": m.group(1),
                        "ts": int(m.group(2)) / 1000.0})
    return out


def build_report(history_dir: str, *,
                 window: Optional[float] = None,
                 access_log: Optional[str] = None,
                 captures: Optional[str] = None) -> dict:
    """Assemble the incident-report document. ``window`` (seconds) trails
    back from the newest timestamp found in ANY artifact, so a report
    over a crashed process's directory covers right up to the crash."""
    hist = history_mod.load_history(history_dir)
    alerts_path = os.path.join(history_dir, "alerts.jsonl")
    alert_entries = (_read_jsonl_tolerant(alerts_path)
                     if os.path.isfile(alerts_path) else [])
    capture_entries = _scan_captures(captures) if captures else []
    forensics = _scan_dumps(os.path.join(history_dir, "forensics"),
                            r"^slowest-(.+)-(\d+)\.json$")
    profiles = _scan_dumps(os.path.join(history_dir, "profiles"),
                           r"^profile-(.+)-(\d+)\.json$")

    all_ts = [s[0] for s in hist.samples]
    all_ts += [e["ts"] for e in alert_entries if isinstance(e.get("ts"), (int, float))]
    all_ts += [c["t0_unix"] for c in capture_entries
               if isinstance(c.get("t0_unix"), (int, float))]
    if not all_ts:
        raise DataError(f"{history_dir}: nothing to report on")
    t_hi = max(all_ts)
    t_lo = t_hi - window if window is not None else min(all_ts)

    def in_window(ts) -> bool:
        return isinstance(ts, (int, float)) and t_lo <= ts <= t_hi

    timeline: List[dict] = []
    fires = resolves = 0
    for e in alert_entries:
        if not in_window(e.get("ts")):
            continue
        event = e.get("event")
        if event == "fire":
            fires += 1
            summary = (f"alert {e.get('alert')} FIRED "
                       f"(severity={e.get('severity')}, value={e.get('value')})")
        elif event == "resolve":
            resolves += 1
            summary = f"alert {e.get('alert')} resolved (value={e.get('value')})"
        elif event == "action":
            summary = (f"action {e.get('action')} on {e.get('alert')} "
                       f"({e.get('on')}): {e.get('outcome')}")
        else:
            summary = f"alert {e.get('alert')}: {event}"
        timeline.append({"ts": round(float(e["ts"]), 3), "kind": f"alert-{event}",
                         "summary": summary, **{k: v for k, v in e.items()
                                                if k not in ("ts", "event")}})
    for c in capture_entries:
        if not in_window(c.get("t0_unix")):
            continue
        timeline.append({
            "ts": round(float(c["t0_unix"]), 3), "kind": "capture",
            "summary": (f"workload capture ({c.get('reason')}): "
                        f"{c.get('records')} records, "
                        f"stop={c.get('stop_reason')}"),
            "reason": c.get("reason"), "path": c["path"]})
    for f in forensics:
        if in_window(f["ts"]):
            timeline.append({"ts": round(f["ts"], 3), "kind": "forensics",
                             "summary": f"slowest-K frozen for {f['alert']}",
                             "path": f["path"]})
    for p in profiles:
        if in_window(p["ts"]):
            timeline.append({"ts": round(p["ts"], 3), "kind": "profile",
                             "summary": f"device profile for {p['alert']}",
                             "path": p["path"]})

    access = None
    if access_log and os.path.isfile(access_log):
        lines = _read_jsonl_tolerant(access_log)
        total = errors = 0
        err_lines = []
        for rec in lines:
            if not in_window(rec.get("ts")):
                continue
            total += 1
            status = rec.get("status")
            if isinstance(status, int) and status >= 400:
                errors += 1
                if len(err_lines) < _MAX_ERROR_LINES:
                    err_lines.append(rec)
        for rec in err_lines:
            timeline.append({
                "ts": round(float(rec["ts"]), 3), "kind": "request-error",
                "summary": (f"{rec.get('kind')} {rec.get('status')} "
                            f"{rec.get('outcome')} "
                            f"({rec.get('ms')} ms, rung={rec.get('rung')}, "
                            f"id={rec.get('request_id')})"),
                "request_id": rec.get("request_id")})
        access = {"path": access_log, "requests": total, "errors": errors,
                  "error_lines_on_timeline": len(err_lines)}

    timeline.sort(key=lambda e: (e["ts"], e["kind"], e["summary"]))

    metrics = _summarize_metrics(hist.samples, t_lo, t_hi)
    return {
        "report": 1,
        "history_dir": history_dir,
        "window": {"from": round(t_lo, 3), "to": round(t_hi, 3),
                   "seconds": round(t_hi - t_lo, 3)},
        "history": {"segments": len(hist.segments),
                    "samples": len(hist.samples),
                    "repaired_torn_tail": hist.repaired},
        "alerts": {"fires": fires, "resolves": resolves,
                   "entries": len(alert_entries)},
        "captures": capture_entries,
        "access_log": access,
        "timeline": timeline,
        "metrics": metrics,
    }


def _summarize_metrics(samples, t_lo, t_hi) -> List[dict]:
    """Per-series digest over the window: counters report their delta,
    gauges min/last/max, histograms observation-count delta + mean."""
    first: dict = {}
    last: dict = {}
    lo: dict = {}
    hi: dict = {}
    for ts, state in samples:
        if ts < t_lo or ts > t_hi:
            continue
        for key, e in state.items():
            v = history_mod._value_of(e)
            if key not in first:
                first[key] = (e, v)
                lo[key] = hi[key] = v
            lo[key] = min(lo[key], v)
            hi[key] = max(hi[key], v)
            last[key] = (e, v)
    out = []
    for key in sorted(first):
        e0, v0 = first[key]
        e1, v1 = last[key]
        row = {"name": e1[1], "kind": {"c": "counter", "g": "gauge",
                                       "h": "histogram"}[e1[0]],
               "labels": e1[2]}
        if e1[0] == "c":
            row["delta"] = round(v1 - v0, 6)
            row["last"] = round(v1, 6)
        elif e1[0] == "g":
            row.update(min=round(lo[key], 6), max=round(hi[key], 6),
                       last=round(v1, 6))
        else:
            row["count_delta"] = int(v1 - v0)
            dsum = e1[5] - e0[5]
            row["sum_delta"] = round(dsum, 6)
            if v1 > v0:
                row["mean"] = round(dsum / (v1 - v0), 6)
        out.append(row)
    return out


def render_markdown(doc: dict) -> str:
    w = doc["window"]
    lines = [
        "# Incident report",
        "",
        f"History: `{doc['history_dir']}` — {doc['history']['segments']} "
        f"segment(s), {doc['history']['samples']} snapshot(s)"
        + (" (torn tail repaired)" if doc["history"]["repaired_torn_tail"]
           else ""),
        f"Window: {w['from']} .. {w['to']} ({w['seconds']}s)",
        f"Alerts: {doc['alerts']['fires']} fire(s), "
        f"{doc['alerts']['resolves']} resolve(s)",
    ]
    if doc.get("access_log"):
        a = doc["access_log"]
        lines.append(f"Requests: {a['requests']} in window, "
                     f"{a['errors']} error(s)")
    lines += ["", "## Timeline", ""]
    if doc["timeline"]:
        lines += ["| t | kind | event |", "|---|---|---|"]
        for e in doc["timeline"]:
            summary = e["summary"].replace("|", "\\|")
            lines.append(f"| {e['ts']} | {e['kind']} | {summary} |")
    else:
        lines.append("(no events in window)")
    if doc["captures"]:
        lines += ["", "## Workload captures", ""]
        for c in doc["captures"]:
            lines.append(f"- `{c['path']}` — reason={c['reason']}, "
                         f"records={c['records']}, stop={c['stop_reason']}")
    lines += ["", "## Metrics", ""]
    rows = doc["metrics"]
    if rows:
        lines += ["| metric | labels | summary |", "|---|---|---|"]
        for r in rows:
            labels = ",".join(f"{k}={v}" for k, v in sorted(r["labels"].items()))
            if r["kind"] == "counter":
                s = f"+{r['delta']} (last {r['last']})"
            elif r["kind"] == "gauge":
                s = f"min {r['min']} / max {r['max']} / last {r['last']}"
            else:
                s = f"count +{r['count_delta']}"
                if "mean" in r:
                    s += f", mean {r['mean']}"
            lines.append(f"| {r['name']} | {labels} | {s} |")
    else:
        lines.append("(no metrics in window)")
    lines.append("")
    return "\n".join(lines)
