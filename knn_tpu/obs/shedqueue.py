"""The bounded shed-on-overload sample queue + background consumer — the
never-block-the-producer primitive both quality layers ride
(:mod:`knn_tpu.obs.quality` shadow samples, :mod:`knn_tpu.obs.drift`
query rows). One implementation so the contract lives — and is tested —
in one place (the two hand-rolled copies had already diverged once).

Contract:

- :meth:`offer` runs on the SERVING worker thread and is O(1): one
  seeded RNG draw plus one append under a lock whose every critical
  section is O(1). A full queue **sheds** the sample (``on_shed`` counts
  it) and returns immediately — the producer never blocks, whatever the
  consumer is doing.
- the consumer daemon thread calls ``consume(sample)`` per queued item
  and absorbs every exception (``on_error`` counts those): a scoring bug
  must never kill serving or wedge the queue.
- ``autostart=False`` holds the consumer off so tests can pin the
  shed/queue mechanics deterministically; :meth:`start` arms it later.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Optional


class ShedQueue:
    """See the module docstring. ``rate`` is the per-offer sampling
    probability (the OWNING layer decides whether rate 0 is legal —
    here it simply never enqueues); ``make()`` passed to :meth:`offer`
    builds the sample lazily, only after the draw and the cap admit it.
    """

    def __init__(self, *, rate: float, queue_cap: int,
                 consume: Callable, thread_name: str, seed: int = 0,
                 on_shed: Optional[Callable] = None,
                 on_error: Optional[Callable] = None,
                 autostart: bool = True):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        if queue_cap < 1:
            raise ValueError(f"queue_cap must be >= 1, got {queue_cap}")
        self.rate = float(rate)
        # Optional zero-arg gate the control plane's brownout installs
        # (knn_tpu/control/brownout.py): while it returns True, offers
        # are deferred — counted as shed, never enqueued — so background
        # scoring work schedules into measured headroom. None (the
        # default, and always without a control plane) costs nothing.
        self.defer: Optional[Callable[[], bool]] = None
        self.queue_cap = int(queue_cap)
        self.thread_name = thread_name
        self._consume = consume
        self._on_shed = on_shed
        self._on_error = on_error
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._wake = threading.Event()
        self._closed = False
        self._in_flight = False
        self.shed = 0
        self._worker: Optional[threading.Thread] = None
        if autostart:
            self.start()

    def start(self) -> None:
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._run, name=self.thread_name, daemon=True)
            self._worker.start()

    # -- producer side (the serving worker thread) -------------------------

    def offer(self, make: Callable) -> bool:
        """Sample one item; O(1), never blocks. Returns whether it was
        queued."""
        with self._lock:
            if self._closed or self._rng.random() >= self.rate:
                return False
            if self.defer is not None and self.defer():
                # Headroom-negative deferral: the draw stays ahead of the
                # RNG stream (a deferred offer consumes its draw exactly
                # like an admitted one), the sample is counted shed.
                self.shed += 1
                if self._on_shed is not None:
                    self._on_shed()
                return False
            if len(self._queue) >= self.queue_cap:
                self.shed += 1
                if self._on_shed is not None:
                    self._on_shed()
                return False
            self._queue.append(make())
        self._wake.set()
        return True

    # -- consumer side -----------------------------------------------------

    def _run(self) -> None:
        while True:
            self._wake.wait(0.2)
            while True:
                with self._lock:
                    if not self._queue:
                        self._wake.clear()
                        if self._closed:
                            return
                        break
                    sample = self._queue.popleft()
                    self._in_flight = True
                try:
                    self._consume(sample)
                except Exception:  # noqa: BLE001 — must never kill the queue
                    if self._on_error is not None:
                        try:
                            self._on_error()
                        except Exception:  # noqa: BLE001
                            pass
                finally:
                    with self._lock:
                        self._in_flight = False

    # -- lifecycle / read side ---------------------------------------------

    def depth(self) -> int:
        """Samples queued OR currently being consumed — a poller that
        waits for depth 0 (the soak gates' `/debug/quality` loop) is
        guaranteed the consumer's stats include every earlier offer."""
        with self._lock:
            return len(self._queue) + (1 if self._in_flight else 0)

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued sample has been fully CONSUMED —
        empty queue and no sample in flight, so stats read after a
        successful drain are complete (tests + the soak gates); the
        serving path never calls this."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._in_flight:
                    return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        if self._worker is not None:
            self._worker.join(timeout=5)
