"""Multihost fleet aggregation: merge per-process metric registries on
process 0, with ``{proc=…}`` labels and straggler gauges.

Every process in a multi-controller run (``parallel/multihost.py``) keeps
its own private :class:`~knn_tpu.obs.metrics.MetricsRegistry`; until now
those never met, so a fleet-wide question ("which shard is the
straggler?") had no answer. This module closes that gap:

- :func:`snapshot_registry` — one process's registry as a plain
  JSON-able list (raw bucket counts for histograms, so merging is exact);
- :func:`merge_snapshots`   — process 0 folds the per-process snapshots
  into one registry, every instrument gaining a ``proc`` label (counters
  stay per-process — summing them is the scrape consumer's choice, the
  merge must not destroy attribution);
- :func:`straggler_gauges`  — derived fleet gauges over each process's
  ``knn_shard_dispatch_ms`` sample (``obs/instrument.py::
  record_shard_dispatch`` — recorded by the query-sharded, train-sharded,
  and ring strategies): ``knn_shard_dispatch_ms_max`` /
  ``knn_shard_dispatch_ms_min`` / ``knn_shard_dispatch_skew`` per path.
  A skew ratio near 1.0 means a balanced fleet; the straggler is the
  proc whose gauge equals the max.
- :func:`aggregate_multihost` — the transport: snapshots cross hosts as
  length-prefixed uint8 arrays through
  ``jax.experimental.multihost_utils.process_allgather`` (the same
  device fabric the predict collectives use — no side channel to
  configure). Process 0 returns the merged registry + straggler dict;
  other processes return ``(None, {})``. Single-process: merges its own
  snapshot (proc 0), so the output shape is launcher-independent.

Where jaxlib lacks multi-process collectives (the CPU test box), the
merge/straggler math is pinned by fake-registry unit tests instead
(tests/test_aggregate.py) — the acceptance contract of ISSUE 6.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from knn_tpu import obs
from knn_tpu.obs.metrics import Histogram, MetricsRegistry

#: The sharded strategies whose dispatch walls feed the straggler gauges.
STRATEGY_PATHS = ("query-sharded", "train-sharded", "ring")


def snapshot_registry(registry: Optional[MetricsRegistry] = None) -> List[dict]:
    """One registry as a JSON-able list of instrument records. Histograms
    carry RAW (non-cumulative) bucket counts so a merge can reconstruct
    them exactly; the exposition-side cumulative form is derivable, the
    reverse only up to the shared bucket ladder."""
    reg = registry if registry is not None else obs.registry()
    out = []
    for inst in reg.instruments():
        rec = {
            "name": inst.name,
            "kind": inst.kind,
            "labels": dict(inst.labels),
            "help": inst.help,
        }
        if isinstance(inst, Histogram):
            rec.update(
                buckets=list(inst.buckets),
                counts=inst.bucket_counts(),
                sum=inst.sum,
                count=inst.count,
            )
        else:
            rec["value"] = inst.value
        out.append(rec)
    return out


def merge_snapshots(
    snapshots: Dict,
    registry: Optional[MetricsRegistry] = None,
    label: Optional[str] = "proc",
) -> MetricsRegistry:
    """Fold per-source snapshots into one registry, adding
    ``<label>=<source key>`` to every label set. Values stay per-source
    (a counter from proc 1 never adds into proc 0's) — fleet-level sums
    are a query over the merged registry, not a lossy pre-aggregation.

    ``label`` names the attribution key: ``"proc"`` for multihost
    processes (the original use), ``"replica"`` for the fleet router's
    federated ``/metrics`` scrape (snapshot keys are replica base URLs).
    ``label=None`` folds records with their labels unchanged — how the
    router overlays its OWN registry into the same merged document."""
    reg = registry if registry is not None else MetricsRegistry()
    for proc in sorted(snapshots):
        for rec in snapshots[proc]:
            labels = dict(rec["labels"])
            if label is not None:
                labels[label] = str(proc)
            help_ = rec.get("help", "")
            if rec["kind"] == "counter":
                reg.counter(rec["name"], help=help_, **labels).add(
                    rec["value"]
                )
            elif rec["kind"] == "gauge":
                reg.gauge(rec["name"], help=help_, **labels).set(
                    rec["value"]
                )
            elif rec["kind"] == "histogram":
                h = reg.histogram(
                    rec["name"], buckets=rec["buckets"], help=help_, **labels
                )
                h.merge_counts(rec["counts"], rec["sum"], rec["count"])
            else:
                raise ValueError(
                    f"snapshot record {rec['name']!r} has unknown kind "
                    f"{rec['kind']!r}"
                )
    return reg


def straggler_gauges(
    snapshots: Dict[int, List[dict]],
    registry: MetricsRegistry,
) -> Dict[str, dict]:
    """Derive the fleet straggler gauges from each process's
    ``knn_shard_dispatch_ms`` sample: per strategy path, set
    ``knn_shard_dispatch_ms_max`` / ``_min`` and
    ``knn_shard_dispatch_skew`` (= max/min) on ``registry`` and return
    ``{path: {"max_ms", "min_ms", "skew", "max_proc", "procs"}}``.
    Paths no process dispatched are absent from the result."""
    per_path: Dict[str, Dict[int, float]] = {}
    for proc, snap in snapshots.items():
        for rec in snap:
            if rec["name"] != "knn_shard_dispatch_ms":
                continue
            path = rec["labels"].get("path", "?")
            per_path.setdefault(path, {})[proc] = float(rec["value"])
    out: Dict[str, dict] = {}
    for path, by_proc in sorted(per_path.items()):
        vals = list(by_proc.values())
        mx, mn = max(vals), min(vals)
        # A 0 ms min (the gauge rounds to 3 decimals, so a sub-µs wall
        # stores 0.0) must not read as INFINITE skew — inf also breaks
        # strict-JSON consumers of the --metrics-out artifact. Clamp the
        # denominator to the rounding floor: the ratio then means "at
        # least this skewed", stays finite, and a fleet of all-zero walls
        # is exactly balanced.
        skew = 1.0 if mx == 0 else mx / max(mn, 0.001)
        max_proc = max(by_proc, key=by_proc.get)
        registry.gauge(
            "knn_shard_dispatch_ms_max",
            help="slowest process's sharded dispatch->fetch wall ms",
            path=path,
        ).set(mx)
        registry.gauge(
            "knn_shard_dispatch_ms_min",
            help="fastest process's sharded dispatch->fetch wall ms",
            path=path,
        ).set(mn)
        registry.gauge(
            "knn_shard_dispatch_skew",
            help="straggler ratio: max/min sharded dispatch wall across "
                 "processes (1.0 = balanced; min clamped to the 0.001 ms "
                 "rounding floor so the gauge stays finite)",
            path=path,
        ).set(round(skew, 4))
        out[path] = {
            "max_ms": mx,
            "min_ms": mn,
            "skew": skew,
            "max_proc": max_proc,
            "procs": len(by_proc),
        }
    return out


def local_straggler_gauges(path: str,
                           walls_ms: Dict[int, float]) -> Optional[dict]:
    """The IN-PROCESS twin of :func:`straggler_gauges`: derive the same
    ``knn_shard_dispatch_ms_max/min`` + ``knn_shard_dispatch_skew``
    family from one sharded serve dispatch's per-shard walls
    (``{shard: wall_ms}``) and set them on the default registry. One
    metric family for both topologies — a dashboard watching shard skew
    does not care whether the shards are logical (one process, PR 18's
    ``serve --shards``) or whole processes (the multihost launcher).
    Returns ``{"max_ms", "min_ms", "skew", "max_shard", "shards"}`` or
    None when obs is off / no walls."""
    from knn_tpu import obs

    if not walls_ms or not obs.enabled():
        return None
    vals = list(walls_ms.values())
    mx, mn = max(vals), min(vals)
    # Same finite-skew clamp as the fleet derivation above.
    skew = 1.0 if mx == 0 else mx / max(mn, 0.001)
    obs.gauge_set(
        "knn_shard_dispatch_ms_max",
        round(mx, 3),
        help="slowest process's sharded dispatch->fetch wall ms",
        path=path,
    )
    obs.gauge_set(
        "knn_shard_dispatch_ms_min",
        round(mn, 3),
        help="fastest process's sharded dispatch->fetch wall ms",
        path=path,
    )
    obs.gauge_set(
        "knn_shard_dispatch_skew",
        round(skew, 4),
        help="straggler ratio: max/min sharded dispatch wall across "
             "processes (1.0 = balanced; min clamped to the 0.001 ms "
             "rounding floor so the gauge stays finite)",
        path=path,
    )
    return {
        "max_ms": round(mx, 3),
        "min_ms": round(mn, 3),
        "skew": round(skew, 4),
        "max_shard": max(walls_ms, key=walls_ms.get),
        "shards": len(walls_ms),
    }


def aggregate_multihost(
    registry: Optional[MetricsRegistry] = None,
) -> Tuple[Optional[MetricsRegistry], Dict[str, dict]]:
    """Gather every process's registry snapshot and merge on process 0.

    Returns ``(merged_registry, stragglers)`` on process 0 and
    ``(None, {})`` elsewhere. Single-process (no launcher): merges the
    local snapshot as proc 0 so callers see one output shape.

    Transport: the JSON snapshot rides ``process_allgather`` as a padded
    uint8 array (lengths gathered first) — the collectives fabric the
    predicts already proved works, no extra RPC channel. The gather is
    symmetric (every process participates and receives all snapshots);
    only process 0 pays the merge.
    """
    import jax

    local = snapshot_registry(registry)
    if jax.process_count() <= 1:
        snaps = {0: local}
        merged = merge_snapshots(snaps)
        return merged, straggler_gauges(snaps, merged)

    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(
        json.dumps(local, separators=(",", ":")).encode(), dtype=np.uint8
    )
    lengths = np.asarray(
        multihost_utils.process_allgather(np.int64(payload.size))
    ).reshape(-1)
    buf = np.zeros(int(lengths.max()), np.uint8)
    buf[: payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    if jax.process_index() != 0:
        return None, {}
    snaps = {
        p: json.loads(bytes(gathered[p][: int(lengths[p])]).decode())
        for p in range(gathered.shape[0])
    }
    merged = merge_snapshots(snaps)
    return merged, straggler_gauges(snaps, merged)
