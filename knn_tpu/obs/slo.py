"""SLO tracking: objectives, multi-window burn rates, `knn_slo_*` gauges.

A latency histogram says what happened; an SLO burn rate says how fast the
error budget is being spent — the Monarch/SRE-workbook alerting shape
(PAPERS.md): ``burn = bad_fraction / (1 - target)`` over a window, so
``burn == 1`` means "exactly on budget", ``burn >> 1`` means "budget gone
in hours, page someone", and multi-window (a short and a long window
together) separates a real incident from one bad scrape.

Three serving SLIs recorded once per terminal HTTP outcome
(``serve/server.py``), plus a fourth recorded at shadow-scoring cadence
(``obs/quality.py``):

- ``availability`` — good = the request answered 200. Overload shedding
  (429/503), deadline 504s, and 500s spend budget; client-side 400s are
  excluded entirely (they are the caller's defect, not the service's).
- ``latency``      — good = answered 200 within ``latency_target_ms``.
- ``fast_rung``    — good = answered 200 by the model's own configured
  engine, NOT a degradation rung. The motivation's "a request silently
  rode the oracle rung" is exactly this SLI burning while availability
  stays green — bit-identical answers, degraded capacity.
- ``quality``      — good = a shadow-scored request whose served answer
  matched the oracle rung exactly (recall 1.0, vote agreement —
  ``obs/quality.py``). Recorded via :meth:`SLOTracker.record_quality` by
  the background scorer, NOT per HTTP outcome: only sampled requests
  spend or bank quality budget, so the burn rate is meaningful at any
  ``--shadow-rate``. This is the SLI ROADMAP item 4's approximate
  retrieval will be held to — a wrong-answer rung burns quality while
  availability/latency stay green.

Implementation: a per-second ring of counters sized to the longest window
(default 5 m / 1 h, env- and CLI-tunable), one lock, O(window) only on
scrape — recording is O(1). Burn-rate gauges are computed lazily at
exposition time (:meth:`SLOTracker.export`), surfaced in ``/metrics`` and
``/healthz``, and asserted by the chaos-soak gate (burn rises during the
fault burst, recovers to ~0 after the breaker re-closes).

Like every obs layer: no tracker installed → one predicate per call site.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from knn_tpu import obs

#: Default burn-rate windows (seconds): the 5 m fast signal and the 1 h
#: budget view. The soak gate shortens these via ``--slo-windows``.
DEFAULT_WINDOWS_S = (300, 3600)

OBJECTIVES = ("availability", "latency", "fast_rung", "quality")


def window_label(seconds: int) -> str:
    if seconds % 3600 == 0:
        return f"{seconds // 3600}h"
    if seconds % 60 == 0:
        return f"{seconds // 60}m"
    return f"{seconds}s"


class SecondRing:
    """A trailing-window ring of per-slot counter sums — THE per-second
    machinery under the SLO SLIs, shared with :mod:`knn_tpu.obs.capacity`'s
    arrival/served/dispatch rate rings.

    Each slot holds ``[slot_stamp, field_0, ..., field_{n-1}]``; ``add``
    is O(1) (stale slots are lazily reset on reuse), ``window_sums`` is
    O(ring) and only runs at scrape/export time. Slot width widens past an
    hour so the ring stays bounded at ~3600 slots whatever the longest
    window is (the PR 5 bounding rule). Field values may be ints or floats
    (they are sums, e.g. busy milliseconds), all under one lock.
    """

    def __init__(self, fields: int, max_window_s: int):
        if fields < 1:
            raise ValueError(f"fields must be >= 1, got {fields}")
        if max_window_s < 1:
            raise ValueError(
                f"max_window_s must be >= 1, got {max_window_s}")
        self.fields = int(fields)
        self.slot_s = max(1, -(-int(max_window_s) // 3600))
        size = -(-int(max_window_s) // self.slot_s)
        self._lock = threading.Lock()
        self._slots = [[0] * (self.fields + 1) for _ in range(size)]

    def __len__(self) -> int:
        return len(self._slots)

    def _now_slot(self) -> int:
        return int(time.monotonic() // self.slot_s)

    def add(self, *deltas) -> None:
        """Fold one event's field deltas into the current slot (O(1))."""
        if len(deltas) != self.fields:
            raise ValueError(
                f"expected {self.fields} field deltas, got {len(deltas)}")
        now = self._now_slot()
        slot = self._slots[now % len(self._slots)]
        with self._lock:
            if slot[0] != now:
                slot[0] = now
                for i in range(1, len(slot)):
                    slot[i] = 0
            for i, d in enumerate(deltas, 1):
                slot[i] += d

    def window_sums(self, window_s: int) -> Tuple:
        """Per-field totals over the trailing ``window_s`` seconds."""
        now = self._now_slot()
        lo = now - max(1, int(window_s) // self.slot_s)
        totals = [0] * self.fields
        with self._lock:
            for slot in self._slots:
                if lo < slot[0] <= now:
                    for i in range(self.fields):
                        totals[i] += slot[i + 1]
        return tuple(totals)


class SLOTracker:
    """Multi-window burn-rate tracker over per-second outcome buckets.

    ``record`` is called once per terminal outcome with the three SLI
    verdicts already decided by the caller; ``burn_rates`` /
    ``export`` aggregate the ring on demand. A window with zero events
    reports burn 0.0 (no traffic spends no budget).
    """

    def __init__(self, *, availability_target: float = 0.999,
                 latency_target_ms: float = 100.0,
                 latency_target: float = 0.99,
                 fast_rung_target: float = 0.99,
                 quality_target: float = 0.999,
                 windows_s: Sequence[int] = DEFAULT_WINDOWS_S):
        for name, t in (("availability_target", availability_target),
                        ("latency_target", latency_target),
                        ("fast_rung_target", fast_rung_target),
                        ("quality_target", quality_target)):
            if not 0.0 < t < 1.0:
                raise ValueError(f"{name} must be in (0, 1), got {t}")
        if latency_target_ms <= 0:
            raise ValueError(
                f"latency_target_ms must be > 0, got {latency_target_ms}")
        ws = tuple(sorted({int(w) for w in windows_s}))
        if not ws or ws[0] < 1:
            raise ValueError(f"windows_s must be positive, got {windows_s}")
        self.targets = {
            "availability": float(availability_target),
            "latency": float(latency_target),
            "fast_rung": float(fast_rung_target),
            "quality": float(quality_target),
        }
        self.latency_target_ms = float(latency_target_ms)
        self.windows_s = ws
        # Ring fields: [total, ok, latency_ok, fast_ok]; the ~3600-slot
        # bounding (coarser slots past an hour — a 30-day window gets
        # 12-minute slots) lives in SecondRing, shared with
        # obs/capacity.py's rate rings.
        self._ring = SecondRing(4, ws[-1])
        self.slot_s = self._ring.slot_s
        # Quality rides its own ring at shadow-scoring cadence: a sampled
        # request scored seconds after it was served must not perturb the
        # per-HTTP-outcome counters above. Fields: [total, good].
        self._qring = SecondRing(2, ws[-1])
        # Policy sheds ride their own ring too: a DELIBERATE 429 of a
        # non-protected class (knn_tpu/control/admission.py) is the
        # control plane working, not an availability incident — it must
        # be visible (exported per window) without spending any
        # objective's budget. Protected classes are never shed by
        # policy, so their overload 429s still land in `record` and
        # still burn. Fields: [sheds].
        self._shed_ring = SecondRing(1, ws[-1])

    # -- recording (O(1)) --------------------------------------------------

    def record(self, ok: bool, latency_ms: float,
               degraded: bool = False) -> None:
        """One terminal outcome: ``ok`` = answered 200, ``latency_ms`` =
        the request's wall, ``degraded`` = served by a fallback rung (or
        unknown — failures count degraded)."""
        self._ring.add(
            1,
            1 if ok else 0,
            1 if ok and latency_ms <= self.latency_target_ms else 0,
            1 if ok and not degraded else 0,
        )

    def record_quality(self, good: bool) -> None:
        """One shadow-scored request (``obs/quality.py``): ``good`` = the
        served answer matched the oracle rung (recall 1.0 and vote
        agreement). Only sampled requests move this SLI."""
        self._qring.add(1, 1 if good else 0)

    def record_shed(self) -> None:
        """One policy shed of a non-protected class: counted for the
        export (an operator must see shed volume next to the burn it was
        spent to avoid), excluded from every objective's denominator —
        the availability-exclusion half of the shed-by-policy contract
        (docs/RESILIENCE.md §Degradation order)."""
        self._shed_ring.add(1)

    # -- aggregation (O(window), scrape-time only) -------------------------

    def window_counts(self, window_s: int) -> Tuple[int, int, int, int]:
        """``(total, ok, latency_ok, fast_ok)`` over the trailing window."""
        return self._ring.window_sums(window_s)

    def quality_window_counts(self, window_s: int) -> Tuple[int, int]:
        """``(scored, good)`` shadow-scored events over the trailing
        window."""
        return self._qring.window_sums(window_s)

    def burn_rates(self) -> Dict[str, Dict[str, float]]:
        """``{objective: {window_label: burn}}``; burn 1.0 = spending the
        error budget exactly at the sustainable rate."""
        out: Dict[str, Dict[str, float]] = {o: {} for o in OBJECTIVES}
        for w in self.windows_s:
            total, ok, lat, fast = self.window_counts(w)
            q_total, q_good = self.quality_window_counts(w)
            label = window_label(w)
            counts = {
                "availability": (total, ok),
                "latency": (total, lat),
                "fast_rung": (total, fast),
                "quality": (q_total, q_good),
            }
            for objective in OBJECTIVES:
                obj_total, obj_good = counts[objective]
                if obj_total == 0:
                    burn = 0.0
                else:
                    bad_frac = 1.0 - obj_good / obj_total
                    burn = bad_frac / (1.0 - self.targets[objective])
                out[objective][label] = round(burn, 4)
        return out

    def export(self) -> dict:
        """Compute burn rates, push the ``knn_slo_*`` gauges into the
        global registry (no-ops while obs is disabled), and return the
        summary dict ``/healthz`` embeds."""
        burns = self.burn_rates()
        for objective, per_window in burns.items():
            obs.gauge_set(
                "knn_slo_target", self.targets[objective],
                help="SLO objective target (good-event fraction)",
                objective=objective,
            )
            for label, burn in per_window.items():
                obs.gauge_set(
                    "knn_slo_burn_rate", burn,
                    help="error-budget burn rate (bad fraction / budget; "
                         "1.0 = on budget, >1 = burning faster)",
                    objective=objective, window=label,
                )
        obs.gauge_set(
            "knn_slo_latency_target_ms", self.latency_target_ms,
            help="latency SLO threshold (ms)",
        )
        policy_sheds = {
            window_label(w): int(self._shed_ring.window_sums(w)[0])
            for w in self.windows_s
        }
        return {
            "targets": dict(self.targets),
            "latency_target_ms": self.latency_target_ms,
            "windows": [window_label(w) for w in self.windows_s],
            "burn_rates": burns,
            "policy_sheds": policy_sheds,
        }
