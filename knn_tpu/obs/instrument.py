"""Instrumentation weave: the helpers that put spans + metrics into the
model layer, the backends, and the sharded paths.

Backend instrumentation happens at the registry (``backends/__init__.py``
wraps every registered predict fn with :func:`observed_backend`), so every
backend — including the sharded ones — uniformly reports:

- ``knn_predict_calls_total{backend=...}``   calls through the registry
- ``knn_queries_total{backend=...}``         query rows classified
- ``knn_predict_wall_ms{backend=...}``       per-call wall histogram
- ``knn_predict_qps{backend=...}``           last call's queries/s gauge
- ``knn_first_call_wall_ms{backend=...}``    first-call wall (compile +
  dispatch upper bound — XLA compiles on first dispatch, so this is the
  honest "compile ms" a host-side tracer can report without jax internals)

plus a ``predict`` span wrapping the call. The collective-traffic helpers
turn ``parallel/comm_audit.py``'s analytic byte model into live counters
(``knn_collective_bytes_total{path=...,op=...}``): the sharded predict
entries compute the model bytes for the call they are about to dispatch
and record them here, so the static StableHLO audit and the runtime
counter can be cross-checked for exact equality (tests/test_obs.py).

The ``record_serve_*`` helpers are the serving subsystem's instrument set
(``knn_tpu/serve/`` — docs/SERVING.md): admission counters
(``knn_serve_requests_total`` / ``knn_serve_rejected_total`` /
``knn_serve_deadline_expired_total``), per-batch coalescing histograms
(``knn_serve_batch_size`` in requests, ``knn_serve_batch_rows`` in rows,
``knn_serve_dispatch_ms``), and per-request latency
(``knn_serve_queue_wait_ms``, ``knn_serve_request_ms``).

Everything here is a no-op while ``knn_tpu.obs`` is disabled.
"""

from __future__ import annotations

import functools
import threading
import time

from knn_tpu import obs

# Wall-time histogram ladder for predict calls: sub-ms cached dispatches
# through multi-minute first-call compiles.
PREDICT_MS_BUCKETS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
    5000.0, 15000.0, 60000.0,
)

_first_call_lock = threading.Lock()
_first_call_seen = set()


def observed_backend(name: str, fn):
    """Wrap a backend predict fn with a span + the per-backend metrics."""

    @functools.wraps(fn)
    def wrapped(train, test, k, *args, **kwargs):
        if not obs.enabled():
            return fn(train, test, k, *args, **kwargs)
        q = getattr(test, "num_instances", None)
        t0 = time.monotonic()
        with obs.span("predict", backend=name, k=k):
            out = fn(train, test, k, *args, **kwargs)
        wall_ms = (time.monotonic() - t0) * 1e3
        with _first_call_lock:
            first = name not in _first_call_seen
            _first_call_seen.add(name)
        obs.counter_add(
            "knn_predict_calls_total", 1,
            help="predict calls through the backend registry", backend=name,
        )
        if first:
            obs.gauge_set(
                "knn_first_call_wall_ms", round(wall_ms, 3),
                help="first predict call wall ms (compile + dispatch upper "
                     "bound)", backend=name,
            )
        else:
            obs.histogram_observe(
                "knn_predict_wall_ms", wall_ms, buckets=PREDICT_MS_BUCKETS,
                help="predict call wall ms (post-first-call)", backend=name,
            )
        if q:
            obs.counter_add(
                "knn_queries_total", int(q),
                help="query rows classified", backend=name,
            )
            if wall_ms > 0:
                obs.gauge_set(
                    "knn_predict_qps", round(q / (wall_ms / 1e3), 1),
                    help="last predict call's steady-state queries/s",
                    backend=name,
                )
        return out

    wrapped.__wrapped_backend__ = fn
    return wrapped


# Serving-path instrument ladders (knn_tpu/serve/). Request/queue/dispatch
# latencies live in low single-digit ms when batching works and in the
# hundreds when it doesn't, so the ladder starts below the default's floor.
SERVE_MS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 10000.0,
)
# Coalesced requests (and rows) per dispatched batch: powers of two up to
# far past any sane max_batch.
SERVE_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                       512.0, 1024.0)


def record_serve_request(kind: str, rows: int) -> None:
    """Count an ADMITTED serving request (rejected ones go to
    :func:`record_serve_rejected` instead)."""
    obs.counter_add(
        "knn_serve_requests_total", 1,
        help="serving requests admitted to the micro-batch queue", kind=kind,
    )
    obs.counter_add(
        "knn_serve_rows_total", int(rows),
        help="query rows admitted to the micro-batch queue", kind=kind,
    )


def record_serve_rejected(reason: str) -> None:
    obs.counter_add(
        "knn_serve_rejected_total", 1,
        help="serving requests refused by admission control (HTTP 429)",
        reason=reason,
    )


def record_serve_deadline_expired() -> None:
    obs.counter_add(
        "knn_serve_deadline_expired_total", 1,
        help="serving requests whose deadline expired while queued "
             "(HTTP 504)",
    )


def record_serve_queue_wait(ms: float, kind: str) -> None:
    obs.histogram_observe(
        "knn_serve_queue_wait_ms", ms, buckets=SERVE_MS_BUCKETS,
        help="per-request wait from enqueue to batch close", kind=kind,
    )


def record_serve_topup(rows: int) -> None:
    """Continuous batching admitted ``rows`` into an already-closed batch
    below its bucket boundary (free rows — the compiled shape the batch
    pads to is unchanged; serve/batcher.py::_admit_topup)."""
    obs.counter_add(
        "knn_serve_topup_rows_total", int(rows),
        help="query rows admitted into a closed batch up to its bucket "
             "boundary (continuous batching; they paid no extra wait "
             "window and no extra compiled rows)",
    )


def record_serve_batch(requests: int, rows: int, dispatch_ms: float,
                       padded_rows: "int | None" = None) -> None:
    """Record one dispatched micro-batch. ``knn_serve_batch_size`` counts
    REQUESTS coalesced per dispatch — the number whose histogram exceeding
    1 is the measured proof that dynamic batching engages (pinned by
    tests/test_serve.py); ``knn_serve_batch_rows`` counts actual query
    rows, ``knn_serve_batch_padded_rows`` the compiled-shape rows the
    engine really swept (XLA pads queries to 128, stripe to its block
    grid) — the gap between the two histograms IS the padding waste."""
    obs.histogram_observe(
        "knn_serve_batch_size", requests, buckets=SERVE_BATCH_BUCKETS,
        help="requests coalesced per dispatched micro-batch",
    )
    obs.histogram_observe(
        "knn_serve_batch_rows", rows, buckets=SERVE_BATCH_BUCKETS,
        help="query rows per dispatched micro-batch",
    )
    if padded_rows is not None:
        # The histogram stays UNLABELED (pre-ladder dashboards keep
        # reading the same series); the per-bucket dispatch counts live
        # on a dedicated counter whose `bucket` label names the compiled
        # shape — cardinality bounded by the ladder length plus the
        # (rare) chunked-dispatch sums.
        obs.histogram_observe(
            "knn_serve_batch_padded_rows", padded_rows,
            buckets=SERVE_BATCH_BUCKETS,
            help="compiled-shape query rows per dispatched micro-batch "
                 "(actual rows + the padding the dispatched bucket or "
                 "shape quantum forced)",
        )
        obs.counter_add(
            "knn_serve_bucket_dispatch_total", 1,
            help="micro-batch dispatches per compiled bucket shape "
                 "(which --batch-buckets rungs the traffic actually "
                 "exercises)",
            bucket=int(padded_rows),
        )
    obs.histogram_observe(
        "knn_serve_dispatch_ms", dispatch_ms, buckets=SERVE_MS_BUCKETS,
        help="engine dispatch wall ms per micro-batch (kneighbors + "
             "scatter)",
    )


def record_serve_request_done(kind: str, outcome: str, ms: float,
                              trace_id: "str | None" = None) -> None:
    """One terminal serving outcome. ``trace_id`` (the request's id when
    request tracing is on) rides the latency histogram as an OpenMetrics
    exemplar, so a slow bucket links straight to its ``/debug/requests``
    timeline."""
    obs.counter_add(
        "knn_serve_responses_total", 1,
        help="serving requests completed, by outcome", kind=kind,
        outcome=outcome,
    )
    obs.histogram_observe(
        "knn_serve_request_ms", ms, buckets=SERVE_MS_BUCKETS,
        help="per-request latency from enqueue to completion", kind=kind,
        outcome=outcome,
        exemplar={"trace_id": trace_id} if trace_id else None,
    )


def record_transfer(nbytes: int, direction: str = "h2d",
                    backend: str = "tpu") -> None:
    """Count host<->device payload bytes (the arrays a predict call moves)."""
    if nbytes:
        obs.counter_add(
            "knn_transfer_bytes_total", int(nbytes),
            help="host<->device payload bytes moved by predict calls",
            direction=direction, backend=backend,
        )


def record_shard_dispatch(path: str, t0_monotonic: float) -> None:
    """Record this process's dispatch->fetch wall for one sharded predict
    (``knn_shard_dispatch_ms{path=...}``, last call wins). THE per-process
    straggler signal: obs/aggregate.py collects this gauge across the
    fleet's registry snapshots and derives
    ``knn_shard_dispatch_ms_max/min`` and the skew ratio on process 0."""
    if obs.enabled():
        obs.gauge_set(
            "knn_shard_dispatch_ms",
            round((time.monotonic() - t0_monotonic) * 1e3, 3),
            help="this process's last sharded dispatch->fetch wall ms "
                 "(the fleet straggler signal — obs/aggregate.py)",
            path=path,
        )


def record_shard_wall(path: str, shard: int, wall_ms: float) -> None:
    """One logical shard's dispatch->resolve wall within a sharded serve
    process (``knn_shard_dispatch_ms{path=..., shard=N}``, last call
    wins) — the in-process twin of :func:`record_shard_dispatch`'s
    per-process gauge. ``obs/aggregate.local_straggler_gauges`` derives
    the same ``knn_shard_dispatch_ms_max/min`` + skew family from these
    walls that the fleet path derives from merged snapshots."""
    if obs.enabled():
        obs.gauge_set(
            "knn_shard_dispatch_ms", round(wall_ms, 3),
            help="this process's last sharded dispatch->fetch wall ms "
                 "(the fleet straggler signal — obs/aggregate.py)",
            path=path, shard=str(shard),
        )


def record_shard_candidates(path: str, shard: int, rows: int,
                            nbytes: int) -> None:
    """Per-shard candidate/byte counters for one sharded dispatch
    (``knn_shard_candidates_total`` / ``knn_shard_bytes_total``): how
    many survivor candidate rows each shard contributed to the
    cross-shard merge and the host bytes those survivors carried —
    the imbalance signal /debug/capacity's shard block surfaces beside
    the dispatch-wall skew."""
    if not obs.enabled():
        return
    obs.counter_add(
        "knn_shard_candidates_total", int(rows),
        help="survivor candidate rows contributed to cross-shard merges "
             "per shard", path=path, shard=str(shard),
    )
    obs.counter_add(
        "knn_shard_bytes_total", int(nbytes),
        help="host bytes of per-shard survivor candidates merged "
             "cross-shard", path=path, shard=str(shard),
    )


def record_collective(path: str, op: str, nbytes: int) -> None:
    """Count modeled collective-traffic bytes for one sharded predict call.

    ``nbytes`` must come from the matching ``parallel/comm_audit.py`` model
    fn (``model_train_sharded_bytes`` / ``model_ring_bytes`` /
    ``model_query_sharded_bytes``) so the runtime counter and the static
    lowering audit agree exactly.
    """
    if nbytes:
        obs.counter_add(
            "knn_collective_bytes_total", int(nbytes),
            help="modeled collective payload bytes on the sharded paths "
                 "(comm_audit byte model)", path=path, op=op,
        )
    obs.counter_add(
        "knn_collective_calls_total", 1,
        help="sharded predict dispatches", path=path, op=op,
    )
