"""Shape padding helpers.

XLA wants static, evenly-divisible shapes; the reference instead handles ragged
work with variable per-worker counts (`MPI_Gatherv`, mpi.cpp:177-186; remainder
rows to the last pthread, multi-thread.cpp:154-161). We pad + mask instead
(SURVEY.md §5.8): padded train rows get +inf distance so they can never enter
the candidate set (the same role as the reference's FLT_MAX init, main.cpp:33),
and padded query rows are sliced off the output.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def pad_axis_to_multiple(
    arr: np.ndarray, multiple: int, axis: int = 0, value: float = 0.0
) -> Tuple[np.ndarray, int]:
    """Pad ``arr`` along ``axis`` up to the next multiple. Returns (padded,
    original_size)."""
    n = arr.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - n)
    return np.pad(arr, widths, constant_values=value), n


def pad_axis_to_size(
    arr: np.ndarray, size: int, axis: int = 0, value: float = 0.0
) -> np.ndarray:
    """Pad ``arr`` along ``axis`` up to an EXACT target size (the
    bucket-ladder pad, ``models/knn.query_padded_rows``): unlike
    :func:`pad_axis_to_multiple` the target is a resolved shape, not a
    quantum. ``size`` below the current extent raises — truncation would
    silently drop query rows."""
    n = arr.shape[axis]
    if size < n:
        raise ValueError(f"pad target {size} below current size {n}")
    if size == n:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, size - n)
    return np.pad(arr, widths, constant_values=value)
