"""Windowed device dispatch — THE host-side streaming idiom.

Every chunked predict/retrieval entry (stripe candidates, stripe classify,
the XLA query-batched backend) streams fixed-shape chunks through the
device with a small in-flight window: enough dispatches to keep the device
pipeline full, few enough that only ``window`` chunks' inputs/outputs are
resident at once (the query set may exceed HBM; fetching a result retires
its buffers). One definition so the tuning that matters lives in one place:

- Each chunk's device->host copy starts ASYNCHRONOUSLY at dispatch time.
  On a tunneled device a blocking fetch pays a full ~100 ms round trip no
  matter how the dispatches pipeline (measured r4: many small chunks each
  fetched synchronously turned a 110k-query retrieval into 246 serial
  round trips — 27 s of wall for ~60 ms of device compute); with the copy
  already in flight the drain finds the bytes landed.
- Callers should pad ragged last chunks up to the shared chunk shape so
  one compiled executable serves every dispatch.
"""

from __future__ import annotations

from typing import Callable, Iterable, List


def windowed_dispatch_deferred(
    items: Iterable,
    dispatch: Callable,
    fetch: Callable,
    window: int = 4,
) -> Callable[[], List]:
    """Dispatch every item NOW (async host copies started immediately) and
    return a ``resolve()`` callable that drains the remaining fetches and
    returns the result list. Items beyond ``window`` still drain eagerly
    during dispatch, so in-flight residency keeps the same bound as the
    synchronous path; the deferral buys overlap for the common small-call
    case (one or two chunks) and for cross-call pipelining — M deferred
    calls resolved together pay ~one device->host round trip instead of M
    (the ~100 ms tunnel sync floor, VERDICT r4 #6)."""
    import jax

    pending: list = []
    results: list = []

    def drain_one():
        out, item = pending.pop(0)
        results.append(fetch(out, item))

    for item in items:
        out = dispatch(item)
        for leaf in jax.tree_util.tree_leaves(out):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        pending.append((out, item))
        if len(pending) > window:
            drain_one()

    def resolve():
        while pending:
            drain_one()
        return results

    return resolve


def windowed_dispatch(
    items: Iterable,
    dispatch: Callable,
    fetch: Callable,
    window: int = 4,
) -> List:
    """``[fetch(dispatch(item), item) for item in items]`` with a bounded
    number of dispatched results in flight (``window + 1``, matching the
    original inline loops: draining starts once the window is exceeded)
    and async host copies started at dispatch time. ``dispatch(item)``
    returns a device array or tuple/list of device arrays; ``fetch(out,
    item)`` converts one result to its host form (and is where padding is
    trimmed)."""
    return windowed_dispatch_deferred(items, dispatch, fetch, window)()
