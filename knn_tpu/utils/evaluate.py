"""Evaluation layer (reference L4).

``confusion_matrix``: row = true class, column = predicted class, sized by the
*test* set's num_classes, exactly as main.cpp:87-100. ``accuracy`` =
trace / total (main.cpp:102-112).
"""

from __future__ import annotations

import numpy as np


def confusion_matrix(predictions: np.ndarray, true_labels: np.ndarray, num_classes: int) -> np.ndarray:
    # The reference sizes the matrix by the *test* set's num_classes
    # (main.cpp:89) — UB when a prediction (drawn from train labels) exceeds
    # it. We grow the matrix instead of crashing; accuracy (trace/total) is
    # unaffected for in-range entries.
    if predictions.size:
        num_classes = max(num_classes, int(predictions.max()) + 1,
                          int(true_labels.max()) + 1)
    cm = np.zeros((num_classes, num_classes), dtype=np.int64)
    np.add.at(cm, (true_labels.astype(np.int64), predictions.astype(np.int64)), 1)
    return cm


def accuracy(cm: np.ndarray) -> float:
    total = cm.sum()
    if total == 0:
        return 0.0
    return float(np.trace(cm)) / float(total)
