from knn_tpu.utils.padding import pad_axis_to_multiple
from knn_tpu.utils.evaluate import confusion_matrix, accuracy
from knn_tpu.utils.timing import RegionTimer
from knn_tpu.utils.cli_format import result_line

__all__ = [
    "pad_axis_to_multiple",
    "confusion_matrix",
    "accuracy",
    "RegionTimer",
    "result_line",
]
