"""Region timing, mirroring the reference's CLOCK_MONOTONIC_RAW pair around the
KNN region only — parsing excluded (main.cpp:133-137). Also exposes an opt-in
``jax.profiler`` trace for TPU runs (SURVEY.md §5.1)."""

from __future__ import annotations

import contextlib
import time
from typing import Optional


class RegionTimer:
    """``with RegionTimer() as t: ...`` then ``t.ms`` (integer ms, matching the
    reference's ns→ms integer division, main.cpp:144)."""

    def __enter__(self):
        self._start = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        self._end = time.monotonic_ns()
        return False

    @property
    def ns(self) -> int:
        return self._end - self._start

    @property
    def ms(self) -> int:
        return self.ns // 1_000_000


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when ``trace_dir`` is set."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
