"""Region timing, mirroring the reference's CLOCK_MONOTONIC_RAW pair around the
KNN region only — parsing excluded (main.cpp:133-137). Also exposes an opt-in
``jax.profiler`` trace for TPU runs (SURVEY.md §5.1). Fine-grained phase
timing lives in :mod:`knn_tpu.obs` — this module keeps only the headline
region clock the reference-parity result line reports."""

from __future__ import annotations

import contextlib
import os
import tempfile
import time
from typing import Optional


def ensure_writable_dir(d: str, create: bool = False) -> None:
    """Raise OSError when directory ``d`` is missing (unless ``create``) or
    not writable. The probe file gets a per-process unique name (tempfile)
    so concurrent probers of one directory cannot race each other's
    cleanup. ONE definition — shared by :func:`maybe_profile` and
    ``knn_tpu/obs/export.py::check_parent_dir``."""
    if create:
        os.makedirs(d, exist_ok=True)
    elif not os.path.isdir(d):
        raise OSError(f"directory does not exist: {d!r}")
    with tempfile.NamedTemporaryFile(
        dir=d, prefix=".knn_tpu_write_probe_"
    ):
        pass


class RegionTimer:
    """``with RegionTimer() as t: ...`` then ``t.ms`` (integer ms, matching the
    reference's ns→ms integer division, main.cpp:144)."""

    def __init__(self):
        self._start: Optional[int] = None
        self._end: Optional[int] = None

    def __enter__(self):
        self._end = None  # a reused timer must not expose a stale region
        self._start = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        self._end = time.monotonic_ns()
        return False

    @property
    def ns(self) -> int:
        if self._start is None or self._end is None:
            raise RuntimeError(
                "RegionTimer region not finished: read .ns/.ms after the "
                "`with RegionTimer() as t:` block exits"
            )
        return self._end - self._start

    @property
    def ms(self) -> int:
        return self.ns // 1_000_000


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when ``trace_dir`` is set.

    The directory is validated/created UP FRONT so an unwritable path fails
    before the region runs (as a ``ValueError`` with a clear message — the
    CLI's clean-error contract) instead of discarding the computed region
    in the profiler's teardown."""
    if not trace_dir:
        yield
        return
    try:
        ensure_writable_dir(trace_dir, create=True)
    except OSError as e:
        raise ValueError(f"--trace-dir {trace_dir!r} is not writable: {e}")
    import jax

    with jax.profiler.trace(trace_dir):
        yield
