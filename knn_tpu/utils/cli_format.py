"""Canonical output line — byte-compatible with the reference's printf
(main.cpp:146, multi-thread.cpp:203, mpi.cpp:198):

  "The %i-NN classifier for %lu test instances on %lu train instances
   required %llu ms CPU time. Accuracy was %.4f\\n"

plus an opt-in structured JSON form (SURVEY.md §5.5).
"""

from __future__ import annotations

import json


def result_line(k: int, num_test: int, num_train: int, ms: int, acc: float) -> str:
    return (
        f"The {k}-NN classifier for {num_test} test instances on {num_train} "
        f"train instances required {ms} ms CPU time. Accuracy was {acc:.4f}"
    )


def result_json(k: int, num_test: int, num_train: int, ms: int, acc: float,
                backend: str) -> str:
    return json.dumps(
        {
            "k": k,
            "num_test": num_test,
            "num_train": num_train,
            "ms": ms,
            "accuracy": round(acc, 6),
            "backend": backend,
        }
    )
