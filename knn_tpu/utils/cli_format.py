"""Canonical output line — byte-compatible with the reference's printf
(main.cpp:146, multi-thread.cpp:203, mpi.cpp:198):

  "The %i-NN classifier for %lu test instances on %lu train instances
   required %llu ms CPU time. Accuracy was %.4f\\n"

plus an opt-in structured JSON form (SURVEY.md §5.5).
"""

from __future__ import annotations

import json


def result_line(k: int, num_test: int, num_train: int, ms: int, acc: float) -> str:
    return (
        f"The {k}-NN classifier for {num_test} test instances on {num_train} "
        f"train instances required {ms} ms CPU time. Accuracy was {acc:.4f}"
    )


def result_json(k: int, num_test: int, num_train: int, ms: int, acc: float,
                backend: str, phases: "dict | None" = None) -> str:
    """``phases`` (present when the obs tracer is on) carries the per-phase
    span totals of the timed region in milliseconds — the same numbers
    ``--metrics-out`` writes under ``"phases"``, so the two artifacts can
    be cross-checked (tests/test_obs.py)."""
    rec = {
        "k": k,
        "num_test": num_test,
        "num_train": num_train,
        "ms": ms,
        "accuracy": round(acc, 6),
        "backend": backend,
    }
    if phases is not None:
        rec["phases"] = phases
    return json.dumps(rec)
