// Native ARFF ingest library.
//
// Re-implements the role of the reference's libarff (arff_parser.h:18,
// arff_lexer.h:20, arff_scanner.h:22) with a TPU-era design: instead of a
// char-at-a-time fread scanner (arff_scanner.cpp:46) feeding a
// pointer-per-scalar object graph (ArffValue, arff_value.h:45), the whole file
// is read in one shot and parsed straight into dense float32 [N, D-1] features
// + int32 labels — the exact layout the device wants, zero intermediate
// objects.
//
// Dialect parity with the reference (SURVEY.md §3.4): '%' comment lines,
// case-insensitive keywords, NUMERIC/REAL/INTEGER/STRING/DATE/{nominal}
// attribute types, single/double-quoted values, '?' missing -> NaN, rows may
// span physical lines (the token-stream reader consumes exactly
// num_attributes values per instance, arff_parser.cpp:121-153), a partial row
// at EOF is discarded, sparse rows are rejected. STRING/DATE data cells
// intern to first-seen float32 codes (tables exported per attribute).
// A quoted value may span physical lines, preserving the newline(s) inside
// the value (the reference's _read_str reads through newlines,
// arff_lexer.cpp:159-188), and an open '{' nominal list continues on the
// following line(s) — newlines are ordinary inter-token whitespace to the
// reference lexer. Errors carry file:line context like libarff's THROW
// (arff_utils.cpp:8-20), citing the token's own line for multi-line rows.
//
// C ABI only — bound from Python via ctypes (no pybind11 in this image).

#include <array>
#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cerrno>
#include <cstring>
#include <sys/mman.h>
#include <sys/stat.h>

#include <memory>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

enum TypeCode { TC_NUMERIC = 0, TC_NOMINAL = 1, TC_STRING = 2, TC_DATE = 3 };

struct Attr {
  std::string name;
  std::string type;  // "numeric" | "string" | "date" | "nominal"
  // The same fact as an enum: cell_view_to_float runs per CELL and three
  // std::string comparisons there were a measurable slice of ingest.
  int type_code = TC_NUMERIC;
  std::vector<std::string> nominal;
  // STRING/DATE cell interning (first-seen order): the dense matrix stores
  // the code, `interned` is the code->value table. The reference keeps heap
  // strings per cell (arff_value.cpp:33-48) and only fails when its KNN
  // kernel reads one as float (arff_value.cpp:121) — so these files LOAD
  // there and must load here; the numeric-only check moves to predict time.
  std::vector<std::string> interned;
  std::unordered_map<std::string, int> intern_idx;
};

struct ParseState {
  std::string path;
  std::string relation;
  std::vector<Attr> attrs;
  std::vector<float> cells;  // row-major, attrs.size() per row
  std::string error;
  int line = 0;
};

bool ieq(const std::string& a, const char* b) {
  if (a.size() != strlen(b)) return false;
  for (size_t i = 0; i < a.size(); ++i)
    if (tolower((unsigned char)a[i]) != tolower((unsigned char)b[i])) return false;
  return true;
}

void fail(ParseState& st, const std::string& msg) {
  if (st.error.empty())
    st.error = st.path + ":" + std::to_string(st.line) + ": " + msg;
}

std::string strip(const std::string& s) {
  size_t b = s.find_first_not_of(" \t\r\n");
  if (b == std::string::npos) return "";
  size_t e = s.find_last_not_of(" \t\r\n");
  return s.substr(b, e - b + 1);
}

// Fold quote state over `s`: returns the open quote char if the text ends
// inside a quoted value, else 0. The carry for multi-line quoted values
// (arff_lexer.cpp:159-188 reads through newlines to the matching quote).
char scan_quote(const std::string& s, char quote = 0) {
  for (char ch : s) {
    if (quote) {
      if (ch == quote) quote = 0;
    } else if (ch == '\'' || ch == '"') {
      quote = ch;
    }
  }
  return quote;
}

// True when `rest` opens a '{' nominal list (outside quotes) that no later
// unquoted '}' closes — the declaration continues on the next physical
// line, as in the reference's token-stream reader (newlines are ordinary
// whitespace between tokens, arff_lexer.cpp:93-97).
bool open_nominal(const std::string& rest) {
  char quote = 0;
  bool opened = false;
  for (char ch : rest) {
    if (quote) {
      if (ch == quote) quote = 0;
    } else if (ch == '\'' || ch == '"') {
      quote = ch;
    } else if (ch == '{') {
      opened = true;
    } else if (ch == '}' && opened) {
      return false;
    }
  }
  return opened;
}

// Tokenize a data/nominal segment the way the reference lexer does:
// unquoted whitespace and commas BOTH end a token (next_token skips
// whitespace between tokens, arff_lexer.cpp:93-97; a comma terminates
// _read_str, :190), so "1 2" and "1,2" are the same two tokens and several
// rows may share one physical line. Quoted content is preserved verbatim.
// A comma with no token since the previous comma yields an empty cell,
// which callers reject — the reference silently truncates the dataset
// there (arff_lexer.cpp:125-127), a defect replaced with a located error.
// A comma directly after its token is that token's terminator, so a single
// trailing comma is absorbed ("1,2," tokenizes like "1,2").
bool split_csv(const std::string& line, std::vector<std::string>& out,
               ParseState& st) {
  out.clear();
  std::string buf;
  bool active = false;             // a token is in progress
  bool token_since_comma = false;  // a completed token awaits its comma
  char quote = 0;
  auto flush = [&]() {
    out.push_back(buf);
    buf.clear();
    active = false;
    token_since_comma = true;
  };
  for (char ch : line) {
    if (quote) {
      if (ch == quote) {
        quote = 0;
      } else {
        buf.push_back(ch);
      }
      continue;
    }
    if (ch == '\'' || ch == '"') {
      quote = ch;
      active = true;
      continue;
    }
    if (ch == ' ' || ch == '\t') {
      if (active) flush();
      continue;
    }
    if (ch == ',') {
      if (active) {
        flush();
        token_since_comma = false;  // comma terminated its own token
      } else if (token_since_comma) {
        token_since_comma = false;  // separator for the flushed token
      } else {
        out.push_back("");  // ",," or leading comma: empty cell
      }
      continue;
    }
    active = true;
    buf.push_back(ch);
  }
  if (quote) {
    fail(st, "unterminated quoted value");
    return false;
  }
  if (active) flush();
  return true;
}

bool parse_attribute(const std::string& rest_in, ParseState& st) {
  std::string rest = strip(rest_in);
  if (rest.empty()) {
    fail(st, "@attribute needs a name and a type");
    return false;
  }
  Attr attr;
  if (rest[0] == '\'' || rest[0] == '"') {
    char q = rest[0];
    size_t end = rest.find(q, 1);
    if (end == std::string::npos) {
      fail(st, "unterminated quoted attribute name");
      return false;
    }
    attr.name = rest.substr(1, end - 1);
    rest = strip(rest.substr(end + 1));
  } else {
    size_t sp = rest.find_first_of(" \t");
    if (sp == std::string::npos) {
      fail(st, "@attribute '" + rest + "' is missing a type");
      return false;
    }
    attr.name = rest.substr(0, sp);
    rest = strip(rest.substr(sp));
  }
  if (rest.empty()) {
    fail(st, "@attribute '" + attr.name + "' is missing a type");
    return false;
  }
  if (rest[0] == '{') {
    if (rest.back() != '}') {
      fail(st, "unterminated nominal value list");
      return false;
    }
    attr.type = "nominal";
    attr.type_code = TC_NOMINAL;
    std::string inner = rest.substr(1, rest.size() - 2);
    std::vector<std::string> vals;
    // "{a,b,}" is reference-valid: the comma before "}" is consumed as the
    // previous token's terminator (arff_lexer.cpp:190, then next_token's
    // unconditional advance) and "}" lexes as BRKT_CLOSE. Only a literal
    // trailing comma is absorbed — a quoted-empty final value ({a,''})
    // still hits the empty-value error below. "{}" is an empty nominal set
    // (reference: BRKT_CLOSE immediately ends the value loop).
    if (!strip(inner).empty()) {
      if (!split_csv(inner, vals, st)) return false;
      for (const std::string& v : vals)
        if (v.empty()) {
          fail(st, "empty value in nominal list");
          return false;
        }
    }
    attr.nominal = vals;
  } else {
    size_t sp = rest.find_first_of(" \t");
    std::string word = sp == std::string::npos ? rest : rest.substr(0, sp);
    if (ieq(word, "numeric") || ieq(word, "real") || ieq(word, "integer")) {
      attr.type = "numeric";
      attr.type_code = TC_NUMERIC;
    } else if (ieq(word, "string")) {
      attr.type = "string";
      attr.type_code = TC_STRING;
    } else if (ieq(word, "date")) {
      attr.type = "date";
      attr.type_code = TC_DATE;
    } else {
      fail(st, "unsupported attribute type '" + rest + "'");
      return false;
    }
  }
  st.attrs.push_back(std::move(attr));
  return true;
}

bool cell_view_to_float(const char* p, size_t len, Attr& attr, float* out,
                        ParseState& st) {
  if (len == 1 && p[0] == '?') {
    *out = NAN;
    return true;
  }
  if (attr.type_code == TC_NUMERIC) {
    // Fastest path: plain short decimals ([-]D*.D*, <= 8 digits, no
    // exponent) — the overwhelming cell shape in numeric ARFF. With
    // mantissa m < 2^24 and frac <= 10, float(m) and float(10^frac) are
    // both EXACT (5^10 < 2^24), so one correctly-rounded float division
    // computes the correctly rounded value of the decimal itself —
    // bit-identical to strtof/from_chars at ~3x the speed. Anything else
    // (longer, exponents, inf/nan, signs beyond '-') falls through.
    {
      static const float kP10[11] = {1e0f, 1e1f, 1e2f, 1e3f, 1e4f, 1e5f,
                                     1e6f, 1e7f, 1e8f, 1e9f, 1e10f};
      const char* c = p;
      const char* e = p + len;
      bool neg = c < e && *c == '-';
      if (neg) c++;
      uint32_t m = 0;
      int ndig = 0, frac = 0;
      bool seen_dot = false, simple = c < e;
      while (c < e) {
        char ch = *c;
        if (ch >= '0' && ch <= '9') {
          m = m * 10u + (uint32_t)(ch - '0');
          if (++ndig > 8) { simple = false; break; }
          if (seen_dot) frac++;
        } else if (ch == '.' && !seen_dot) {
          seen_dot = true;
        } else {
          simple = false;
          break;
        }
        c++;
      }
      if (simple && ndig >= 1 && m < (1u << 24) && frac <= 10) {
        float v = (float)m / kP10[frac];
        *out = neg ? -v : v;
        return true;
      }
    }
    // General path: from_chars — no allocation, no locale. It must consume
    // the ENTIRE token (same acceptance rule as the old strtof+endp
    // check). The fallback keeps strtof's wider acceptance — leading '+',
    // hex floats, inf/nan spellings, and over/underflow (from_chars
    // reports out_of_range, strtof clamps and accepts) — so the dialect
    // is unchanged.
#if defined(__cpp_lib_to_chars)
    // libstdc++ < 11 declares only the integer overloads; the strtof
    // fallback below is the whole general path there.
    auto res = std::from_chars(p, p + len, *out);
    if (res.ec == std::errc() && res.ptr == p + len) return true;
#endif
    std::string tok(p, len);
    char* endp = nullptr;
    *out = strtof(tok.c_str(), &endp);
    if (len == 0 || endp != tok.c_str() + tok.size()) {
      fail(st, "cannot parse '" + tok + "' as a number for '" + attr.name + "'");
      return false;
    }
    return true;
  }
  if (attr.type_code == TC_NOMINAL) {
    for (size_t i = 0; i < attr.nominal.size(); ++i)
      if (attr.nominal[i].size() == len &&
          memcmp(attr.nominal[i].data(), p, len) == 0) {
        *out = (float)i;
        return true;
      }
    fail(st, "value '" + std::string(p, len) + "' not in nominal set for '" +
             attr.name + "'");
    return false;
  }
  // TC_STRING / TC_DATE: intern in first-seen order.
  std::string tok(p, len);
  auto ins = attr.intern_idx.emplace(tok, (int)attr.interned.size());
  if (ins.second) attr.interned.push_back(tok);
  *out = (float)ins.first->second;
  return true;
}

// The seven structural bytes of the @data tokenizer; everything else is an
// ordinary token byte the run scan consumes without per-byte dispatch.
static const std::array<bool, 256> kStructural = [] {
  std::array<bool, 256> t{};
  for (unsigned char c : {' ', '\t', ',', '\n', '\r', '\'', '"'}) t[c] = true;
  return t;
}();

// Streaming zero-copy scanner for the @data section — the ingest hot path.
//
// One pass over the raw buffer: tokens are (offset, length) views into it
// (ARFF has no escape syntax, so even quoted content is a contiguous slice);
// only quote-spliced composites like ab'cd'ef fall back to a scratch string.
// Tokens buffer per ROW (views + their line numbers) and convert to float
// when the row completes — preserving the reference reader's exact behavior
// (arff_parser.cpp:121-153): rows span/share physical lines, a partial row
// at EOF is DISCARDED UNCONVERTED (a malformed value there must not error),
// while empty cells error at scan time like the per-line validation did.
//
// Tokenization semantics are split_csv's, verbatim: unquoted whitespace and
// commas both terminate tokens, a comma directly after its token is that
// token's terminator (so one trailing comma per line is absorbed and the
// comma-state resets per line), ",," or a leading comma is an empty cell,
// '%' comments only at the true line start, a first non-ws '{' is a sparse
// row, '\r' is a token character unless it belongs to line-trailing
// whitespace, a quoted value reads through newlines to its closing quote.
//
// EAGER mode (all-numeric headers only): each token converts the moment it
// closes, skipping the per-row Tok buffering entirely. The deferred-error
// dance preserves the discard rule exactly: a conversion failure stashes
// its message and only surfaces if that row COMPLETES (a malformed value
// in the final partial row must not error); at EOF the partial row's
// already-pushed cells are truncated away. Numeric conversion has no side
// effects, so eager conversion of a to-be-discarded partial row is
// invisible — which is exactly why interning types (STRING/DATE) must take
// the buffered path instead.
template <bool EAGER>
bool parse_data_stream_impl(std::string_view data, size_t pos,
                            ParseState& st) {
  const char* s = data.data();
  const size_t N = data.size();
  const size_t d = st.attrs.size();
  if (N > UINT32_MAX) {
    // Token views store 32-bit offsets; refuse cleanly rather than let a
    // >= 4 GiB buffer wrap them into silently corrupt cells.
    fail(st, "file exceeds the 4 GiB parser limit");
    return false;
  }

  struct Tok {
    uint32_t off, len;  // view into `data` when owned < 0
    int32_t line;
    int32_t owned;  // index into `owned` for composite tokens, else -1
  };
  // `row` and `convert_row` serve only the buffered (!EAGER) instantiation;
  // every use sits behind the EAGER branches, so the eager binary carries
  // no Tok traffic (the compiler strips the dead lambda).
  std::vector<Tok> row;      // tokens of the row in progress
  std::vector<std::string> owned;
  if constexpr (!EAGER) row.reserve(d);
  // One up-front reservation keeps the hot push_back from ever
  // reallocating. Estimate rows from the line density of a 64 KB sample
  // instead of a blind bytes/3 guess: at 90 MB the blind guess
  // over-reserved ~60%, and the first-touch page faults on the unused
  // tail were a measurable slice of large-file ingest.
  {
    size_t span = N - pos;
    size_t sample = span < (64u << 10) ? span : (64u << 10);
    size_t nl = 0;
    for (size_t i = pos; i < pos + sample; ++i) nl += s[i] == '\n';
    // No newline in the sample = rows wider than 64 KB: estimate cells
    // from bytes-per-cell instead of rows (a row-count guess that ignores
    // d asked for ~row_est*d cells and turned a 2 MB, 30k-attribute file
    // into a multi-GB reserve). Either way, clamp by the hard bound that
    // every cell costs at least 2 input bytes (token + separator).
    double cells_est =
        nl ? (double)span * nl / sample * (double)d * 1.08 : span / 6.0;
    size_t cap = span / 2 + d;
    st.cells.reserve(st.cells.size() +
                     (cells_est < (double)cap ? (size_t)cells_est : cap));
  }

  auto convert_row = [&]() -> bool {
    int save_line = st.line;
    for (size_t j = 0; j < d; ++j) {
      const Tok& tk = row[j];
      const char* p = tk.owned >= 0 ? owned[tk.owned].data() : s + tk.off;
      size_t len = tk.owned >= 0 ? owned[tk.owned].size() : tk.len;
      float v;
      st.line = tk.line;  // cite the token's own line
      if (!cell_view_to_float(p, len, st.attrs[j], &v, st)) return false;
      st.cells.push_back(v);
    }
    st.line = save_line;
    row.clear();
    owned.clear();
    return true;
  };

  size_t toks_in_row = 0;   // EAGER: tokens seen in the current row
  size_t cells_in_row = 0;  // EAGER: cells pushed for the current row
  std::string pending_err;  // EAGER: first conversion error in the row

  while (pos < N) {
    st.line++;
    // '%' comments only at the true line start (arff_lexer.cpp:60-78).
    if (s[pos] == '%') {
      while (pos < N && s[pos] != '\n') pos++;
      if (pos < N) pos++;
      continue;
    }
    if constexpr (EAGER) {
      // Opportunistic fused line scan — the shape of essentially every
      // line of a dense numeric file: ordinary-byte tokens separated by
      // SINGLE commas, ending straight in '\n' (or EOF). One run scan and
      // one convert per token, no per-character dispatch. Anything off the
      // shape (leading '{' or whitespace, tabs/CR/quotes, empty cells,
      // trailing comma) restores the line-start state transactionally and
      // falls through to the general machinery below — which re-parses
      // the line from scratch, so the fast attempt can never change what
      // is accepted, rejected, or reported.
      if (s[pos] != '{') {
        size_t p2 = pos;
        const size_t save_cells = st.cells.size();
        const size_t save_toks = toks_in_row;
        const size_t save_cir = cells_in_row;
        const bool had_pending = !pending_err.empty();
        bool ok_line = true, line_done = false;
        while (true) {
          size_t t0 = p2;
          while (p2 < N && !kStructural[(unsigned char)s[p2]]) p2++;
          if (p2 == t0) {
            ok_line = false;  // blank line, leading ws, or empty cell
            break;
          }
          // Validate the terminator BEFORE converting or counting: a
          // quote here means the token CONTINUES as a spliced composite
          // (e.g. 1e'5' -> 1e5) and a non-EOL '\r' may be an interior
          // token char — both must go to the general machinery with no
          // eager side effects, or a row the general parser accepts could
          // be rejected on the truncated token (r4 review repro). A '\r'
          // directly before '\n' (or EOF) is a plain CRLF ending and
          // stays on the fast path.
          char term = p2 < N ? s[p2] : '\n';
          bool eol = term == '\n' ||
                     (term == '\r' && (p2 + 1 >= N || s[p2 + 1] == '\n'));
          if (term != ',' && !eol) {
            ok_line = false;  // space/tab, quote, or interior CR
            break;
          }
          if (pending_err.empty()) {
            float v;
            if (cell_view_to_float(s + t0, p2 - t0, st.attrs[toks_in_row],
                                   &v, st)) {
              st.cells.push_back(v);
              cells_in_row++;
            } else {
              pending_err.swap(st.error);
            }
          }
          if (++toks_in_row == d) {
            // Same first-error semantics as the general path: the tokens
            // up to here are identical either way, so failing now reports
            // exactly what a full re-parse would.
            if (!pending_err.empty()) {
              st.error = std::move(pending_err);
              return false;
            }
            toks_in_row = 0;
            cells_in_row = 0;
          }
          if (p2 >= N) {
            line_done = true;  // EOF completes the token like EOL
            break;
          }
          if (eol) {
            p2 += term == '\r' ? (p2 + 1 < N ? 2 : 1) : 1;
            line_done = true;
            break;
          }
          p2++;  // consume ','; ",,", ",\n" etc. bail on the next pass
        }
        if (ok_line && line_done) {
          pos = p2;
          continue;
        }
        // Transactional bail: undo everything this attempt did (including
        // a row it may have completed — the re-parse recreates it).
        st.cells.resize(save_cells);
        toks_in_row = save_toks;
        cells_in_row = save_cir;
        if (!had_pending) pending_err.clear();
      }
    }
    // Leading whitespace, then the sparse-row check on the first real char.
    while (pos < N && (s[pos] == ' ' || s[pos] == '\t' || s[pos] == '\r'))
      pos++;
    if (pos < N && s[pos] == '{') {
      // Not quite: a leading '\r' run reaching the newline is a blank line,
      // already skipped above; a real first char '{' is a sparse row.
      fail(st, "sparse ARFF rows are not supported");
      return false;
    }
    bool token_since_comma = false;  // resets per physical line
    while (pos < N && s[pos] != '\n') {
      char c = s[pos];
      if (c == ' ' || c == '\t') {
        pos++;
        continue;
      }
      if (c == '\r') {
        // Line-trailing [ \t\r]* is stripped; an interior '\r' is a token
        // character (split_csv semantics).
        size_t q = pos;
        while (q < N && (s[q] == ' ' || s[q] == '\t' || s[q] == '\r')) q++;
        if (q >= N || s[q] == '\n') {
          pos = q;
          continue;
        }
      }
      if (c == ',') {
        if (token_since_comma) {
          token_since_comma = false;  // separator for the previous token
        } else {
          fail(st, "empty value in data row");
          return false;
        }
        pos++;
        continue;
      }
      // Token scan: c starts a token (possibly '\r', possibly a quote).
      // The hot structure is a RUN scan: a 256-entry class table marks the
      // seven structural bytes (space, tab, comma, newline, CR, both
      // quotes) and everything else is an "ordinary" token byte consumed
      // in a tight one-load-per-byte loop — the digits that dominate a
      // numeric file never touch the structural dispatch below it.
      uint32_t t_off = (uint32_t)pos, t_len = 0;
      int32_t t_owned = -1;
      int32_t t_line = st.line;  // cite the token's opening line
      auto append_run = [&](size_t off, size_t len) {
        if (len == 0) return;
        if (t_owned >= 0) {
          owned[t_owned].append(s + off, len);
        } else if (t_len == 0) {
          t_off = (uint32_t)off;
          t_len = (uint32_t)len;
        } else if ((size_t)t_off + t_len == off) {
          t_len += (uint32_t)len;  // contiguous: extend the view
        } else {
          // Discontiguous continuation (the view came from a quoted slice,
          // e.g. 'ab'cd): promote to an owned splice.
          owned.emplace_back(s + t_off, t_len);
          t_owned = (int32_t)owned.size() - 1;
          owned[t_owned].append(s + off, len);
          t_len = 0;
        }
      };
      for (;;) {
        size_t run0 = pos;
        while (pos < N && !kStructural[(unsigned char)s[pos]]) pos++;
        append_run(run0, pos - run0);
        if (pos >= N) break;
        char ch = s[pos];
        if (ch == '\n' || ch == ' ' || ch == '\t' || ch == ',') break;
        if (ch == '\'' || ch == '"') {
          // The close search runs THROUGH newlines (arff_lexer.cpp:159-188:
          // a quoted value may span physical lines; the content, newlines
          // included, stays one contiguous zero-copy slice).
          size_t close = pos + 1;
          int nl_in_quote = 0;
          while (close < N && s[close] != ch) {
            if (s[close] == '\n') nl_in_quote++;
            close++;
          }
          if (close >= N) {
            st.line = t_line;
            fail(st, "unterminated quoted value");
            return false;
          }
          st.line += nl_in_quote;
          if (t_len == 0 && t_owned < 0) {
            // Token starts with a quote: stay a zero-copy view. If more
            // token characters follow, append_run's discontiguity check
            // promotes it to an owned splice.
            t_off = (uint32_t)(pos + 1);
            t_len = (uint32_t)(close - (pos + 1));
          } else {
            if (t_owned < 0) {
              owned.emplace_back(s + t_off, t_len);
              t_owned = (int32_t)owned.size() - 1;
              t_len = 0;
            }
            owned[t_owned].append(s + pos + 1, close - (pos + 1));
          }
          pos = close + 1;
          continue;
        }
        // ch == '\r': line-trailing [ \t\r]* ends the token; an interior
        // '\r' is an ordinary token character (split_csv semantics).
        size_t q = pos;
        while (q < N && (s[q] == ' ' || s[q] == '\t' || s[q] == '\r')) q++;
        if (q >= N || s[q] == '\n') break;
        append_run(pos, 1);
        pos++;
      }
      if (t_owned < 0 && t_len == 0) {
        // '' / "" — an empty quoted cell (split_csv pushed "" here).
        fail(st, "empty value in data row");
        return false;
      }
      if (t_owned >= 0 && owned[t_owned].empty()) {
        fail(st, "empty value in data row");
        return false;
      }
      if constexpr (EAGER) {
        if (pending_err.empty()) {
          const char* tp = t_owned >= 0 ? owned[t_owned].data() : s + t_off;
          size_t tl = t_owned >= 0 ? owned[t_owned].size() : t_len;
          float v;
          int save_line = st.line;
          st.line = t_line;  // cite the token's own line
          if (cell_view_to_float(tp, tl, st.attrs[toks_in_row], &v, st)) {
            st.cells.push_back(v);
            cells_in_row++;
          } else {
            pending_err.swap(st.error);  // defer until the row completes
          }
          st.line = save_line;
        }
        owned.clear();
      } else {
        row.push_back({t_off, t_len, t_line, t_owned});
      }
      if (pos < N && s[pos] == ',') {
        pos++;
        token_since_comma = false;  // the comma terminated its own token
      } else {
        token_since_comma = true;
      }
      if constexpr (EAGER) {
        if (++toks_in_row == d) {
          if (!pending_err.empty()) {
            st.error = std::move(pending_err);
            return false;
          }
          toks_in_row = 0;
          cells_in_row = 0;
        }
      } else {
        if (row.size() == d && !convert_row()) return false;
      }
    }
    if (pos < N) pos++;  // consume '\n'
  }
  // A partial row at EOF is discarded unconverted (arff_parser.cpp:130-133);
  // eager mode truncates the partial row's already-converted cells.
  if constexpr (EAGER) st.cells.resize(st.cells.size() - cells_in_row);
  return true;
}

// ---------------------------------------------------------------------------
// Parallel @data scan (VERDICT r2/r3/r4 #5; shipped r5).
//
// Two passes over the span, both parallel over newline-aligned segments:
//   pass 1: count tokens + newlines per segment (and detect anything the
//           parallel subset does not handle — quotes, lone '\r');
//   pass 2: with exact per-segment token prefixes known, convert every
//           token with its true attribute index ((prefix + i) % d) and
//           write it DIRECTLY at its final offset in one preallocated
//           cells buffer — no locks, no merge.
//
// Scope: NUMERIC/NOMINAL attribute sets only (conversion is pure; the
// STRING/DATE intern tables mutate in first-seen order, which is
// inherently sequential, so those files keep the serial scanner), and the
// quote-free dialect subset (quoted cells may span lines and splice
// tokens, which breaks newline segmentation — pass 1 detects any quote
// byte and falls back). ANY worker error (malformed value, empty cell,
// sparse row) or a pass-1/pass-2 token-count mismatch also falls back to
// the serial scanner, so every diagnostic — message, line number,
// first-error ordering, and the discard-partial-row-at-EOF rule — is the
// serial parser's own, byte for byte. The parallel path only ever COMMITS
// on clean input it counted consistently.
//
// Host note: the axon bench box has 1 core, so BENCH ingest numbers there
// are the serial path's; this scan exists for real multi-core hosts
// (segment conversion measured ~550 MB/s/core on that box's idealized
// loop — see r5 probe — so 4-8 cores clear the GB/s bar the reference's
// one-char-per-fread scanner could never approach, arff_scanner.cpp:46).

struct SegCount {
  size_t tokens = 0, newlines = 0;
  bool bail = false;  // quote / lone '\r': not the parallel subset
};

struct SegResult {
  size_t tokens = 0;
  bool error = false;  // any diagnostic -> serial rerun
};

// Pass 1: count token runs and newlines exactly as the quote-free
// tokenizer would (comment lines skipped whole; '\r' legal only as part
// of a CRLF or trailing [ \t\r]* run — anything else bails).
void count_segment(const char* s, size_t b, size_t e, SegCount& out) {
  bool at_line_start = true;
  bool in_token = false;
  size_t pos = b;
  while (pos < e) {
    char c = s[pos];
    if (at_line_start && c == '%') {
      while (pos < e && s[pos] != '\n') pos++;
      continue;  // the '\n' (if any) is handled below
    }
    if (c == '\n') {
      out.newlines++;
      at_line_start = true;
      in_token = false;
      pos++;
      continue;
    }
    at_line_start = false;
    if (c == '\'' || c == '"') {
      out.bail = true;
      return;
    }
    if (c == '\r') {
      // Legal only when the [ \t\r]* run reaches '\n' or EOF (trailing
      // whitespace); an interior '\r' is a token byte in the serial
      // dialect — bail rather than miscount.
      size_t q = pos;
      while (q < e && (s[q] == ' ' || s[q] == '\t' || s[q] == '\r')) q++;
      if (q < e && s[q] != '\n') {
        out.bail = true;
        return;
      }
      in_token = false;
      pos = q;
      continue;
    }
    if (c == ' ' || c == '\t' || c == ',') {
      in_token = false;
      pos++;
      continue;
    }
    if (!in_token) {
      out.tokens++;
      in_token = true;
    }
    while (pos < e && !kStructural[(unsigned char)s[pos]]) pos++;
    in_token = false;
  }
}

// Pass 2: convert one segment's tokens at their final offsets. Replicates
// the serial tokenizer's quote-free subset exactly (split_csv semantics:
// comma directly after a token is its terminator, ",," and leading ','
// are empty-cell errors, '%' comments at true line start, '{' first char
// is a sparse-row error). Tokens at global index >= `complete` belong to
// the discarded partial row at EOF and are not written. `tok_budget` is
// the segment's PASS-1 token count: writes are clamped to it (counting
// continues, so the caller's mismatch check still fires and discards the
// result) because the prefixes of the following segments were computed
// from pass 1 — a tokenizer divergence that produced extra pass-2 tokens
// would otherwise store into the next worker's index range, a concurrent
// unsynchronized write even though the committed result is re-parsed
// serially.
void convert_segment(const char* s, size_t b, size_t e, ParseState& wst,
                     size_t tok_prefix, size_t tok_budget, size_t complete,
                     float* cells, size_t d, SegResult& out) {
  size_t pos = b;
  size_t cnt = 0;  // tokens seen in this segment
  while (pos < e) {
    wst.line++;
    if (s[pos] == '%') {
      while (pos < e && s[pos] != '\n') pos++;
      if (pos < e) pos++;
      continue;
    }
    while (pos < e && (s[pos] == ' ' || s[pos] == '\t' || s[pos] == '\r'))
      pos++;
    if (pos < e && s[pos] == '{') {
      fail(wst, "sparse ARFF rows are not supported");
      out.error = true;
      return;
    }
    bool token_since_comma = false;
    while (pos < e && s[pos] != '\n') {
      char c = s[pos];
      if (c == ' ' || c == '\t') {
        pos++;
        continue;
      }
      if (c == '\r') {
        size_t q = pos;
        while (q < e && (s[q] == ' ' || s[q] == '\t' || s[q] == '\r')) q++;
        pos = q;  // pass 1 guaranteed this run reaches '\n' or EOF
        continue;
      }
      if (c == ',') {
        if (token_since_comma) {
          token_since_comma = false;
        } else {
          fail(wst, "empty value in data row");
          out.error = true;
          return;
        }
        pos++;
        continue;
      }
      size_t t0 = pos;
      while (pos < e && !kStructural[(unsigned char)s[pos]]) pos++;
      size_t g = tok_prefix + cnt;
      if (cnt < tok_budget && g < complete) {
        float v;
        if (!cell_view_to_float(s + t0, pos - t0, wst.attrs[g % d], &v,
                                wst)) {
          out.error = true;  // serial rerun reproduces the exact diagnostic
          return;
        }
        cells[g] = v;
      }
      cnt++;
      if (pos < e && s[pos] == ',') {
        pos++;
        token_since_comma = false;
      } else {
        token_since_comma = true;
      }
    }
    if (pos < e) pos++;  // consume '\n'
  }
  out.tokens = cnt;
}

// Returns true when the parallel scan COMMITTED a result into `st`;
// false means "use the serial scanner" (unsupported dialect/attrs, an
// error anywhere, or a count mismatch).
bool try_parse_data_parallel(std::string_view data, size_t pos,
                             ParseState& st, unsigned threads) {
  const size_t N = data.size();
  const size_t d = st.attrs.size();
  if (threads < 2 || N - pos < (4u << 20) || N > UINT32_MAX || d == 0)
    return false;
  for (const Attr& a : st.attrs)
    if (a.type_code != TC_NUMERIC && a.type_code != TC_NOMINAL)
      return false;  // interning is first-seen sequential
  const char* s = data.data();

  // Newline-aligned segment boundaries.
  size_t span = N - pos;
  size_t T = threads;
  if (span / T < (1u << 20)) T = span / (1u << 20);
  if (T < 2) return false;
  std::vector<size_t> bounds{pos};
  for (size_t i = 1; i < T; ++i) {
    size_t cand = pos + span * i / T;
    const void* nl = memchr(s + cand, '\n', N - cand);
    size_t b = nl ? (size_t)((const char*)nl - s) + 1 : N;
    if (b > bounds.back()) bounds.push_back(b);
  }
  bounds.push_back(N);
  size_t S = bounds.size() - 1;
  if (S < 2) return false;

  std::vector<SegCount> counts(S);
  {
    std::vector<std::thread> pool;
    for (size_t i = 1; i < S; ++i)
      pool.emplace_back(count_segment, s, bounds[i], bounds[i + 1],
                        std::ref(counts[i]));
    count_segment(s, bounds[0], bounds[1], counts[0]);
    for (auto& t : pool) t.join();
  }
  size_t total_tokens = 0;
  for (const SegCount& c : counts) {
    if (c.bail) return false;
    total_tokens += c.tokens;
  }
  size_t complete = total_tokens / d * d;

  st.cells.assign(complete, 0.0f);
  std::vector<ParseState> wstates(S);
  std::vector<SegResult> results(S);
  const int line0 = st.line;
  size_t total_nl = 0;
  {
    size_t tok_prefix = 0, nl_prefix = 0;
    std::vector<std::thread> pool;
    for (size_t i = 0; i < S; ++i) {
      wstates[i].attrs = st.attrs;  // nominal tables: read-only per worker
      wstates[i].line = line0 + (int)nl_prefix;
      if (i)
        pool.emplace_back(convert_segment, s, bounds[i], bounds[i + 1],
                          std::ref(wstates[i]), tok_prefix,
                          counts[i].tokens, complete, st.cells.data(), d,
                          std::ref(results[i]));
      tok_prefix += counts[i].tokens;
      nl_prefix += counts[i].newlines;
    }
    convert_segment(s, bounds[0], bounds[1], wstates[0], 0,
                    counts[0].tokens, complete, st.cells.data(), d,
                    results[0]);
    for (auto& t : pool) t.join();
    total_nl = nl_prefix;
  }
  for (size_t i = 0; i < S; ++i)
    if (results[i].error || results[i].tokens != counts[i].tokens) {
      // Serial rerun owns every diagnostic; `st` must be exactly as the
      // serial scanner expects at entry (an advanced st.line here doubled
      // the reported error line — caught by tests/test_native_parallel).
      st.cells.clear();
      return false;
    }
  st.line = line0 + (int)total_nl;
  return true;
}

unsigned resolve_parse_threads(int threads) {
  if (threads > 0) return (unsigned)threads;
  if (const char* env = getenv("KNN_ARFF_THREADS")) {
    long v = strtol(env, nullptr, 10);
    if (v > 0) return (unsigned)v;
  }
  unsigned hw = std::thread::hardware_concurrency();
  return hw ? hw : 1;
}

bool parse_data_stream(std::string_view data, size_t pos, ParseState& st,
                       int threads = 0) {
  unsigned T = resolve_parse_threads(threads);
  if (T > 1 && try_parse_data_parallel(data, pos, st, T)) return true;
  for (const Attr& a : st.attrs)
    if (a.type_code != TC_NUMERIC)
      return parse_data_stream_impl<false>(data, pos, st);
  return parse_data_stream_impl<true>(data, pos, st);
}

bool parse_buffer(std::string_view data, ParseState& st) {
  size_t pos = 0;
  // Pull the next physical line into *out; false at EOF. No comment
  // skipping — callers decide (none applies inside an open quote).
  auto next_line = [&](std::string* out) -> bool {
    if (pos > data.size()) return false;
    size_t nl = data.find('\n', pos);
    *out = nl == std::string::npos ? data.substr(pos)
                                   : data.substr(pos, nl - pos);
    pos = nl == std::string::npos ? data.size() + 1 : nl + 1;
    st.line++;
    return true;
  };
  std::string raw;
  while (next_line(&raw)) {
    // '%' comments only at the true line start (arff_lexer.cpp:60-78);
    // indented/trailing '%' is data and errors downstream on typed attrs.
    if (!raw.empty() && raw[0] == '%') continue;
    // A quoted value may span physical lines (arff_lexer.cpp:159-188 reads
    // to the matching quote through newlines): join lines into one logical
    // line while a quote is open, preserving the newline inside the value.
    int start_line = st.line;
    std::string logical = raw;
    // Quote state folds incrementally over each appended segment, so the
    // join stays linear in the value's length.
    char open_q = scan_quote(logical);
    while (open_q) {
      std::string nxt;
      if (!next_line(&nxt)) {
        st.line = start_line;
        fail(st, "unterminated quoted value");
        return false;
      }
      logical += "\n" + nxt;
      open_q = scan_quote("\n" + nxt, open_q);
    }
    std::string line = strip(logical);
    if (line.empty()) continue;
    if (line[0] == '@') {
      size_t sp = line.find_first_of(" \t");
      std::string word = sp == std::string::npos ? line : line.substr(0, sp);
      std::string rest = sp == std::string::npos ? "" : strip(line.substr(sp));
      if (ieq(word, "@relation")) {
        st.relation = rest;
        if (st.relation.size() >= 2 &&
            (st.relation.front() == '\'' || st.relation.front() == '"') &&
            st.relation.back() == st.relation.front())
          st.relation = st.relation.substr(1, st.relation.size() - 2);
      } else if (ieq(word, "@attribute")) {
        // An open nominal list continues on the next physical line(s): the
        // reference reads the {...} value tokens from the lexer stream,
        // where a newline is ordinary whitespace (arff_parser.cpp:69-119).
        // '%' comment lines between the value tokens are skipped as usual;
        // a quoted value inside the continued list may span further lines.
        while (open_nominal(rest)) {
          std::string seg;
          if (!next_line(&seg)) break;  // parse_attribute fails located
          if (!seg.empty() && seg[0] == '%') continue;
          char seg_q = scan_quote(seg);
          while (seg_q) {
            std::string more;
            if (!next_line(&more)) {
              fail(st, "unterminated quoted value");
              return false;
            }
            seg += "\n" + more;
            seg_q = scan_quote("\n" + more, seg_q);
          }
          rest += " " + strip(seg);
        }
        int end_line = st.line;
        st.line = start_line;  // cite the declaration's own line
        if (!parse_attribute(rest, st)) return false;
        st.line = end_line;
      } else if (ieq(word, "@data")) {
        if (st.attrs.empty()) {
          fail(st, "@data before any @attribute");
          return false;
        }
        // Hand the rest of the buffer (everything after this line's newline)
        // to the streaming zero-copy data scanner.
        return parse_data_stream(data, pos, st);
      } else {
        st.line = start_line;
        fail(st, "unknown keyword '" + word + "'");
        return false;
      }
      continue;
    }
    st.line = start_line;
    fail(st, "unexpected content before @data: '" + line + "'");
    return false;
  }
  // No @data section at all. Match the historical error precedence: a file
  // with no @attribute declarations reports that first.
  if (st.attrs.empty()) {
    st.line = 0;
    fail(st, "no @attribute declarations found");
    return false;
  }
  return true;
}

void json_escape(const std::string& s, std::string& out) {
  char buf[8];
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out.push_back('\\');
      out.push_back(c);
    } else if ((unsigned char)c < 0x20) {
      snprintf(buf, sizeof(buf), "\\u%04x", (unsigned char)c);
      out += buf;
    } else {
      out.push_back(c);
    }
  }
}

char* dup_string(const std::string& s) {
  char* p = (char*)malloc(s.size() + 1);
  memcpy(p, s.c_str(), s.size() + 1);
  return p;
}

}  // namespace

extern "C" {

// Bumped whenever KnnArffResult's layout changes (raw_targets was inserted
// for the regression extension). The Python binding refuses to use a library
// whose ABI version does not match, so a stale prebuilt .so can never be
// read through the wrong struct layout.
int knn_arff_abi_version(void) { return 2; }

// Result of parsing: dense features [n, d_features] + labels [n] where the
// class is the last declared attribute cast to int (main.cpp:57,66 contract).
// attrs_json describes all attributes (name/type/nominal values).
// On failure, `error` is set and all other fields are null/0.
struct KnnArffResult {
  float* features;
  int32_t* labels;
  float* raw_targets;  // the class column before the int cast (regression)
  int64_t n;
  int64_t d_features;
  int32_t num_classes;  // max(label)+1 (arff_data.cpp:41-58 semantics)
  char* relation;
  char* attrs_json;
  char* error;
};

int knn_arff_parse(const char* path, KnnArffResult* out) {
  memset(out, 0, sizeof(*out));
  ParseState st;
  st.path = path;

  // The parser runs over a read-only view of the file. Preferred path:
  // mmap with MAP_POPULATE — the batch prefault makes a page-cached 90 MB
  // file mappable in ~1-2 ms where one streaming fread copy costs ~55 ms
  // (r5 measurement; plain mmap WITHOUT populate was slower than fread —
  // per-access soft faults — which is what an earlier round measured).
  // Falls back to the fread copy when mmap is unavailable (exotic FS).
  std::unique_ptr<char[]> file_buf;
  void* mapped = nullptr;
  size_t mapped_size = 0;
  std::string_view data;
  {
    FILE* f = fopen(path, "rb");
    if (!f) {
      out->error = dup_string(std::string(path) + ": cannot open file (" +
                              strerror(errno) + ")");
      return 1;
    }
    // A directory opens fine on Linux but reads garbage (EISDIR on fread,
    // ENODEV on mmap) and its ftell size is fs-dependent: reject up front
    // with a truthful message instead of "no @attribute declarations".
    struct stat stbuf;
    if (fstat(fileno(f), &stbuf) == 0 && S_ISDIR(stbuf.st_mode)) {
      fclose(f);
      out->error = dup_string(std::string(path) + ": is a directory");
      return 1;
    }
    fseek(f, 0, SEEK_END);
    long size = ftell(f);
    fseek(f, 0, SEEK_SET);
    if (size > 0) {
      mapped = mmap(nullptr, (size_t)size, PROT_READ,
                    MAP_PRIVATE | MAP_POPULATE, fileno(f), 0);
      if (mapped != MAP_FAILED) {
        mapped_size = (size_t)size;
        data = std::string_view((const char*)mapped, (size_t)size);
      } else {
        mapped = nullptr;
        // bad_alloc here must not escape extern "C" (that aborts the host
        // interpreter): a truncated-allocation error is a parse error.
        try {
          file_buf.reset(new char[(size_t)size]);
        } catch (const std::bad_alloc&) {
          fclose(f);
          out->error = dup_string(std::string(path) +
                                  ": out of memory reading file");
          return 1;
        }
        if (fread(file_buf.get(), 1, (size_t)size, f) != (size_t)size) {
          fclose(f);
          out->error = dup_string(std::string(path) +
                                  ": short read (truncated or unreadable file)");
          return 1;
        }
        data = std::string_view(file_buf.get(), (size_t)size);
      }
    }
    fclose(f);
  }
  struct Unmap {
    void* p;
    size_t n;
    ~Unmap() { if (p) munmap(p, n); }
  } unmap_guard{mapped, mapped_size};

  bool parsed;
  try {
    parsed = parse_buffer(data, st);
  } catch (const std::bad_alloc&) {
    // Allocation failure must come back through the C ABI's error field —
    // an exception escaping extern "C" aborts the host interpreter.
    out->error = dup_string(std::string(path) + ": out of memory while parsing");
    return 1;
  }
  if (!parsed) {
    out->error = dup_string(st.error);
    return 1;
  }

  size_t d = st.attrs.size();
  size_t n = d ? st.cells.size() / d : 0;
  size_t df = d - 1;
  out->n = (int64_t)n;
  out->d_features = (int64_t)df;
  out->features = (float*)malloc(sizeof(float) * n * (df ? df : 1));
  out->labels = (int32_t*)malloc(sizeof(int32_t) * (n ? n : 1));
  out->raw_targets = (float*)malloc(sizeof(float) * (n ? n : 1));
  if (!out->features || !out->labels || !out->raw_targets) {
    // A NULL from malloc fed to memcpy below is a segfault, not an error:
    // surface allocation failure through the ABI like every other failure.
    free(out->features);
    free(out->labels);
    free(out->raw_targets);
    memset(out, 0, sizeof(*out));
    out->error = dup_string(st.path + ": out of memory materializing arrays");
    return 1;
  }
  int32_t max_label = -1;
  for (size_t i = 0; i < n; ++i) {
    const float* row = &st.cells[i * d];
    memcpy(out->features + i * df, row, sizeof(float) * df);
    float lab = row[d - 1];
    if (std::isnan(lab)) {
      free(out->features);
      free(out->labels);
      free(out->raw_targets);
      memset(out, 0, sizeof(*out));
      // ":0:" — instance index, not line, is known here; same format as the
      // Python parser's ArffError(path, 0, ...) for this case.
      out->error = dup_string(st.path + ":0: instance " + std::to_string(i) +
                              " has a missing class label");
      return 1;
    }
    out->labels[i] = (int32_t)lab;
    out->raw_targets[i] = lab;
    if (out->labels[i] > max_label) max_label = out->labels[i];
  }
  out->num_classes = max_label + 1;
  out->relation = dup_string(st.relation);

  std::string j = "[";
  for (size_t a = 0; a < st.attrs.size(); ++a) {
    if (a) j += ",";
    j += "{\"name\":\"";
    json_escape(st.attrs[a].name, j);
    j += "\",\"type\":\"" + st.attrs[a].type + "\"";
    if (st.attrs[a].type == "nominal") {  // emit [] for "{}" (parity with py)
      j += ",\"nominal_values\":[";
      for (size_t v = 0; v < st.attrs[a].nominal.size(); ++v) {
        if (v) j += ",";
        j += "\"";
        json_escape(st.attrs[a].nominal[v], j);
        j += "\"";
      }
      j += "]";
    }
    if (st.attrs[a].type == "string" || st.attrs[a].type == "date") {
      j += ",\"string_values\":[";
      for (size_t v = 0; v < st.attrs[a].interned.size(); ++v) {
        if (v) j += ",";
        j += "\"";
        json_escape(st.attrs[a].interned[v], j);
        j += "\"";
      }
      j += "]";
    }
    j += "}";
  }
  j += "]";
  out->attrs_json = dup_string(j);
  return 0;
}

void knn_arff_free(KnnArffResult* r) {
  if (!r) return;
  free(r->features);
  free(r->labels);
  free(r->raw_targets);
  free(r->relation);
  free(r->attrs_json);
  free(r->error);
  memset(r, 0, sizeof(*r));
}

}  // extern "C"
