// Native KNN runtime: the serial and thread-pool execution backends.
//
// One kernel, reference-exact semantics (SURVEY.md §3.5): squared Euclidean
// accumulated in source order (main.cpp:14-23), sorted k-candidate insertion
// with strict '<' so the earliest-scanned train index wins distance ties
// (main.cpp:46-61), bincount vote with strict '>' so the lowest class id wins
// vote ties (main.cpp:64-78). Unlike the reference's three copy-pasted
// kernels, num_threads selects the execution strategy over this single
// implementation: 1 = serial (main.cpp analogue), >1 = fork-join over
// contiguous query ranges with the remainder going to the last worker
// (multi-thread.cpp:154-161 partitioning), <=0 = hardware concurrency.
//
// C ABI only — bound from Python via ctypes.

#include <algorithm>
#include <cfloat>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

void knn_range(const float* train, const int32_t* labels, int64_t n, int64_t d,
               const float* test, int32_t k, int32_t num_classes,
               int64_t q_start, int64_t q_end, int32_t* out) {
  std::vector<float> cand_dist((size_t)k);
  std::vector<int32_t> cand_label((size_t)k);
  std::vector<int32_t> counts((size_t)num_classes);

  for (int64_t q = q_start; q < q_end; ++q) {
    const float* query = test + q * d;
    std::fill(cand_dist.begin(), cand_dist.end(), FLT_MAX);
    std::fill(cand_label.begin(), cand_label.end(), -1);
    int32_t filled = 0;

    for (int64_t i = 0; i < n; ++i) {
      const float* row = train + i * d;
      float dist = 0.0f;
      for (int64_t j = 0; j < d; ++j) {
        float diff = query[j] - row[j];
        dist += diff * diff;
      }
      // Framework-wide policy (where the reference is UB, SURVEY.md §3.5.5):
      // NaN distances count as +inf, and +inf candidates are admitted in
      // (distance, index) order — every backend selects the k lexicographically
      // smallest (dist, train_index) pairs.
      if (std::isnan(dist)) dist = INFINITY;
      // Sorted insertion, strict '<': first-seen wins among equal distances;
      // an unfilled tail slot admits the row even at equal/inf distance.
      int32_t pos = -1;
      for (int32_t c = 0; c < filled; ++c) {
        if (dist < cand_dist[c]) {
          pos = c;
          break;
        }
      }
      if (pos < 0 && filled < k) pos = filled;
      if (pos >= 0) {
        for (int32_t x = k - 1; x > pos; --x) {
          cand_dist[x] = cand_dist[x - 1];
          cand_label[x] = cand_label[x - 1];
        }
        cand_dist[pos] = dist;
        cand_label[pos] = labels[i];
        if (filled < k) filled++;
      }
    }

    std::fill(counts.begin(), counts.end(), 0);
    for (int32_t c = 0; c < k; ++c)
      if (cand_label[c] >= 0 && cand_label[c] < num_classes)
        counts[cand_label[c]]++;
    int32_t best = -1, best_class = 0;
    for (int32_t cls = 0; cls < num_classes; ++cls) {
      if (counts[cls] > best) {  // strict '>': lowest class id wins ties
        best = counts[cls];
        best_class = cls;
      }
    }
    out[q] = best_class;
  }
}

}  // namespace

extern "C" {

// Returns 0 on success, nonzero on invalid arguments.
int knn_native_predict(const float* train, const int32_t* labels, int64_t n,
                       int64_t d, const float* test, int64_t q, int32_t k,
                       int32_t num_classes, int32_t num_threads,
                       int32_t* out_predictions) {
  if (!train || !labels || !test || !out_predictions) return 1;
  if (n <= 0 || d < 0 || q < 0 || k < 1 || k > n || num_classes < 1) return 2;

  int32_t t = num_threads;
  if (t <= 0) t = (int32_t)std::max(1u, std::thread::hardware_concurrency());
  t = (int32_t)std::min<int64_t>(t, std::max<int64_t>(q, 1));

  if (t == 1) {
    knn_range(train, labels, n, d, test, k, num_classes, 0, q, out_predictions);
    return 0;
  }

  // Contiguous ranges, remainder to the last worker — the reference's
  // partition (multi-thread.cpp:154-161); disjoint output slices need no
  // synchronization (multi-thread.cpp:15,94).
  int64_t per = q / t;
  std::vector<std::thread> workers;
  workers.reserve((size_t)t);
  for (int32_t w = 0; w < t; ++w) {
    int64_t s = w * per;
    int64_t e = (w == t - 1) ? q : s + per;
    workers.emplace_back(knn_range, train, labels, n, d, test, k, num_classes,
                         s, e, out_predictions);
  }
  for (auto& th : workers) th.join();
  return 0;
}

}  // extern "C"
