"""ctypes binding for the native ARFF parser (native/arff/arff_c.cc).

Emits the same :class:`Dataset` as the pure-Python parser; the golden-array
tests assert bit-identical output between the two.
"""

from __future__ import annotations

import ctypes
import json

import numpy as np

from knn_tpu.data.dataset import Attribute, Dataset
from knn_tpu.native import build_if_missing


class _KnnArffResult(ctypes.Structure):
    _fields_ = [
        ("features", ctypes.POINTER(ctypes.c_float)),
        ("labels", ctypes.POINTER(ctypes.c_int32)),
        ("n", ctypes.c_int64),
        ("d_features", ctypes.c_int64),
        ("num_classes", ctypes.c_int32),
        ("relation", ctypes.c_char_p),
        ("attrs_json", ctypes.c_char_p),
        ("error", ctypes.c_char_p),
    ]


def _load():
    lib = ctypes.CDLL(str(build_if_missing("libknn_arff.so")))  # OSError if unbuildable
    lib.knn_arff_parse.argtypes = [ctypes.c_char_p, ctypes.POINTER(_KnnArffResult)]
    lib.knn_arff_parse.restype = ctypes.c_int
    lib.knn_arff_free.argtypes = [ctypes.POINTER(_KnnArffResult)]
    lib.knn_arff_free.restype = None
    return lib


_lib = _load()


def parse(path: str) -> Dataset:
    res = _KnnArffResult()
    rc = _lib.knn_arff_parse(str(path).encode(), ctypes.byref(res))
    try:
        if rc != 0:
            msg = res.error.decode() if res.error else f"parse failed (rc={rc})"
            raise ValueError(msg)
        n, df = res.n, res.d_features
        features = np.ctypeslib.as_array(res.features, shape=(n, df)).copy() \
            if n and df else np.zeros((n, df), np.float32)
        labels = np.ctypeslib.as_array(res.labels, shape=(n,)).copy() \
            if n else np.zeros((n,), np.int32)
        attrs = [
            Attribute(a["name"], a["type"], a.get("nominal_values"))
            for a in json.loads(res.attrs_json.decode() if res.attrs_json else "[]")
        ]
        return Dataset(
            features=features,
            labels=labels,
            relation=res.relation.decode() if res.relation else "",
            attributes=attrs,
        )
    finally:
        _lib.knn_arff_free(ctypes.byref(res))
