"""ctypes binding for the native ARFF parser (native/arff/arff_c.cc).

Emits the same :class:`Dataset` as the pure-Python parser; the golden-array
tests assert bit-identical output between the two.
"""

from __future__ import annotations

import ctypes
import json

import numpy as np

from knn_tpu.data.dataset import Attribute, Dataset
from knn_tpu.native import build_if_missing
from knn_tpu.resilience.errors import DataError


class _KnnArffResult(ctypes.Structure):
    _fields_ = [
        ("features", ctypes.POINTER(ctypes.c_float)),
        ("labels", ctypes.POINTER(ctypes.c_int32)),
        ("raw_targets", ctypes.POINTER(ctypes.c_float)),
        ("n", ctypes.c_int64),
        ("d_features", ctypes.c_int64),
        ("num_classes", ctypes.c_int32),
        ("relation", ctypes.c_char_p),
        ("attrs_json", ctypes.c_char_p),
        ("error", ctypes.c_char_p),
    ]


_ABI_VERSION = 2  # must match knn_arff_abi_version() in arff_c.cc


def _load():
    lib = ctypes.CDLL(str(build_if_missing("libknn_arff.so")))  # OSError if unbuildable
    # A stale prebuilt .so (source unavailable / no compiler to rebuild) must
    # never be read through a newer struct layout — that is silent memory
    # corruption. Old libraries lack the version symbol entirely; both cases
    # surface as OSError, which load_arff treats as "native unavailable".
    try:
        abi = lib.knn_arff_abi_version()
    except AttributeError as e:
        raise OSError(f"libknn_arff.so predates the ABI version export: {e}")
    if abi != _ABI_VERSION:
        raise OSError(
            f"libknn_arff.so ABI version {abi} != expected {_ABI_VERSION}; rebuild "
            f"with `make native`"
        )
    lib.knn_arff_parse.argtypes = [ctypes.c_char_p, ctypes.POINTER(_KnnArffResult)]
    lib.knn_arff_parse.restype = ctypes.c_int
    lib.knn_arff_free.argtypes = [ctypes.POINTER(_KnnArffResult)]
    lib.knn_arff_free.restype = None
    return lib


_lib = _load()


def parse(path: str) -> Dataset:
    res = _KnnArffResult()
    rc = _lib.knn_arff_parse(str(path).encode(), ctypes.byref(res))
    try:
        if rc != 0:
            msg = res.error.decode() if res.error else f"parse failed (rc={rc})"
            # Typed like the pure-Python twin's ArffError: both parsers
            # surface malformed input as DataError with file:line context.
            raise DataError(msg)
        n, df = res.n, res.d_features
        features = np.ctypeslib.as_array(res.features, shape=(n, df)).copy() \
            if n and df else np.zeros((n, df), np.float32)
        labels = np.ctypeslib.as_array(res.labels, shape=(n,)).copy() \
            if n else np.zeros((n,), np.int32)
        raw_targets = np.ctypeslib.as_array(res.raw_targets, shape=(n,)).copy() \
            if n else np.zeros((n,), np.float32)
        attrs = [
            Attribute(
                a["name"], a["type"], a.get("nominal_values"),
                a.get("string_values"),
            )
            for a in json.loads(res.attrs_json.decode() if res.attrs_json else "[]")
        ]
        return Dataset(
            features=features,
            labels=labels,
            relation=res.relation.decode() if res.relation else "",
            attributes=attrs,
            raw_targets=raw_targets,
        )
    finally:
        _lib.knn_arff_free(ctypes.byref(res))
