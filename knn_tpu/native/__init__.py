"""Native (C++) components: the ARFF ingest library (``native/arff``) and the
serial/threaded runtime kernels (``native/runtime``), bound via ctypes.
Build with ``make native`` at the repo root."""
