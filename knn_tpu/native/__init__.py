"""Native (C++) components: the ARFF ingest library (``native/arff``) and the
serial/threaded runtime kernels (``native/runtime``), bound via ctypes.

The shared libraries build on demand at first import (or with ``make native``
at the repo root): :func:`build_if_missing` compiles the single-TU library
with the ambient C++ compiler when the ``.so`` is absent or older than its
source, so a fresh checkout needs no explicit build step.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from pathlib import Path

_ROOT = Path(__file__).parent
_LIB_DIR = _ROOT / "lib"

_SOURCES = {
    "libknn_arff.so": (_ROOT / "arff" / "arff_c.cc", ["-lpthread"]),
    "libknn_runtime.so": (_ROOT / "runtime" / "knn_runtime.cc", ["-lpthread"]),
}


class NativeBuildError(RuntimeError):
    """The C++ source exists and a compiler was found, but compilation failed.

    Deliberately NOT an OSError: the backend registry treats OSError from
    dlopen as "native backends unavailable" and continues silently, which is
    right for a missing compiler but would hide a genuinely broken build.
    """


def build_if_missing(name: str) -> Path:
    """Return the path to shared library `name`, compiling it if needed.

    No-op when the library exists and is newer than its source. If no C++
    compiler is available the stale/missing path is returned unchanged and the
    subsequent ``ctypes.CDLL`` raises ``OSError``, which the backend registry
    treats as "native backends unavailable".
    """
    out = _LIB_DIR / name
    src, extra_link = _SOURCES[name]
    if out.exists() and (not src.exists() or out.stat().st_mtime >= src.stat().st_mtime):
        return out
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None or not src.exists():
        return out
    _LIB_DIR.mkdir(parents=True, exist_ok=True)
    # Build to a pid-unique temp file and atomically rename, so concurrent
    # importers (e.g. pytest-xdist workers) never dlopen a half-written .so.
    tmp = _LIB_DIR / f".{name}.{os.getpid()}.tmp"
    cmd = [
        cxx, "-O3", "-std=c++17", "-fPIC", "-Wall", "-Wextra",
        "-shared", "-o", str(tmp), str(src), *extra_link,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"building {name} failed:\n$ {' '.join(cmd)}\n{proc.stderr}"
            )
        os.replace(tmp, out)
    finally:
        tmp.unlink(missing_ok=True)
    return out
