"""Exact-match result cache for the serving hot path.

Real multi-user traffic is skewed: the same query rows arrive again and
again (hot entities, retried requests, dashboard refreshes). Retrieval is
deterministic, so an answer computed once is correct for every identical
query until the index changes — and the serving stack already stamps
every response with exactly the two tags that define "the index changed":
``index_version`` (hot reload / compaction swap) and ``mutation_seq``
(the delta tier's sequence point). An LRU keyed on

    (index_version, mutation_seq, nprobe, k, metric, canonical row hash)

is therefore **correct by construction** between version/sequence points:
a key can only hit while both tags match, a swap clears the cache
outright (``MicroBatcher.swap_model``), and any acknowledged mutation
moves ``mutation_seq`` so every stale key silently becomes unreachable
and ages out of the LRU. ``nprobe`` rides the key so an approximate
(ivf-rung) answer is only replayed at the probe-policy operating point
that produced it — a cache hit is bit-identical to what a fresh dispatch
at the same tags would return (pinned by tests/test_bucketing.py).

What is cached is the RETRIEVAL ``(dists [q,k], indices [q,k])`` plus the
answering rung, not the per-kind payload: predict and kneighbors share
one retrieval (predict = kneighbors + a host vote), so one entry serves
both kinds. Capacity is measured in cached query ROWS
(``--result-cache-rows``; an entry of q rows charges q), because memory
scales with rows x k, not entries.

When NOT to enable it (docs/SERVING.md): high-entropy query streams
(embeddings of novel inputs, raw sensor rows) never repeat a row, so
every lookup is a paid miss — the hash of the feature bytes — with zero
hits. The flag defaults to 0, which constructs nothing
(scripts/check_disabled_overhead.py pins it).

Thread model: lookups and inserts run on the single batcher worker;
``clear`` (hot reload) and ``stats`` (healthz/debug scrapes) may run on
other threads — all state sits under one lock, and the hot-path cost is
one hash + one OrderedDict move per request.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional, Tuple

from knn_tpu import obs


def query_digest(features) -> bytes:
    """Canonical digest of a query block: the batcher admits features as
    C-contiguous float32 (``MicroBatcher.submit``), so the raw bytes ARE
    the canonical form — equal arrays always collide, bit-different
    floats (including -0.0 vs 0.0 and distinct NaN payloads) never do,
    which is exactly the "identical query" contract exact-match needs."""
    h = hashlib.blake2b(digest_size=16)
    h.update(features.tobytes())
    return h.digest()


class ResultCache:
    """Bounded LRU of retrieval answers, capacity in query rows."""

    def __init__(self, max_rows: int):
        if max_rows < 1:
            raise ValueError(f"max_rows must be >= 1, got {max_rows}")
        self.max_rows = int(max_rows)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._rows = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- the hot path (batcher worker) ------------------------------------

    def key(self, version, seq, nprobe, features) -> tuple:
        return (version, seq, nprobe, features.shape,
                query_digest(features))

    def get(self, key: tuple) -> "Optional[Tuple]":
        """``(dists, idx, rung)`` on a hit (arrays are the cached copies —
        callers slice/read, never mutate), None on a miss. Counts both."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        if ent is not None:
            obs.counter_add(
                "knn_cache_hits_total",
                help="serving requests answered from the exact-match "
                     "result cache (no device dispatch)",
            )
            return ent
        obs.counter_add(
            "knn_cache_misses_total",
            help="result-cache lookups that fell through to a dispatch",
        )
        return None

    def put(self, key: tuple, dists, idx, rung: str) -> None:
        """Insert one answered request's retrieval slice. Oversized
        entries (rows > max_rows) are not cached at all — they would
        evict the whole cache to store one request."""
        rows = int(dists.shape[0])
        if rows > self.max_rows:
            return
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._rows -= old[0].shape[0]
            self._entries[key] = (dists, idx, rung)
            self._rows += rows
            while self._rows > self.max_rows and self._entries:
                _, (d, _i, _r) = self._entries.popitem(last=False)
                self._rows -= d.shape[0]
                evicted += 1
            self.evictions += evicted
        if evicted:
            obs.counter_add(
                "knn_cache_evictions_total", evicted,
                help="result-cache entries evicted by the row-capacity LRU",
            )

    # -- lifecycle / reporting --------------------------------------------

    def clear(self) -> int:
        """Drop everything — the swap/rebase invalidation path (a new
        index version makes every cached answer unreachable anyway; the
        clear returns the memory instead of waiting for LRU aging).
        Returns how many entries were dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._rows = 0
        return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "rows": self._rows,
                "max_rows": self.max_rows,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
