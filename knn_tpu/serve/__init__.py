"""Serving subsystem: long-lived, low-latency request serving.

The reference — and every layer grown on top of it until now — is a batch
CLI: parse ARFF, classify once, print, exit. That shape pays the expensive
one-time costs (ARFF parse, host pad/transpose, device upload, first-call
compile — BENCH_r05 measures train upload alone at ~537 ms) on EVERY
invocation. A production KNN service pays them once and then answers many
small concurrent requests; the pipelined kneighbors path already runs at
~9.5 ms/call vs ~61 ms for naive per-call dispatch (BENCH_r05), and this
package is the machinery that gets concurrent callers onto that path:

- :mod:`knn_tpu.serve.batcher`  — the dynamic micro-batcher: a thread-safe
  request queue that coalesces concurrent ``predict``/``kneighbors``
  requests into one padded device batch under a ``max_batch`` /
  ``max_wait_ms`` policy, dispatches through the model's existing engine
  selection, and scatters per-request slices back to waiting
  :class:`~knn_tpu.models.knn.AsyncResult` futures — bit-identical to the
  synchronous API (pinned by tests/test_serve.py);
- :mod:`knn_tpu.serve.artifact` — the versioned index artifact store:
  save/load of a fitted model as ``arrays.npz`` + a JSON manifest
  (k/metric/engine/dtype/schema hash), so a server boots from a prebuilt
  index without re-parsing ARFF, plus the warmup step that triggers
  first-call compilation for the configured batch shapes before the server
  reports ready;
- :mod:`knn_tpu.serve.server`   — the HTTP front-end (stdlib
  ``ThreadingHTTPServer``, no new dependencies): ``/predict``,
  ``/kneighbors``, ``/healthz``, ``/metrics`` (Prometheus text straight
  from :mod:`knn_tpu.obs`), ``/admin/reload`` (hot index swap with
  rollback), with admission control wired through the resilience
  taxonomy — bounded queue → :class:`OverloadError` → 429, per-request
  deadline → :class:`DeadlineExceededError` → 504.

The process **self-heals** (docs/SERVING.md §Ops runbook): the worker's
dispatch walks an in-loop degradation ladder behind a circuit breaker
(bit-identical answers from a lower rung under device failure, OOM
halves ``max_batch`` in place, half-open probes re-promote the fast
rung), a supervisor restarts a dead worker, ``SIGTERM`` drains
gracefully within ``--drain-timeout-s``, and ``SIGHUP`` hot-reloads the
index — all soaked by ``make chaos-soak`` under seeded fault injection.

CLI: ``python -m knn_tpu save-index train.arff index/`` then
``python -m knn_tpu serve index/``. Policy, artifact format, and endpoint
contract: docs/SERVING.md.
"""

from __future__ import annotations

from knn_tpu.serve.batcher import MicroBatcher
from knn_tpu.serve.artifact import load_index, save_index, warmup

__all__ = ["MicroBatcher", "load_index", "save_index", "warmup"]
