"""The ``knn_tpu serve`` HTTP front-end (stdlib only — no new deps).

A :class:`ServeApp` owns the loaded model, the micro-batcher, and the
readiness flag; :class:`KNNServer` (a ``ThreadingHTTPServer``) gives every
connection a handler thread that does nothing device-side itself — it
validates, enqueues on the batcher, and waits on the request future, so
the batcher's single worker thread stays the only device dispatcher.

Endpoint contract (docs/SERVING.md):

- ``POST /predict``     body ``{"instances": [[...], ...]}`` (rows of
  ``num_features`` floats; optional ``"deadline_ms"`` overriding the
  server default) → ``{"predictions": [...]}``.
- ``POST /kneighbors``  same body → ``{"distances": [[...]], "indices":
  [[...]]}`` (k per row, model order).
- ``GET /healthz``      → 200 ``{"ready": true, ...}`` once warmup has
  compiled the configured batch shapes; 503 before that (so a load
  balancer never routes a request into a multi-second first-call
  compile).
- ``GET /metrics``      → the Prometheus text exposition straight from
  the global :mod:`knn_tpu.obs` registry (``knn_serve_*`` plus every
  model/backend metric the process has recorded).

Admission control maps the resilience taxonomy to status codes:
:class:`OverloadError` (bounded queue full) → **429**,
:class:`DeadlineExceededError` (queue or result wait expired) → **504**,
``ValueError``/bad JSON → **400**, any other typed failure → **500** with
the error class name in the body. Always a JSON body, never a traceback.
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.resilience.errors import DeadlineExceededError, OverloadError
from knn_tpu.serve import artifact
from knn_tpu.serve.batcher import MicroBatcher

#: Request bodies past this are rejected 413 before json.loads allocates.
MAX_BODY_BYTES = 64 * 1024 * 1024


class ServeApp:
    """Everything the handlers need, built once at boot."""

    def __init__(self, model, *, max_batch: int = 256,
                 max_wait_ms: float = 2.0, max_queue_rows: int = 4096,
                 deadline_ms: Optional[float] = None):
        self.model = model
        self.family = (
            "classifier" if isinstance(model, KNNClassifier) else "regressor"
        )
        self.deadline_ms = deadline_ms
        self.batcher = MicroBatcher(
            model, max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_queue_rows=max_queue_rows,
        )
        self.ready = False
        self.started_unix = time.time()
        self.warmup_ms: dict = {}

    def warm(self, batch_sizes=None) -> dict:
        """Compile the serving dispatch shapes, then report ready.

        One kind suffices: predict warmup runs the retrieval executable
        (kneighbors) plus a host-side vote that compiles nothing, so a
        separate kneighbors pass would re-dispatch the identical
        executable for zero extra compilation."""
        if batch_sizes is None:
            batch_sizes = (1, self.batcher.max_batch)
        self.warmup_ms = artifact.warmup(
            self.model, batch_sizes=batch_sizes, kinds=("predict",)
        )
        self.ready = True
        return self.warmup_ms

    def close(self) -> None:
        self.ready = False
        self.batcher.close()

    def health(self) -> dict:
        return {
            "ready": self.ready,
            "family": self.family,
            "k": self.model.k,
            "train_rows": self.model.train_.num_instances,
            "num_features": self.model.train_.num_features,
            "uptime_s": round(time.time() - self.started_unix, 1),
            "warmup_ms": self.warmup_ms,
        }


class _Handler(BaseHTTPRequestHandler):
    server_version = "knn-tpu-serve/1"
    protocol_version = "HTTP/1.1"
    # Socket timeout: a client stalling mid-body (or idling on keep-alive)
    # must release its handler thread — without this, N slow connections
    # pin N threads forever and starve the process before the batcher's
    # admission control ever engages.
    timeout = 60

    @property
    def app(self) -> ServeApp:
        return self.server.app  # type: ignore[attr-defined]

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        # Per-request stderr lines at serving rates are an accidental DoS
        # on the process's own stderr; the /metrics endpoint is the log.
        pass

    def _send(self, status: int, payload: dict, content_type="application/json"):
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, status: int, text: str, content_type: str):
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- GET ---------------------------------------------------------------

    def do_GET(self):  # noqa: N802 — stdlib dispatch name
        if self.path == "/healthz":
            h = self.app.health()
            self._send(200 if h["ready"] else 503, h)
        elif self.path == "/metrics":
            self._send_text(
                200, obs.registry().to_prometheus(),
                "text/plain; version=0.0.4",
            )
        else:
            self._send(404, {"error": f"no such endpoint: {self.path}"})

    # -- POST --------------------------------------------------------------

    def do_POST(self):  # noqa: N802 — stdlib dispatch name
        # Error replies sent before the body was drained must also close
        # the connection: with HTTP/1.1 keep-alive the unread bytes would
        # be parsed as the next request line.
        if self.path not in ("/predict", "/kneighbors"):
            self.close_connection = True
            self._send(404, {"error": f"no such endpoint: {self.path}"})
            return
        kind = self.path[1:]
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if length <= 0:
            self.close_connection = True
            self._send(400, {"error": "a JSON body with Content-Length is "
                                      "required"})
            return
        if length > MAX_BODY_BYTES:
            self.close_connection = True
            self._send(413, {"error": f"body {length} B exceeds the "
                                      f"{MAX_BODY_BYTES} B bound"})
            return
        try:
            body = json.loads(self.rfile.read(length))
            instances = body["instances"]
            deadline_ms = body.get("deadline_ms", self.app.deadline_ms)
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
                if not math.isfinite(deadline_ms) or deadline_ms <= 0:
                    raise ValueError(f"deadline_ms must be a finite value "
                                     f"> 0, got {deadline_ms}")
            x = np.asarray(instances, dtype=np.float32)
        except (KeyError, TypeError, ValueError) as e:
            self._send(400, {"error": f"bad request body: {e}"})
            return
        t0 = time.monotonic()
        try:
            handle = self.app.batcher.submit(x, kind, deadline_ms=deadline_ms)
        except OverloadError as e:
            self._send(429, {"error": str(e)})
            return
        except ValueError as e:  # shape/kind rejection
            self._send(400, {"error": str(e)})
            return
        timeout = deadline_ms / 1e3 if deadline_ms is not None else None
        try:
            value = handle.result(timeout=timeout)
        except DeadlineExceededError as e:
            self._send(504, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — the batcher delivers ANY
            # failure to the future (that is its worker-survival contract);
            # whatever arrives must become the documented JSON 500, never a
            # handler traceback + dropped connection.
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        ms = round((time.monotonic() - t0) * 1e3, 3)
        if kind == "predict":
            self._send(200, {"predictions": np.asarray(value).tolist(),
                             "ms": ms})
        else:
            dists, idx = value
            self._send(200, {
                "distances": np.asarray(dists).tolist(),
                "indices": np.asarray(idx).tolist(),
                "ms": ms,
            })


class KNNServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the :class:`ServeApp`. Daemon handler
    threads: a hung client connection must not block process exit."""

    daemon_threads = True

    def __init__(self, address, app: ServeApp):
        super().__init__(address, _Handler)
        self.app = app

    def handle_error(self, request, client_address):
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return  # the client went away mid-response; not a server error
        super().handle_error(request, client_address)


def make_server(app: ServeApp, host: str = "127.0.0.1",
                port: int = 0) -> KNNServer:
    """Bind (port 0 → ephemeral; read ``server.server_address``)."""
    return KNNServer((host, port), app)


def serve_forever(server: KNNServer, *, banner=None) -> int:
    """Run until SIGINT/SIGTERM, then shut down cleanly (stop accepting,
    drain the batcher). Returns 0 — the `knn_tpu serve` main loop."""
    import signal

    def on_signal(signum, frame):
        # shutdown() must come from another thread than serve_forever's.
        threading.Thread(target=server.shutdown, daemon=True).start()

    previous = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            previous[sig] = signal.signal(sig, on_signal)
        except ValueError:
            pass  # not the main thread (embedded use): caller manages stop
    if banner:
        print(banner, flush=True)
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        server.server_close()
        server.app.close()
    return 0
