"""The ``knn_tpu serve`` HTTP front-end (stdlib only — no new deps).

A :class:`ServeApp` owns the loaded model, the micro-batcher, and the
readiness flag; :class:`KNNServer` (a ``ThreadingHTTPServer``) gives every
connection a handler thread that does nothing device-side itself — it
validates, enqueues on the batcher, and waits on the request future, so
the batcher's single worker thread stays the only device dispatcher.

Endpoint contract (docs/SERVING.md):

- ``POST /predict``     body ``{"instances": [[...], ...]}`` (rows of
  ``num_features`` floats; optional ``"deadline_ms"`` overriding the
  server default) → ``{"predictions": [...], "index_version": ...}``.
- ``POST /kneighbors``  same body → ``{"distances": [[...]], "indices":
  [[...]], ...}`` (k per row, model order).
- ``GET /healthz``      → 200 ``{"ready": true, "draining": false,
  "index_version": ..., "breaker": ..., ...}`` once warmup has compiled
  the configured batch shapes; 503 before that (so a load balancer never
  routes a request into a multi-second first-call compile) and again
  while draining.
- ``GET /metrics``      → the Prometheus text exposition straight from
  the global :mod:`knn_tpu.obs` registry (``knn_serve_*`` plus every
  model/backend metric the process has recorded); with ``Accept:
  application/openmetrics-text``, the OpenMetrics exposition whose
  ``knn_serve_request_ms`` buckets carry ``trace_id`` exemplars.
- ``GET /debug/requests`` / ``GET /debug/slowest`` → the flight
  recorder's last-N / slowest-K per-request timelines
  (``?id=<request_id>`` resolves one, ``?format=perfetto`` exports
  Chrome ``trace_event`` JSON — docs/OBSERVABILITY.md).
- ``GET /debug/quality`` → the answer-quality join (docs/OBSERVABILITY.md
  §Quality & drift): shadow-scored recall/accuracy and divergence counts
  per answering rung (``obs/quality.py``), the query-drift summary vs the
  artifact's training sketch (``obs/drift.py`` — a pre-sketch artifact
  reports the distinct ``baseline: "absent"`` state), and the ``quality``
  SLO burn rates, in one payload — the page an operator reads when a
  recall regression is suspected (docs/SERVING.md runbook).
- ``GET /debug/capacity`` → the cost & capacity join (docs/OBSERVABILITY.md
  §Cost & capacity): per-class device-cost totals and attribution
  conservation (``obs/accounting.py``), the duty-cycle / occupancy /
  rate-ring capacity summary and the headroom model's sustainable-QPS
  estimate (``obs/capacity.py``), plus the live batching policy — the page
  an operator reads to size ``max_batch`` and replica counts
  (docs/SERVING.md §Capacity-planning a replica). Always 200; the layers
  report ``null`` while ``--cost-accounting off``.
- ``GET /debug/profile?ms=N`` → an on-demand ``jax.profiler`` capture
  (``obs/devprof.py``): the handler holds the window open for N ms
  (default 200, cap 10 s) while the other handler threads keep serving,
  then returns ONE Perfetto-loadable trace in which the serve host spans
  (via the tracer's ``TraceAnnotation`` pass-through) and the device/XLA
  events share a time axis. 409 while another capture runs.

Every request is tagged with a **request id** — the ``x-request-id``
header when the client sent a valid one (≤128 printable ASCII chars;
anything else is a 400), generated at admission otherwise — echoed on
EVERY response (header + JSON body, errors included), resolvable in the
flight recorder, stamped on latency-histogram exemplars, and keyed into
the optional ``--access-log`` (one JSON line per terminal outcome,
written by the handler thread after the response — off the dispatch hot
path). Terminal outcomes also feed the SLO tracker
(:mod:`knn_tpu.obs.slo` — availability / latency / fast-rung burn rates
in ``/healthz`` and ``knn_slo_*`` gauges).
- ``POST /admin/reload`` body ``{}`` or ``{"index": PATH}`` → hot index
  reload: load + validate the artifact off the serving path, warm it in
  the background, atomically swap; ANY failure rolls back with the old
  index still serving. 409 while another reload is in flight. ``SIGHUP``
  triggers the same reload from the boot index path.
- ``POST /admin/capture`` body ``{"action": "start"|"stop"}`` → arm /
  finalize a workload-capture window (``--capture-dir``; 404 while off,
  409 on a state contradiction); ``stop`` returns the finalized workload
  artifact's path + counts. ``GET /debug/capture`` → the capture status
  (armed window, burn trigger, last artifact; always 200, ``enabled:
  false`` while off). docs/OBSERVABILITY.md §Workload capture & replay.

Admission control maps the resilience taxonomy to status codes:
:class:`OverloadError` (bounded queue full) → **429** (**503** while
draining — the load balancer's cue to route away, not retry here),
:class:`DeadlineExceededError` (queue or result wait expired) → **504**,
``ValueError``/bad JSON → **400**, any other typed failure → **500** with
the error class name in the body. Always a JSON body, never a traceback.

Signals (the ops runbook, docs/SERVING.md): **SIGTERM** = graceful drain
(the LISTENER closes first — new connects are refused at the TCP layer,
so a fleet router demotes this replica immediately — then healthz flips
to 503 ``draining``, in-flight answered within ``--drain-timeout-s``,
remainders failed 504, exit 0); **SIGINT** = fast clean stop;
**SIGHUP** = hot reload.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import random
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from knn_tpu import obs
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs import reqtrace
from knn_tpu.obs.slo import SLOTracker
from knn_tpu.resilience.errors import (
    DataError,
    DeadlineExceededError,
    OverloadError,
    ShedByPolicy,
)
from knn_tpu.serve import artifact
from knn_tpu.serve.batcher import MicroBatcher

#: Request bodies past this are rejected 413 before json.loads allocates.
MAX_BODY_BYTES = 64 * 1024 * 1024


class AccessLog:
    """One structured JSON line per terminal request outcome.

    Lines are written by the HANDLER thread after its response went out —
    never by the batcher worker, so logging cost stays off the dispatch
    hot path. ``path='-'`` logs to stderr; anything else appends to the
    file (line-buffered, one lock — the lines are small and terminal, so
    contention is bounded by response rate, not dispatch rate)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._file = (sys.stderr if path == "-"
                      else open(path, "a", buffering=1, encoding="utf-8"))

    def write(self, record: dict) -> None:
        line = json.dumps(record, separators=(",", ":"), default=str)
        with self._lock:
            try:
                self._file.write(line + "\n")
            except (OSError, ValueError):
                pass  # a full disk / closed file must never fail a request

    def close(self) -> None:
        if self._file is not sys.stderr:
            with self._lock:
                try:
                    self._file.close()
                except OSError:
                    pass


class ReloadInProgress(OverloadError):
    """A hot reload is already running; the admin endpoint maps this to
    HTTP 409 (one swap at a time keeps rollback reasoning trivial)."""


class ServeApp:
    """Everything the handlers need, built once at boot."""

    def __init__(self, model, *, max_batch: int = 256,
                 max_wait_ms: float = 2.0, max_queue_rows: int = 4096,
                 deadline_ms: Optional[float] = None,
                 index_path: Optional[str] = None,
                 index_version: Optional[str] = None,
                 flight_recorder_size: int = 256, slowest_k: int = 32,
                 access_log: Optional[str] = None,
                 slo: Optional[SLOTracker] = None,
                 shadow_rate: float = 0.0, drift_rate: float = 0.0,
                 quality_queue: int = 256, quality_seed: int = 0,
                 reference_sketch: Optional[dict] = None,
                 cost_accounting: bool = False,
                 capacity_window_s: int = 60,
                 ivf_probes: Optional[int] = None,
                 ivf_recall_floor: float = 0.95,
                 mutable: bool = False, delta_cap: int = 4096,
                 compact_threshold: int = 1024,
                 compact_interval_s: float = 30.0,
                 mutable_current: Optional[dict] = None,
                 mutable_base_dir=None,
                 capture_dir: Optional[str] = None,
                 capture_rate: float = 1.0,
                 capture_max_requests: int = 65536,
                 capture_queue: int = 1024,
                 capture_burn_threshold: Optional[float] = None,
                 capture_burn_objective: str = "availability",
                 capture_burn_window_s: float = 60.0,
                 batch_buckets=None, result_cache_rows: int = 0,
                 follower_of: Optional[str] = None,
                 replicate_to=None, replicate_ack: str = "any",
                 replicate_ack_timeout_s: float = 5.0,
                 shards: Optional[int] = None,
                 priority_map: Optional[dict] = None,
                 brownout: bool = False,
                 autotune_interval_s: Optional[float] = None,
                 history_dir: Optional[str] = None,
                 history_interval_s: float = 5.0,
                 history_retention_s: float = 3600.0,
                 alert_rules=None):
        self._previous_buckets = None
        self._installed_buckets = False
        if batch_buckets is not None:
            # Make the param REAL for embedders: the compiled-shape pad,
            # the executable-cache key, and padded-row accounting all
            # resolve from the process-wide ladder
            # (models/knn.query_padded_rows) — a ServeApp handed a
            # ladder must install it, or /healthz would report a policy
            # that is not in effect. (The CLI installs the same ladder
            # earlier, before load; set_query_buckets is idempotent.)
            # close() restores the previous ladder so a later
            # non-bucketed app (or direct model call) in the same
            # process is not padded by a policy nothing reports.
            from knn_tpu.models.knn import query_buckets, set_query_buckets

            self._previous_buckets = query_buckets()
            set_query_buckets(batch_buckets)
            self._installed_buckets = True
        # Mesh-sharded serving (knn_tpu/shard/, docs/SERVING.md §Sharded
        # serving): --shards partitions the index across the device mesh
        # behind the same rung ladder. None (the default) constructs
        # NOTHING — no shard package import, no wrapped model, no
        # knn_shard_* instruments (scripts/check_disabled_overhead.py
        # pins it). The count arrives RESOLVED (the CLI maps "auto" to
        # the device count).
        self.shards: Optional[int] = None
        if shards is not None:
            model = self._wrap_shards_new(model, int(shards))
            self.shards = model.shard_plan_.num_shards
        self.model = model
        self.family = (
            "classifier" if isinstance(model, KNNClassifier) else "regressor"
        )
        self.deadline_ms = deadline_ms
        self.index_path = index_path
        self.index_version = index_version
        # Approximate serving (docs/INDEXES.md): --ivf-probes opts in to
        # the ivf rung over the artifact's IVF partition. Validated FIRST
        # — a DataError here must abort before any worker thread exists.
        # None (the default, and always for partition-less artifacts)
        # constructs NOTHING: no IVFServing, no probe policy, no
        # knn_ivf_* instruments (scripts/check_disabled_overhead.py).
        if ivf_probes is not None:
            from knn_tpu.index.ivf import IVF_ATTR, IVFServing

            partition = getattr(model, IVF_ATTR, None)
            if partition is None:
                raise DataError(
                    "--ivf-probes needs an artifact with an IVF partition "
                    "(format 3, built with `save-index --ivf-cells N`); "
                    "this one is exact-only"
                )
            if not 1 <= ivf_probes <= partition.num_cells:
                raise DataError(
                    f"--ivf-probes {ivf_probes} out of range: the "
                    f"partition has {partition.num_cells} cells"
                )
        self.ivf_recall_floor = float(ivf_recall_floor)
        # Request tracing: the flight recorder holds the last-N completed
        # request timelines + a slowest-K reservoir (/debug/requests,
        # /debug/slowest). Size 0 disables the layer entirely (the batcher
        # then pays one `trace is None` predicate per call site).
        self.recorder = (
            reqtrace.FlightRecorder(flight_recorder_size, slowest_k)
            if flight_recorder_size > 0 else None
        )
        self.slo = slo if slo is not None else SLOTracker()
        self.access_log = AccessLog(access_log) if access_log else None
        # Answer-quality layers (obs/quality.py, obs/drift.py): rate 0
        # (the default) constructs NOTHING — no worker thread, no queue,
        # no instruments; the batcher then pays one `is None` predicate
        # per served request (the zero-cost-when-disabled contract,
        # scripts/check_disabled_overhead.py).
        # Drift first: it is the layer that VALIDATES (a malformed or
        # wrong-width manifest sketch raises here), and a construction
        # abort must not leave an already-started scorer thread behind.
        if drift_rate > 0:
            from knn_tpu.obs.drift import DriftMonitor

            self.drift = DriftMonitor(
                reference_sketch, rate=drift_rate,
                num_features=model.train_.num_features,
                queue_cap=quality_queue, seed=quality_seed,
            )
        else:
            self.drift = None
        if shadow_rate > 0:
            from knn_tpu.obs.quality import ShadowScorer

            self.quality = ShadowScorer(
                shadow_rate, queue_cap=quality_queue, seed=quality_seed,
                slo=self.slo,
                # The ivf rung is held to its recall FLOOR, not the exact
                # rungs' bit-exact bar (obs/quality.py) — the quality SLI
                # this feeds is what the probe policy closes its loop on.
                approx_floors=({"ivf": self.ivf_recall_floor}
                               if ivf_probes is not None else None),
            )
        else:
            self.quality = None
        if ivf_probes is not None:
            self.ivf = IVFServing(
                ivf_probes, partition.num_cells, slo=self.slo,
                recall_floor=self.ivf_recall_floor,
            )
        else:
            self.ivf = None
        # Cost & capacity (obs/accounting.py, obs/capacity.py): off (the
        # embedded default) constructs NOTHING — no accountant, no
        # tracker, no knn_cost_*/knn_capacity_* instruments, no x-knn-class
        # header parsing; the batcher then pays one `is None` predicate
        # per call site (scripts/check_disabled_overhead.py pins it).
        if cost_accounting:
            from knn_tpu.obs.accounting import CostAccountant
            from knn_tpu.obs.capacity import CapacityTracker

            self.accounting = CostAccountant()
            self.capacity = CapacityTracker(
                max_batch, window_s=capacity_window_s)
        else:
            self.accounting = None
            self.capacity = None
        # Mutable serving (knn_tpu/mutable/, docs/INDEXES.md §Mutable
        # tier): --mutable on builds the delta/tombstone engine (replaying
        # any existing epoch logs — the crash-recovery path) and the
        # background compactor. Off (the default) constructs NOTHING: no
        # engine, no compactor thread, no knn_mutable_* instruments, no
        # per-dispatch snapshot/merge work
        # (scripts/check_disabled_overhead.py pins it).
        if mutable:
            from knn_tpu.mutable.engine import MutableEngine

            if index_path is None:
                raise DataError(
                    "mutable serving needs an artifact directory for its "
                    "write-ahead epoch log; build one with `knn_tpu "
                    "save-index` and boot `serve INDEX --mutable on`"
                )
            self.mutable = MutableEngine(
                model, index_path, delta_cap=delta_cap,
                current=mutable_current, base_dir=mutable_base_dir,
                version=index_version,
            )
        else:
            self.mutable = None
        # Fleet replication (knn_tpu/fleet/, docs/SERVING.md §Running a
        # replica set): --follower-of makes this process a read-only
        # follower applying primary-shipped WAL records; --replicate-to
        # makes it the primary fanning its WAL out. Neither (the default)
        # constructs NOTHING — no fleet import, no shipper threads, no
        # knn_fleet_* instruments (scripts/check_disabled_overhead.py).
        if follower_of is not None or replicate_to:
            if follower_of is not None and replicate_to:
                raise DataError(
                    "--follower-of and --replicate-to are contradictory: "
                    "a replica is born EITHER the primary or a follower "
                    "(promotion flips the role later)"
                )
            if self.mutable is None:
                raise DataError(
                    "fleet replication ships the mutable tier's "
                    "write-ahead log; boot with --mutable on"
                )
            from knn_tpu.fleet.replica import FleetReplica

            self.fleet = FleetReplica(
                self.mutable,
                role="follower" if follower_of is not None else "primary",
                primary_url=follower_of,
                replicate_to=tuple(replicate_to or ()),
                ack_mode=replicate_ack,
                ack_timeout_s=replicate_ack_timeout_s,
            )
        else:
            self.fleet = None
        # Workload capture (obs/workload.py, docs/OBSERVABILITY.md
        # §Workload capture & replay): --capture-dir opts in to the
        # replayable traffic recorder — windows armed by POST
        # /admin/capture or the SLO burn trigger land versioned workload
        # artifacts `knn_tpu replay` re-drives. No capture_dir (the
        # default) constructs NOTHING: no queue, no consumer thread, no
        # knn_workload_* instruments, no per-request work
        # (scripts/check_disabled_overhead.py pins it).
        if capture_dir is not None:
            from knn_tpu.obs.workload import WorkloadCapture

            self.workload = WorkloadCapture(
                capture_dir, num_features=model.train_.num_features,
                k=model.k, rate=capture_rate,
                max_requests=capture_max_requests,
                queue_cap=capture_queue, slo=self.slo,
                burn_threshold=capture_burn_threshold,
                burn_objective=capture_burn_objective,
                burn_window_s=capture_burn_window_s,
                policy={"max_batch": max_batch,
                        "max_wait_ms": max_wait_ms,
                        "max_queue_rows": max_queue_rows},
                index_version=index_version,
            )
        else:
            self.workload = None
        # Overload control plane (knn_tpu/control/, docs/RESILIENCE.md
        # §Degradation order). --priority installs priority admission:
        # under sustained pressure the LOWEST-priority request classes
        # shed first (typed ShedByPolicy 429 with a headroom-derived
        # Retry-After) while protected classes keep admitting. No flag
        # (the default) constructs NOTHING — no control import, no
        # knn_control_* instruments, no controller threads; the batcher
        # pays one `is None` predicate per submit
        # (scripts/check_disabled_overhead.py pins it).
        if priority_map:
            if self.accounting is None:
                raise DataError(
                    "--priority sheds by request class, and classes are "
                    "only parsed while cost accounting runs; boot with "
                    "--cost-accounting"
                )
            from knn_tpu.control.admission import PriorityAdmission

            self.admission = PriorityAdmission(
                priority_map, slo=self.slo, capacity=self.capacity)
        else:
            self.admission = None
        # --brownout builds the reversible-degradation ladder from
        # whichever quality/cost knobs are actually wired on this serve:
        # sampling rates down, nprobe clamped to base, deadline
        # tightened — applied one per cooldown under pressure, every
        # step audited and walked back on recovery. Its headroom gate
        # (defer_background) also defers shadow/drift sampling and
        # compaction while offered load exceeds sustainable.
        if brownout:
            from knn_tpu.control.brownout import (
                BrownoutController,
                BrownoutStep,
            )

            steps = []
            if self.quality is not None:
                q, q_rate = self.quality, float(shadow_rate)
                steps.append(BrownoutStep(
                    "shadow_rate",
                    lambda q=q, r=q_rate: q.set_rate(r * 0.1),
                    lambda q=q, r=q_rate: q.set_rate(r),
                ))
            if self.drift is not None:
                d, d_rate = self.drift, float(drift_rate)
                steps.append(BrownoutStep(
                    "drift_rate",
                    lambda d=d, r=d_rate: d.set_rate(r * 0.1),
                    lambda d=d, r=d_rate: d.set_rate(r),
                ))
            if self.ivf is not None:
                pol = self.ivf.policy
                steps.append(BrownoutStep(
                    "ivf_probes_to_base",
                    lambda p=pol: p.set_brownout(True),
                    lambda p=pol: p.set_brownout(False),
                ))
            if self.deadline_ms is not None:
                base_deadline = float(self.deadline_ms)
                steps.append(BrownoutStep(
                    "deadline_tighten",
                    lambda d=base_deadline: setattr(
                        self, "deadline_ms", d * 0.5),
                    lambda d=base_deadline: setattr(
                        self, "deadline_ms", d),
                ))
            if not steps:
                raise DataError(
                    "--brownout needs at least one reversible knob on "
                    "this serve; enable --shadow-rate, --drift-rate, "
                    "--ivf-probes, or --deadline-ms"
                )
            self.brownout = BrownoutController(
                steps, slo=self.slo, capacity=self.capacity)
            if self.quality is not None:
                self.quality.set_defer(self.brownout.defer_background)
            if self.drift is not None:
                self.drift.set_defer(self.brownout.defer_background)
        else:
            self.brownout = None
        self.batcher = MicroBatcher(
            model, max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_queue_rows=max_queue_rows, index_version=index_version,
            recorder=self.recorder, quality=self.quality, drift=self.drift,
            accounting=self.accounting, capacity=self.capacity,
            ivf=self.ivf, mutable=self.mutable, workload=self.workload,
            buckets=batch_buckets, result_cache_rows=result_cache_rows,
            admission=self.admission,
        )
        if mutable:
            from knn_tpu.mutable.compact import Compactor

            self.compactor = Compactor(
                self.mutable, swap=self._mutable_swap,
                warm=self._warm_replacement, threshold=compact_threshold,
                interval_s=compact_interval_s,
                # Replicated primaries defer epoch pruning while a live
                # follower's cursor still needs those records (the
                # WAL-retention floor); a plain mutable serve passes
                # nothing and prunes exactly as before.
                retention_floor=(self.fleet.retention_floor
                                 if self.fleet is not None else None),
                # Brownout's headroom gate: compaction waits for
                # measured headroom instead of competing with overload
                # traffic (explicit /admin/compact still overrides).
                defer=(self.brownout.defer_background
                       if self.brownout is not None else None),
            )
        else:
            self.compactor = None
        # --autotune-interval-s re-tunes the batcher's max_wait_ms on a
        # cadence from the what-if frontier over LIVE captured arrivals,
        # applying a candidate only after captured-workload replay
        # verifies bit-identity (knn_tpu/control/autotune.py). Needs the
        # dispatch model (--cost-accounting) and the capture layer
        # (--capture-dir); unset constructs NOTHING.
        if autotune_interval_s is not None:
            if self.workload is None or self.capacity is None:
                raise DataError(
                    "--autotune-interval-s tunes max_wait_ms from "
                    "captured arrivals against the fitted dispatch "
                    "model; boot with --capture-dir and "
                    "--cost-accounting"
                )
            from knn_tpu.control.autotune import BatchAutotuner

            self.autotune = BatchAutotuner(
                self.batcher, self.capacity, self.workload,
                interval_s=float(autotune_interval_s),
            )
        else:
            self.autotune = None
        # Durable metrics history + declarative alerting (obs/history.py,
        # obs/alerts.py, docs/OBSERVABILITY.md §History & alerting):
        # --history-dir appends delta-encoded registry snapshots to an
        # on-disk segment ring (queryable live at /debug/history and
        # post-mortem via `knn_tpu history DIR`); --alert-rules evaluates
        # declarative rules on the same cadence. Neither flag (the
        # default) constructs NOTHING — no obs.history/alerts import, no
        # knn_history_*/knn_alerts_* instruments, no knn-history/
        # knn-alerts thread (scripts/check_disabled_overhead.py pins it).
        if history_dir is not None or alert_rules:
            from knn_tpu import obs as obs_mod
            from knn_tpu.obs import aggregate
            from knn_tpu.obs.alerts import AlertEngine
            from knn_tpu.obs.history import HistoryRecorder

            self.alerts = (AlertEngine(
                alert_rules, slo=self.slo, workload=self.workload,
                recorder=self.recorder, history_dir=history_dir,
            ) if alert_rules else None)

            def _history_sample():
                # slo.export refreshes the knn_slo_* gauges (so burn
                # lands in history) and workload.export finalizes any
                # pending timed capture window — an alert-armed window
                # completes within one snapshot interval even at zero
                # traffic.
                self.slo.export()
                if self.workload is not None:
                    self.workload.export()
                if not obs_mod.enabled():
                    return []
                return aggregate.snapshot_registry()

            self.history = HistoryRecorder(
                history_dir, interval_s=history_interval_s,
                retention_s=history_retention_s, source="serve",
                sample_fn=_history_sample,
                on_sample=(
                    (lambda ts, view: self.alerts.evaluate(ts, view))
                    if self.alerts is not None else None),
            )
        else:
            self.history = None
            self.alerts = None
        self._bootstrap_lock = threading.Lock()
        self.ready = False
        self.draining = False
        self.started_unix = time.time()
        self.warmup_ms: dict = {}
        self.reloads = 0
        self._warm_sizes = None
        self._reload_lock = threading.Lock()
        self._inflight = 0
        self._inflight_cond = threading.Condition()

    @property
    def primary_rung(self) -> str:
        """The rung a healthy request is EXPECTED to ride — what the
        fast_rung SLI scores against: ``ivf`` when approximate serving is
        on (an ivf-answered request is the designed operating point, not
        a degradation), ``fast`` otherwise."""
        return "ivf" if self.ivf is not None else "fast"

    def warm(self, batch_sizes=None) -> dict:
        """Compile the serving dispatch shapes, then report ready.

        One kind suffices: predict warmup runs the retrieval executable
        (kneighbors) plus a host-side vote that compiles nothing, so a
        separate kneighbors pass would re-dispatch the identical
        executable for zero extra compilation. Under a ``--batch-buckets``
        ladder EVERY bucket pre-compiles here (one warmup row count per
        bucket pads to exactly that bucket's shape), so no user request
        ever pays a first-dispatch compile whatever batch the traffic
        forms."""
        if batch_sizes is None:
            buckets = self.batcher.buckets or ()
            batch_sizes = tuple(sorted(
                {1, self.batcher.max_batch, *buckets}))
        self._warm_sizes = tuple(batch_sizes)
        self.warmup_ms = artifact.warmup(
            self.model, batch_sizes=batch_sizes, kinds=("predict",)
        )
        if self.capacity is not None:
            self._seed_capacity_model()
        if self.compactor is not None:
            # Only after warmup: a compaction before ready would compile
            # against the batcher's serving shapes anyway.
            self.compactor.start()
        self.ready = True
        return self.warmup_ms

    @staticmethod
    def _wrap_shards_new(model, shards: int):
        from knn_tpu.shard.model import make_sharded

        return make_sharded(model, shards)

    def _wrap_shards(self, model):
        """Shard a REPLACEMENT model (compaction fold, hot reload,
        bootstrap) when this app serves sharded. Memoized on the
        unsharded instance so the warm pass and the swap share one
        wrapped twin (and its per-shard executable caches) — wrapping
        twice would throw the warmup compiles away."""
        if self.shards is None or getattr(
                model, "shard_plan_", None) is not None:
            return model
        tw = getattr(model, "_sharded_twin", None)
        if tw is None:
            tw = self._wrap_shards_new(model, self.shards)
            model._sharded_twin = tw
        return tw

    def _warm_replacement(self, model) -> dict:
        """Compile a compaction's replacement model at the serving batch
        shapes, OFF the serving path (the reload warmup rule)."""
        return artifact.warmup(
            self._wrap_shards(model),
            batch_sizes=self._warm_sizes or (1, self.batcher.max_batch),
            kinds=("predict",),
        )

    def _mutable_swap(self, model, version, rebase_hook):
        """Compaction's swap callback: model swap + engine rebase in ONE
        batcher critical section (every dispatch snapshot sees exactly
        the old or the new (model, version, view) triple — the
        atomic-swap assertion of the mutable soak), then the app-level
        bookkeeping hot reload also does."""
        # The engine rebases onto the UNSHARDED replacement (they share
        # the train dataset instance); serving dispatch swaps to the
        # sharded twin — the same twin _warm_replacement compiled.
        model = self._wrap_shards(model)
        previous = self.batcher.swap_model(model, version,
                                           hook=rebase_hook)
        # Past this point the swap HAPPENED (run_once reports a failure
        # below as commit_failed, never rolled_back) — so the app-level
        # bookkeeping is best-effort: a capacity-seed probe error must
        # not turn a served generation into a misreported rollback.
        self.model = model
        self.index_version = version
        try:
            new_partition = getattr(model, "ivf_", None)
            if self.ivf is not None and new_partition is not None:
                self.ivf.set_num_cells(new_partition.num_cells)
            if self.capacity is not None:
                self._seed_capacity_model()
        except Exception as e:  # noqa: BLE001 — advisory layers only
            print(f"warning: post-compaction bookkeeping failed "
                  f"({type(e).__name__}: {e}); serving the new "
                  f"generation regardless (capacity/probe state refits "
                  f"from live traffic)", flush=True)
        return previous

    def bootstrap_from(self, source_url: str, *,
                       timeout_s: float = 60.0) -> dict:
        """``POST /admin/bootstrap``: abandon this replica's lineage and
        re-seed from ``source_url``'s current generation snapshot, with
        the old state serving until the atomic flip. Download and
        whole-file digest verification run entirely OUTSIDE any critical
        section (reads keep flowing); the durable commit (clear the old
        lineage's epochs, atomic ``CURRENT.json`` replace) runs inside
        the engine's reseed under the batcher's model-swap critical
        section — the same machinery a compaction swap trusts — and
        with the compaction lock held, so no concurrent fold can seal
        abandoned state and re-commit it afterwards. Any failure leaves
        the prior state serving (``swap_model`` restores the model on a
        hook raise; the staged directory is removed)."""
        if self.mutable is None:
            raise DataError(
                "bootstrap re-seeds the mutable tier; boot with "
                "`serve INDEX --mutable on`"
            )
        if self.fleet is not None and self.fleet.role == "primary":
            from knn_tpu.mutable.state import MutationConflict

            raise MutationConflict(
                "this replica is the primary — it is the snapshot "
                "SOURCE; bootstrap a follower from it instead"
            )
        from knn_tpu.fleet import bootstrap

        if not self._bootstrap_lock.acquire(blocking=False):
            raise ReloadInProgress("a bootstrap is already in progress")
        try:
            staged = bootstrap.download_snapshot(
                source_url, self.mutable.root, timeout_s=timeout_s)
            try:
                model = artifact.load_index(staged["tmp_dir"])
                version = staged["index_version"]
                _block, stable = artifact.read_mutable_block(
                    staged["tmp_dir"])
                self._warm_replacement(model)
                reseed_current = {
                    "generation": staged["generation"],
                    "folded_seq": staged["wal_cursor"],
                    "next_stable": staged["next_stable"],
                }
                committed: dict = {}

                def _commit():
                    committed.update(bootstrap.commit_snapshot(staged))

                hold = (self.compactor.exclusive()
                        if self.compactor is not None
                        else contextlib.nullcontext())
                with hold:
                    previous = self._mutable_swap(
                        model, version,
                        lambda: self.mutable.reseed(
                            model, stable, reseed_current,
                            version=version, commit=_commit),
                    )
            except Exception:
                import shutil

                shutil.rmtree(staged["tmp_dir"], ignore_errors=True)
                raise
            obs.counter_add(
                "knn_fleet_bootstrap_total",
                help="snapshot bootstrap installs this replica served "
                     "as the target, by outcome",
                outcome="ok",
            )
            return {"bootstrapped": True, "previous_version": previous,
                    **committed}
        except Exception:
            obs.counter_add(
                "knn_fleet_bootstrap_total",
                help="snapshot bootstrap installs this replica served "
                     "as the target, by outcome",
                outcome="failed",
            )
            raise
        finally:
            self._bootstrap_lock.release()

    def _seed_capacity_model(self) -> None:
        """Seed the headroom model's affine dispatch-cost fit
        (``obs/capacity.py``) with post-compile timed retrievals at 1 row
        and ``max_batch`` rows — the executables are warm (``warm`` just
        compiled them), so these walls measure dispatch, not compilation,
        and the model exists before the first real request arrives.
        Re-run after a hot reload: a new index has a new cost curve."""
        from knn_tpu.data.dataset import Dataset

        train = self.model.train_
        self.capacity.reset_seeds()
        for rows in sorted({1, self.batcher.max_batch}):
            if rows <= train.num_instances:
                feats = train.features[:rows]  # a view, no copy: this
                # runs at boot AND on the reload thread, where tiling a
                # large train matrix would be a pointless memory spike
            else:
                reps = -(-rows // train.num_instances)  # ceil
                feats = np.tile(train.features, (reps, 1))[:rows]
            ds = Dataset(feats, np.zeros(rows, np.int32))
            best = None
            for _ in range(2):  # best-of-2: stalls only ever add time
                t0 = time.monotonic()
                self.model.kneighbors(ds)
                wall = (time.monotonic() - t0) * 1e3
                best = wall if best is None else min(best, wall)
            self.capacity.seed_dispatch_model(rows, best)

    # -- hot reload --------------------------------------------------------

    def reload(self, path: Optional[str] = None) -> dict:
        """Hot-swap the serving index: load + validate ``path`` (default:
        the boot index path), warm it OFF the serving path, then swap
        atomically (one reference assignment in the batcher — every
        response reflects exactly one index version). Any failure —
        missing/corrupt/newer-format artifact, incompatible schema, a
        warmup compile error — raises typed and leaves the old index
        serving untouched (rollback is "never swapped")."""
        if self.mutable is not None:
            raise DataError(
                "hot reload is disabled under --mutable on: the mutable "
                "tier owns the artifact's lifecycle (its epoch log and "
                "generations); fold pending writes with POST "
                "/admin/compact instead"
            )
        if not self._reload_lock.acquire(blocking=False):
            raise ReloadInProgress("a reload is already in progress")
        try:
            target = path or self.index_path
            if target is None:
                raise DataError(
                    "no index path to reload from: the server was built "
                    "without one and the request named none"
                )
            t0 = time.monotonic()
            manifest = artifact.read_manifest(target)
            version = artifact.index_version(manifest)
            model = artifact.load_index(target)
            new_family = ("classifier" if isinstance(model, KNNClassifier)
                          else "regressor")
            if new_family != self.family:
                raise DataError(
                    f"{target}: artifact family '{new_family}' does not "
                    f"match the serving family '{self.family}' — that is a "
                    f"new deployment, not a reload"
                )
            if (model.train_.num_features
                    != self.model.train_.num_features):
                raise DataError(
                    f"{target}: feature width {model.train_.num_features} "
                    f"does not match the serving width "
                    f"{self.model.train_.num_features} — in-flight requests "
                    f"were validated against the old schema; rejecting the "
                    f"swap"
                )
            new_partition = getattr(model, "ivf_", None)
            if self.ivf is not None and new_partition is None:
                raise DataError(
                    f"{target}: this process serves the ivf rung "
                    f"(--ivf-probes) but the replacement artifact has no "
                    f"IVF partition — rebuild it with `save-index "
                    f"--ivf-cells N` or redeploy exact-only"
                )
            model = self._wrap_shards(model)
            # Warm in the background sense: the OLD index keeps serving
            # while these compiles run — they touch only the new model's
            # device cache.
            warmup_ms = artifact.warmup(
                model, batch_sizes=self._warm_sizes or (1, self.batcher.max_batch),
                kinds=("predict",),
            )
            if self.drift is not None:
                # BEFORE the swap: the new artifact's sketch is the new
                # drift baseline (it may also have none — a pre-sketch
                # rollback returns drift to its distinct no-baseline
                # state). A malformed/mismatched sketch raises here, so
                # the rollback reply's "old index still serving" stays
                # honest.
                self.drift.set_reference(artifact.reference_sketch(manifest))
            previous = self.batcher.swap_model(model, version)
            self.model = model
            self.index_version = version
            self.reloads += 1
            if self.ivf is not None:
                # Re-bound the probe policy: the new partition may have a
                # different cell count (the operating point clamps).
                self.ivf.set_num_cells(new_partition.num_cells)
            if self.capacity is not None:
                # The new index's dispatch-cost curve replaces the old
                # seeds (runs on the reload thread, off the serving path).
                self._seed_capacity_model()
            obs.counter_add(
                "knn_serve_reloads_total",
                help="hot index reloads, by outcome", outcome="ok",
            )
            return {
                "index_version": version,
                "previous_version": previous,
                "warmup_ms": warmup_ms,
                "ms": round((time.monotonic() - t0) * 1e3, 3),
            }
        except Exception as e:
            obs.counter_add(
                "knn_serve_reloads_total",
                help="hot index reloads, by outcome",
                outcome="rolled_back", type=type(e).__name__,
            )
            raise
        finally:
            self._reload_lock.release()

    # -- graceful drain ----------------------------------------------------

    @contextlib.contextmanager
    def track_request(self):
        """In-flight accounting for the drain barrier: a request is
        in-flight from body parse to response written."""
        with self._inflight_cond:
            self._inflight += 1
        try:
            yield
        finally:
            with self._inflight_cond:
                self._inflight -= 1
                self._inflight_cond.notify_all()

    def drain(self, timeout_s: float) -> dict:
        """The SIGTERM path: flip to draining (healthz 503, new admissions
        refused with typed :class:`OverloadError`), then answer every
        in-flight request within ``timeout_s``. Requests that cannot be
        answered in time are failed :class:`DeadlineExceededError` (their
        handlers respond 504) — every admitted request ends with exactly
        one terminal outcome. Returns a summary; the process still exits
        0 (a drained shutdown IS success)."""
        t0 = time.monotonic()
        self.draining = True
        self.batcher.begin_drain()
        deadline = t0 + timeout_s
        with self._inflight_cond:
            while self._inflight > 0 and time.monotonic() < deadline:
                self._inflight_cond.wait(
                    min(0.1, max(0.0, deadline - time.monotonic()))
                )
            remaining = self._inflight
        expired = 0
        if remaining > 0 or self.batcher.pending_rows() > 0:
            expired = self.batcher.fail_pending(
                DeadlineExceededError(
                    f"server drained for {timeout_s:.1f} s and shut down "
                    f"before this request could be dispatched"
                ),
                outcome="expired",
            )
            if expired:
                obs.counter_add(
                    "knn_serve_drain_expired_total", expired,
                    help="requests failed 504 because the drain window "
                         "closed",
                )
            # A short grace for the freshly-failed futures' handlers to
            # write their 504s before the process exits.
            grace = time.monotonic() + min(2.0, timeout_s)
            with self._inflight_cond:
                while self._inflight > 0 and time.monotonic() < grace:
                    self._inflight_cond.wait(0.05)
        # Re-read AFTER the expiry + grace: a request still in flight here
        # (e.g. mid-dispatch on a slow rung, not in the queue for
        # fail_pending to reach) will be cut off at process exit — the
        # drain was NOT clean and the summary must say so.
        with self._inflight_cond:
            remaining = self._inflight
        return {
            "drained_clean": expired == 0 and remaining == 0,
            "expired": expired,
            "inflight_at_exit": remaining,
            "ms": round((time.monotonic() - t0) * 1e3, 3),
        }

    def close(self) -> None:
        self.ready = False
        if self.history is not None:
            # FIRST, while every layer is still live: close() takes one
            # final snapshot so the durable record extends to shutdown
            # (the post-mortem contract `knn_tpu history` relies on).
            self.history.close()
        if self.alerts is not None:
            self.alerts.close()
        if self.autotune is not None:
            # Before the batcher: a mid-cycle capture/replay must not
            # race the worker teardown.
            self.autotune.close()
        if self.brownout is not None:
            self.brownout.close()
        if self.compactor is not None:
            self.compactor.stop()
        self.batcher.close()
        if self._installed_buckets:
            # Restore the process-global ladder this app installed (see
            # __init__) — AFTER the batcher worker has drained, so no
            # dispatch pads under a half-restored policy.
            from knn_tpu.models.knn import set_query_buckets

            set_query_buckets(self._previous_buckets)
            self._installed_buckets = False
        if self.workload is not None:
            # Finalizes any still-armed window first: an incident capture
            # must survive the shutdown the incident may have caused.
            self.workload.close()
        if self.fleet is not None:
            # Before the engine: shippers read the WAL the engine owns.
            self.fleet.close()
        if self.mutable is not None:
            self.mutable.close()
        if self.quality is not None:
            self.quality.close()
        if self.drift is not None:
            self.drift.close()
        if self.access_log is not None:
            self.access_log.close()

    def health(self) -> dict:
        h = {
            "ready": self.ready,
            "draining": self.draining,
            "index_version": self.index_version,
            "breaker": self.batcher.breaker.state,
            "rung": self.batcher.current_rung,
            "worker_restarts": self.batcher.restarts,
            "reloads": self.reloads,
            "family": self.family,
            "k": self.model.k,
            "train_rows": self.model.train_.num_instances,
            "num_features": self.model.train_.num_features,
            "uptime_s": round(time.time() - self.started_unix, 1),
            "warmup_ms": self.warmup_ms,
            # The dispatch-shape policy: the compiled bucket ladder (None
            # = legacy single pad quantum) and the exact-match result
            # cache's live counters (None — the distinct "cache absent"
            # state — while --result-cache-rows 0).
            "batching": {
                "buckets": (list(self.batcher.buckets)
                            if self.batcher.buckets else None),
                "result_cache": (self.batcher.cache.stats()
                                 if self.batcher.cache is not None
                                 else None),
            },
            # export() also refreshes the knn_slo_* gauges, so a /healthz
            # poller keeps them current between /metrics scrapes.
            "slo": self.slo.export(),
            "device": self._device_block(),
            "quality": self.quality_block(),
            # The approximate-serving summary (probe policy operating
            # point, partition shape); None for exact-only serves.
            "ivf": (self.ivf.export(self.model)
                    if self.ivf is not None else None),
            # The capacity summary (export() also refreshes the
            # knn_capacity_* gauges); None while --cost-accounting off.
            "capacity": (self.capacity.export()
                         if self.capacity is not None else None),
            # The mutable-tier summary (delta/tombstone/freshness/
            # compaction; export() refreshes the knn_mutable_* gauges).
            # None — the DISTINCT "mutable: absent" state, never
            # fabricated freshness numbers — while --mutable off.
            "mutable": (self.mutable.export()
                        if self.mutable is not None else None),
            # The workload-capture status (armed window, burn trigger,
            # last artifact). None — the distinct "capture: absent"
            # state — while --capture-dir is unset.
            "workload": (self.workload.export()
                         if self.workload is not None else None),
            # The shard topology + last-dispatch walls/stragglers
            # (knn_tpu/shard/). None — the distinct "unsharded" state —
            # while --shards is unset.
            "shard": self.shard_block(),
            # The replication role (knn_tpu/fleet/replica.py): role,
            # applied_seq, follower cursors/lag on a primary, the
            # takeover point after a promotion. None — the distinct
            # "fleet: absent" state — for a plain single-process serve.
            "fleet": (self.fleet.export()
                      if self.fleet is not None else None),
            # The overload control plane (knn_tpu/control/): admission
            # shed tiers, brownout ladder level, autotune cycle history.
            # None — the distinct "control: absent" state — while no
            # control flag is set.
            "control": self.control_block(),
            # Durable metrics history + alert engine. None — the
            # distinct "absent" state — while --history-dir/--alert-rules
            # are unset.
            "history": (self.history.status()
                        if self.history is not None else None),
            "alerts": ({"firing": self.alerts.export()["firing"],
                        "rules": len(self.alerts.rules)}
                       if self.alerts is not None else None),
        }
        if self.recorder is not None:
            h["flight_recorder"] = self.recorder.stats()
        return h

    def control_block(self) -> "Optional[dict]":
        """The control-plane summary for ``/healthz`` and
        ``/debug/control``: admission (shed tiers, priority map, audit),
        brownout (ladder level, applied steps, audit), autotune (cycle
        outcomes, live max_wait_ms). None when no control layer exists —
        never an empty dict that looks like a healthy controller."""
        if (self.admission is None and self.brownout is None
                and self.autotune is None):
            return None
        return {
            "admission": (self.admission.export()
                          if self.admission is not None else None),
            "brownout": (self.brownout.export()
                         if self.brownout is not None else None),
            "autotune": (self.autotune.export()
                         if self.autotune is not None else None),
        }

    def overload_retry_after_s(self) -> float:
        """The Retry-After value for overload (429) and draining (503)
        responses: headroom-derived with jitter when admission runs (the
        deeper past the knee, the longer clients should back off), a
        jittered ~1-2 s otherwise — never 0, so a thundering herd's
        retries spread instead of re-arriving in lockstep."""
        if self.admission is not None:
            return self.admission.retry_after_s()
        return 1.0 + random.random()

    def shard_block(self) -> "Optional[dict]":
        """The sharded-serving summary for ``/healthz`` and
        ``/debug/capacity``: the frozen plan, per-shard walls of the last
        fanned-out dispatch, and the straggler derivation — what the
        skew-triage runbook (docs/SERVING.md) reads. None while
        --shards is unset (the model then has no shard surface at all)."""
        export = getattr(self.model, "shard_export", None)
        return export() if export is not None else None

    def quality_block(self) -> dict:
        """The answer-quality summary for ``/healthz`` (and the core of
        ``/debug/quality``): shadow-scorer per-rung stats and the drift
        summary, each ``None`` when its layer is off. ``export()`` also
        refreshes the ``knn_quality_*``/``knn_drift_*`` gauges, so a
        /healthz poller keeps them current between /metrics scrapes."""
        return {
            "shadow": (self.quality.export()
                       if self.quality is not None else None),
            "drift": (self.drift.export()
                      if self.drift is not None else None),
        }

    @staticmethod
    def _device_block() -> dict:
        """The device-side health summary (obs/devprof.py): memory per
        device (also refreshing the knn_device_memory_bytes gauges),
        compile events/walls, executable-cache hit/miss."""
        from knn_tpu.obs import devprof

        return {
            "memory": devprof.record_device_memory(),
            "compile": devprof.compile_summary(),
            "executable_cache": devprof.executable_cache_summary(),
        }


class _Handler(BaseHTTPRequestHandler):
    server_version = "knn-tpu-serve/1"
    protocol_version = "HTTP/1.1"
    # Socket timeout: a client stalling mid-body (or idling on keep-alive)
    # must release its handler thread — without this, N slow connections
    # pin N threads forever and starve the process before the batcher's
    # admission control ever engages.
    timeout = 60

    @property
    def app(self) -> ServeApp:
        return self.server.app  # type: ignore[attr-defined]

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        # Per-request stderr lines at serving rates are an accidental DoS
        # on the process's own stderr; the /metrics endpoint (and the
        # structured --access-log) is the log.
        pass

    def _begin(self) -> bool:
        """Adopt or mint the request id for this request. A client-supplied
        ``x-request-id`` is echoed end to end (trace, flight recorder,
        access log, response header + body); an oversized/malformed one is
        a 400 with a generated id — never a traceback. Returns False when
        the request was already answered."""
        raw = self.headers.get("x-request-id")
        if raw is None:
            self._rid = reqtrace.gen_request_id()
            return True
        raw = raw.strip()
        if not reqtrace.valid_request_id(raw):
            self._rid = reqtrace.gen_request_id()
            self.close_connection = True  # the body was never drained
            self._send(400, {
                "error": f"invalid x-request-id header: want 1-"
                         f"{reqtrace.MAX_REQUEST_ID_LEN} printable "
                         f"non-space ASCII characters, got {len(raw)} "
                         f"byte(s)",
            })
            return False
        self._rid = raw
        return True

    def _send(self, status: int, payload: dict,
              content_type="application/json", tag_request_id=True,
              retry_after: "Optional[float]" = None):
        rid = getattr(self, "_rid", None)
        if tag_request_id and rid is not None and "request_id" not in payload:
            payload = {**payload, "request_id": rid}
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        if retry_after is not None:
            # Whole seconds (RFC 9110 delay-seconds), floor 1: the jitter
            # already rode in on the float, and "Retry-After: 0" invites
            # the herd right back.
            self.send_header("Retry-After",
                             str(max(1, int(round(retry_after)))))
        if rid is not None:
            self.send_header("x-request-id", rid)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, status: int, text: str, content_type: str):
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        rid = getattr(self, "_rid", None)
        if rid is not None:
            self.send_header("x-request-id", rid)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- GET ---------------------------------------------------------------

    def do_GET(self):  # noqa: N802 — stdlib dispatch name
        if not self._begin():
            return
        route = urlparse(self.path).path
        if route == "/healthz":
            h = self.app.health()
            ok = h["ready"] and not h["draining"]
            self._send(200 if ok else 503, h)
        elif route == "/metrics":
            # Refresh the scrape-time gauges (knn_slo_*,
            # knn_device_memory_bytes, knn_quality_*/knn_drift_*) before
            # rendering.
            self.app.slo.export()
            from knn_tpu.obs import devprof

            devprof.record_device_memory()
            if self.app.quality is not None:
                self.app.quality.export()
            if self.app.drift is not None:
                self.app.drift.export()
            if self.app.capacity is not None:
                self.app.capacity.export()
            if self.app.mutable is not None:
                self.app.mutable.export()
            if self.app.workload is not None:
                # Refreshes knn_workload_capturing AND completes any
                # deferred auto-stop finalization (a timed window whose
                # traffic ceased finalizes on the next scrape).
                self.app.workload.export()
            q = parse_qs(urlparse(self.path).query)
            if q.get("format", [None])[0] == "json":
                # The machine-readable scrape: this registry as a raw
                # snapshot (exact histogram bucket counts) — what the
                # fleet router's federated /metrics merges per-replica
                # (obs/aggregate.py), same shape the multihost gather
                # ships.
                from knn_tpu.obs import aggregate

                self._send(200,
                           {"snapshot": aggregate.snapshot_registry()},
                           tag_request_id=False)
                return
            accept = self.headers.get("Accept", "")
            if "application/openmetrics-text" in accept:
                self._send_text(
                    200, obs.registry().to_openmetrics(),
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8",
                )
            else:
                self._send_text(
                    200, obs.registry().to_prometheus(),
                    "text/plain; version=0.0.4",
                )
        elif route in ("/debug/requests", "/debug/slowest"):
            self._do_debug(route)
        elif route == "/debug/quality":
            self._do_quality()
        elif route == "/debug/capacity":
            self._do_capacity()
        elif route == "/debug/capture":
            self._do_capture_status()
        elif route == "/debug/control":
            self._do_control()
        elif route == "/debug/history":
            self._do_history()
        elif route == "/debug/alerts":
            self._do_alerts()
        elif route == "/debug/profile":
            self._do_profile()
        elif route == "/admin/wal-since":
            self._do_wal_since()
        elif route == "/admin/snapshot":
            self._do_snapshot()
        else:
            self._send(404, {"error": f"no such endpoint: {self.path}"})

    def _do_quality(self):
        """The answer-quality join: shadow recall/accuracy + per-rung
        divergence, the drift summary (with its distinct no-baseline
        state), and the quality SLO burn rates in ONE payload — drift
        tells you the QUERIES changed, recall tells you the ANSWERS
        changed, the rung attribution tells you WHERE. Always 200: a
        disabled layer reports ``null`` rather than 404, so dashboards
        can hard-code the route."""
        block = self.app.quality_block()
        burns = self.app.slo.burn_rates()
        payload = {
            "enabled": {
                "shadow": self.app.quality is not None,
                "drift": self.app.drift is not None,
            },
            **block,
            "slo_quality": {
                "target": self.app.slo.targets["quality"],
                "burn_rates": burns.get("quality", {}),
            },
            "index_version": self.app.index_version,
        }
        # Like /debug/requests: no request_id stamped into a payload that
        # is about OTHER requests (the header still carries it).
        self._send(200, payload, tag_request_id=False)

    def _do_capacity(self):
        """The cost & capacity join: per-class device spend + attribution
        conservation (``obs/accounting.py``), the duty-cycle / occupancy /
        headroom summary (``obs/capacity.py``), and the live batching
        policy in ONE payload — cost tells you who is paying, capacity
        tells you how close to the knee the replica runs, policy tells you
        what to turn. Always 200: disabled layers report ``null``, so
        dashboards can hard-code the route (the ``/debug/quality`` rule)."""
        b = self.app.batcher
        payload = {
            "enabled": self.app.accounting is not None,
            "capacity": (self.app.capacity.export()
                         if self.app.capacity is not None else None),
            "cost": (self.app.accounting.export()
                     if self.app.accounting is not None else None),
            "policy": {
                "max_batch": b.max_batch,
                "max_wait_ms": b.max_wait_ms,
                "max_queue_rows": b.max_queue_rows,
                # The compiled-shape ladder + result-cache counters: what
                # an operator tunes after reading the waste numbers above
                # (docs/SERVING.md §Tuning the bucket ladder).
                "batch_buckets": list(b.buckets) if b.buckets else None,
                "result_cache": (b.cache.stats()
                                 if b.cache is not None else None),
            },
            # Compaction debt is capacity debt: the delta ratio prices
            # the extra per-dispatch merge work, so it belongs on the
            # page an operator sizes replicas from. None while off.
            "mutable": (self.app.mutable.export()
                        if self.app.mutable is not None else None),
            # The shard fanout is a capacity lever too: per-shard
            # candidate/byte spend and the straggler skew bound the
            # win from adding shards. None while --shards is unset.
            "shard": self.app.shard_block(),
            "index_version": self.app.index_version,
        }
        # No request_id stamped into a payload about OTHER requests (the
        # /debug/requests rule; the response header still carries it).
        self._send(200, payload, tag_request_id=False)

    def _do_capture_status(self):
        """The workload-capture status page: window state, counts, the
        burn trigger, the last finalized artifact. Always 200 — a
        disabled layer reports ``enabled: false`` rather than 404, so
        dashboards can hard-code the route (the /debug/quality rule)."""
        w = self.app.workload
        payload = {"enabled": w is not None,
                   **(w.export() if w is not None else {}),
                   "index_version": self.app.index_version}
        # No request_id stamped into a payload about OTHER requests (the
        # /debug/requests rule; the response header still carries it).
        self._send(200, payload, tag_request_id=False)

    def _do_control(self):
        """The overload-control status page: admission shed tiers +
        audit, brownout ladder level + audit, autotune cycle history,
        and the degradation-order contract the controllers enforce
        (docs/RESILIENCE.md). Always 200 — disabled layers report
        ``null`` rather than 404, so dashboards can hard-code the route
        (the /debug/quality rule)."""
        from knn_tpu.resilience.degrade import DEGRADATION_ORDER

        app = self.app
        block = app.control_block() or {
            "admission": None, "brownout": None, "autotune": None}
        payload = {
            "enabled": {
                "admission": app.admission is not None,
                "brownout": app.brownout is not None,
                "autotune": app.autotune is not None,
            },
            **block,
            "degradation_order": list(DEGRADATION_ORDER),
            "index_version": app.index_version,
        }
        # No request_id stamped into a payload about OTHER requests (the
        # /debug/requests rule; the response header still carries it).
        self._send(200, payload, tag_request_id=False)

    def _do_history(self):
        """The live metrics-history window: ``?metric=NAME`` filters to
        one instrument, ``&label=k=v`` (repeatable) subset-matches
        labels, ``&window=5m`` trails back from the newest snapshot.
        Always 200 — while --history-dir/--alert-rules are off the
        payload says ``enabled: false`` rather than 404, so dashboards
        can hard-code the route (the /debug/quality rule)."""
        app = self.app
        if app.history is None:
            self._send(200, {"enabled": False, "series": [],
                             "index_version": app.index_version},
                       tag_request_id=False)
            return
        from knn_tpu.obs.history import parse_window

        q = parse_qs(urlparse(self.path).query)
        metric = q.get("metric", [None])[0]
        labels = {}
        for item in q.get("label", []):
            k, sep, v = item.partition("=")
            if not sep or not k:
                self._send(400, {"error": f"bad label={item!r}: want k=v"})
                return
            labels[k] = v
        window_s = None
        if q.get("window", [None])[0] is not None:
            try:
                window_s = parse_window(q["window"][0])
            except ValueError as exc:
                self._send(400, {"error": str(exc)})
                return
        payload = {"enabled": True, "status": app.history.status(),
                   **app.history.query(metric=metric, labels=labels,
                                       window_s=window_s),
                   "index_version": app.index_version}
        self._send(200, payload, tag_request_id=False)

    def _do_alerts(self):
        """The alert-engine status page: every rule's hysteresis state,
        the currently-firing set, and the recent audit tail. Always 200
        — no --alert-rules reports ``enabled: false`` with empty,
        well-formed collections (the /debug/quality rule)."""
        app = self.app
        if app.alerts is None:
            self._send(200, {"enabled": False, "rules": [], "firing": [],
                             "recent": [],
                             "index_version": app.index_version},
                       tag_request_id=False)
            return
        payload = {"enabled": True, **app.alerts.export(),
                   "index_version": app.index_version}
        self._send(200, payload, tag_request_id=False)

    def _do_profile(self):
        """On-demand device profile: ``?ms=N`` holds a ``jax.profiler``
        capture open for N ms on THIS handler thread (the other threads
        keep dispatching — their spans/annotations and XLA events are the
        payload), then returns the merged Chrome ``trace_event`` JSON.
        One capture at a time (409); the window is capped so a typo'd
        ``ms`` cannot pin the capture lock for minutes."""
        from knn_tpu.obs import devprof

        q = parse_qs(urlparse(self.path).query)
        try:
            ms = float(q.get("ms", ["200"])[0])
            if not math.isfinite(ms) or ms < 0:
                raise ValueError
        except ValueError:
            self._send(400, {"error": f"bad ms={q.get('ms', [''])[0]!r}: "
                                      f"want a number of milliseconds >= 0"})
            return
        if ms > devprof.MAX_CAPTURE_MS:
            self._send(400, {"error": f"ms={ms:.0f} exceeds the "
                                      f"{devprof.MAX_CAPTURE_MS} ms capture "
                                      f"bound"})
            return
        try:
            trace = devprof.capture_for(ms)
        except devprof.CaptureBusy as e:
            self._send(409, {"error": str(e)})
            return
        # Compact separators: a capture under load easily holds 10^5
        # events, and the default pretty separators add ~20% to a payload
        # that is already the biggest thing this server ever sends. (No
        # request-id stamping either — the payload is a timeline about
        # OTHER requests, the /debug/requests rule.)
        self._send_text(200, json.dumps(trace, separators=(",", ":")),
                        "application/json")

    def _do_debug(self, route: str):
        """The flight recorder's read side: ``/debug/requests`` (last-N
        timelines, newest first) and ``/debug/slowest`` (the slowest-K
        reservoir). ``?id=<request_id>`` resolves one timeline;
        ``?n=<count>`` bounds the list; ``?format=perfetto`` returns the
        timelines as Chrome/Perfetto ``trace_event`` JSON (one track per
        request — load at ui.perfetto.dev)."""
        rec = self.app.recorder
        if rec is None:
            self._send(404, {"error": "request tracing is disabled "
                                      "(--flight-recorder-size 0)"})
            return
        q = parse_qs(urlparse(self.path).query)
        fmt = q.get("format", ["json"])[0]
        rid = q.get("id", [None])[0]
        if rid is not None:
            tl = rec.find(rid)
            if tl is None:
                self._send(404, {"error": f"request_id {rid!r} not in the "
                                          f"flight recorder (evicted or "
                                          f"never traced)"})
                return
            timelines = [tl]
        elif route == "/debug/slowest":
            timelines = rec.slowest()
        else:
            try:
                n = int(q["n"][0]) if "n" in q else None
            except ValueError:
                self._send(400, {"error": f"bad n={q['n'][0]!r}: want an "
                                          f"integer"})
                return
            timelines = rec.recent(n)
        # No request_id injection here: these payloads are ABOUT other
        # requests' ids — the debug GET's own id stamped on top (or into
        # the Perfetto artifact CI uploads) would only mislead. The
        # x-request-id response header still carries it.
        if fmt == "perfetto":
            self._send(200, rec.to_chrome_trace(timelines),
                       tag_request_id=False)
        elif fmt == "json":
            self._send(200, {"requests": timelines, **rec.stats()},
                       tag_request_id=False)
        else:
            self._send(400, {"error": f"bad format={fmt!r}: want json or "
                                      f"perfetto"})

    # -- POST --------------------------------------------------------------

    def _read_json_body(self, required: bool):
        """Parse the JSON request body; returns ``(dict, None, None)`` or
        ``(None, error_string, http_status)``. ``required=False`` treats
        an absent body as ``{}`` (the admin endpoints take optional
        bodies)."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return None, "a JSON body with Content-Length is required", 400
        if length <= 0:
            if required:
                return (None, "a JSON body with Content-Length is required",
                        400)
            return {}, None, None
        if length > MAX_BODY_BYTES:
            return None, (f"body {length} B exceeds the {MAX_BODY_BYTES} B "
                          f"bound"), 413
        try:
            body = json.loads(self.rfile.read(length))
        except ValueError as e:
            return None, f"bad request body: {e}", 400
        if not isinstance(body, dict):
            return None, "the request body must be a JSON object", 400
        return body, None, None

    def do_POST(self):  # noqa: N802 — stdlib dispatch name
        if not self._begin():
            return
        if self.path == "/admin/reload":
            self._do_reload()
            return
        if self.path == "/admin/compact":
            self._do_compact()
            return
        if self.path == "/admin/capture":
            self._do_capture_admin()
            return
        if self.path == "/admin/wal-append":
            self._do_wal_append()
            return
        if self.path == "/admin/promote":
            self._do_promote()
            return
        if self.path == "/admin/bootstrap":
            self._do_bootstrap()
            return
        if self.path in ("/insert", "/delete"):
            with self.app.track_request():
                self._do_mutation(self.path[1:])
            return
        # Error replies sent before the body was drained must also close
        # the connection: with HTTP/1.1 keep-alive the unread bytes would
        # be parsed as the next request line.
        if self.path not in ("/predict", "/kneighbors"):
            self.close_connection = True
            self._send(404, {"error": f"no such endpoint: {self.path}"})
            return
        with self.app.track_request():
            self._do_inference(self.path[1:])

    # -- mutations (the mutable tier, docs/SERVING.md) ---------------------

    def _do_mutation(self, op: str):
        """``POST /insert`` (``{"rows": [[...]], "labels": [...]}``) and
        ``POST /delete`` (``{"ids": [...], "index_version": optional}``).
        Typed status contract: 404 while ``--mutable off`` (the layer
        does not exist — the /debug/requests rule), 400 malformed, 409
        conflict (unknown/deleted row, k-floor, stale version
        precondition), 429 delta tier full, 503 draining, 504 apply
        deadline; a 200 ack means the mutation is DURABLE (epoch-logged,
        flushed) and visible to every subsequent dispatch."""
        if self.app.mutable is None:
            self.close_connection = True
            self._send(404, {"error": "mutable serving is off — boot "
                                      "with `serve INDEX --mutable on`"})
            return
        if (self.app.fleet is not None
                and self.app.fleet.role == "follower"):
            # Read-only replica: the ONE primary owns the write order (a
            # second writer would fork the WAL). 409, not 5xx — the
            # request is well-formed, this replica just refuses it; the
            # router never sends writes here, so seeing this means a
            # client bypassed the router.
            self.close_connection = True
            primary = self.app.fleet.primary_url or "the router"
            self._send(409, {
                "error": f"this replica is a read-only follower; send "
                         f"writes to the primary ({primary})",
            })
            return
        body, err, status = self._read_json_body(required=True)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            return
        from knn_tpu.mutable.state import MutationConflict

        try:
            if op == "insert":
                if "rows" not in body:
                    raise ValueError('insert body needs "rows" '
                                     '(and "labels", one per row)')
                payload = {"rows": body["rows"],
                           "values": body.get("labels")}
            else:
                if "ids" not in body:
                    raise ValueError('delete body needs "ids"')
                # The version precondition rides the payload and is
                # checked by the ENGINE at apply time, under the lock the
                # compaction rebase holds — a handler-side check would
                # race the swap and a stale positional id could silently
                # delete a different row in the new generation.
                payload = {"ids": body["ids"],
                           "expect_version": body.get("index_version")}
            handle = self.app.batcher.submit_mutation(op, payload)
            value = handle.result(timeout=30)
        except MutationConflict as e:
            self._send(409, {"error": str(e)})
            return
        except OverloadError as e:
            st = 503 if self.app.draining else 429
            self._send(st, {"error": str(e)},
                       retry_after=self.app.overload_retry_after_s())
            return
        except DeadlineExceededError as e:
            self._send(504, {"error": str(e)})
            return
        except (ValueError, TypeError) as e:
            self._send(400, {"error": f"bad request body: {e}"})
            return
        except Exception as e:  # noqa: BLE001 — typed JSON, never a
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        fleet = self.app.fleet
        if fleet is not None and not fleet.wait_replicated(value["seq"]):
            # Applied + durable LOCALLY, but no follower confirmed it
            # inside the ack window: claiming success would promise a
            # durability this moment cannot promise (a primary loss now
            # would lose the write at promote). 503 with applied=true is
            # the honest typed outcome — the caller must NOT blindly
            # re-send (that would duplicate the mutation) and the router
            # never retries a write that reached the wire.
            self._send(503, {
                "error": f"replication ack timeout: seq {value['seq']} "
                         f"is applied and WAL-durable on this primary "
                         f"but no follower confirmed it within "
                         f"{fleet.ack_timeout_s:.1f} s — do not re-send; "
                         f"re-read after the fleet recovers",
                "applied": True, "seq": value["seq"],
                "index_version": value.get("index_version"),
            })
            return
        self._send(200, value)

    # -- fleet replication (knn_tpu/fleet/, docs/SERVING.md) ---------------

    def _do_wal_append(self):
        """``POST /admin/wal-append`` body ``{"records": [...],
        "primary_seq": N}``: apply one primary-shipped WAL batch through
        the engine's full validation path. Typed contract: 404 while no
        fleet role exists, 409 on the primary (split-brain refusal), 409
        with ``applied_seq`` on a seq gap (the shipper's resync cue), 409
        with ``diverged: true`` when the logs disagree about an
        already-applied seq, 400 for malformed records — never a
        traceback, never a silent skip."""
        if self.app.fleet is None:
            self.close_connection = True
            self._send(404, {"error": "fleet replication is off — boot "
                                      "with `serve INDEX --mutable on "
                                      "--follower-of PRIMARY_URL`"})
            return
        body, err, status = self._read_json_body(required=True)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            return
        from knn_tpu.mutable.state import (
            MutationConflict,
            ReplicationGap,
            WALDivergence,
        )

        try:
            result = self.app.fleet.apply_wal_records(
                body.get("records"), body.get("primary_seq"))
        except ReplicationGap as e:
            self._send(409, {"error": str(e),
                             "applied_seq": e.applied_seq})
            return
        except WALDivergence as e:
            self._send(409, {"error": str(e), "diverged": True})
            return
        except MutationConflict as e:
            # A shipped record this state refuses (e.g. an impossible
            # delete): divergence in content, not in seq — terminal for
            # the shipper too.
            self._send(409, {"error": str(e), "diverged": True})
            return
        except OverloadError as e:
            self._send(503, {"error": str(e)},
                       retry_after=self.app.overload_retry_after_s())
            return
        except (ValueError, TypeError) as e:
            self._send(400, {"error": f"bad wal-append body: {e}"})
            return
        except Exception as e:  # noqa: BLE001 — typed JSON, never a
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._send(200, result)

    def _do_promote(self):
        """``POST /admin/promote`` body ``{}`` or ``{"replicate_to":
        [URL, ...]}``: flip this follower to primary in place (the
        failover step — the router or the operator calls it on the
        most-caught-up follower after a primary loss). 404 while no
        fleet role, 409 when already primary."""
        if self.app.fleet is None:
            self.close_connection = True
            self._send(404, {"error": "fleet replication is off — this "
                                      "process has no role to promote"})
            return
        body, err, status = self._read_json_body(required=False)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            return
        from knn_tpu.mutable.state import MutationConflict

        urls = body.get("replicate_to") or []
        if not isinstance(urls, list) or not all(
                isinstance(u, str) for u in urls):
            self._send(400, {"error": '"replicate_to" must be a list of '
                                      'base URLs'})
            return
        try:
            result = self.app.fleet.promote(urls)
        except MutationConflict as e:
            self._send(409, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — typed JSON, never a
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._send(200, result)

    def _do_wal_since(self):
        """``GET /admin/wal-since?seq=N[&limit=M]``: this replica's WAL
        records newer than ``seq``, digest-stamped — the rejoin/catch-up
        export (any mutable replica can serve its own log). 404 while
        ``--mutable off``; 409 typed when ``seq`` predates the fold
        point (those records are compacted away — re-seed instead)."""
        if self.app.mutable is None:
            self._send(404, {"error": "mutable serving is off — there is "
                                      "no write-ahead log to export"})
            return
        q = parse_qs(urlparse(self.path).query)
        try:
            seq = int(q.get("seq", ["0"])[0])
            limit = int(q.get("limit", ["512"])[0])
            if limit < 1:
                raise ValueError
        except ValueError:
            self._send(400, {"error": f"bad seq/limit query: want "
                                      f"integers, got {self.path!r}"})
            return
        try:
            records, own_seq = self.app.mutable.records_since(
                seq, limit=limit)
        except DataError as e:
            self._send(409, {"error": str(e)})
            return
        except OSError as e:
            # Transient epoch churn (compaction pruning raced the scan
            # past its re-read budget): retry later, NOT the re-seed
            # refusal — and always typed JSON, never a traceback.
            self._send(503, {"error": f"WAL scan raced compaction "
                                      f"pruning; retry: {e}"})
            return
        self._send(200, {"records": records, "seq": own_seq},
                   tag_request_id=False)

    def _do_snapshot(self):
        """``GET /admin/snapshot``: the snapshot-shipping export any
        mutable replica serves from its committed on-disk state
        (fleet/bootstrap.py). No query → the snapshot manifest (file
        list with sizes + sha256 digests, the generation, and the WAL
        cursor a freshly installed follower resumes from);
        ``?file=NAME&offset=N&length=M&generation=G`` → one raw chunk,
        409 typed when ``G`` was superseded by a compaction mid-transfer
        (the client restarts from a fresh manifest). 404 while
        ``--mutable off``."""
        if self.app.mutable is None:
            self._send(404, {"error": "mutable serving is off — there is "
                                      "no generation artifact to ship"})
            return
        from knn_tpu.fleet import bootstrap

        q = parse_qs(urlparse(self.path).query)
        name = q.get("file", [None])[0]
        try:
            if name is None:
                self._send(200,
                           bootstrap.snapshot_manifest(self.app.mutable.root),
                           tag_request_id=False)
                return
            offset = int(q.get("offset", ["0"])[0])
            length = int(q.get("length", [str(bootstrap.CHUNK_BYTES)])[0])
            generation = int(q.get("generation", ["0"])[0])
        except ValueError:
            self._send(400, {"error": f"bad snapshot chunk query: "
                                      f"{self.path!r}"})
            return
        try:
            chunk = bootstrap.read_chunk(self.app.mutable.root, name,
                                         offset, length, generation)
        except DataError as e:
            self._send(409, {"error": str(e)})
            return
        except OSError as e:
            self._send(503, {"error": f"snapshot read failed: {e}"})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        rid = getattr(self, "_rid", None)
        if rid is not None:
            self.send_header("x-request-id", rid)
        self.send_header("Content-Length", str(len(chunk)))
        self.end_headers()
        self.wfile.write(chunk)

    def _do_bootstrap(self):
        """``POST /admin/bootstrap`` body ``{"from": SOURCE_URL}``:
        re-seed THIS replica from the source's snapshot while the prior
        state keeps serving until the atomic flip (the self-healing leg
        — the router calls this on a follower whose shipper parked
        behind the fold or diverged). 404 while ``--mutable off``, 409
        on the primary / while another bootstrap or compaction runs,
        502 typed when the transfer itself failed — prior state serving
        in every non-200 case."""
        if self.app.mutable is None:
            self.close_connection = True
            self._send(404, {"error": "mutable serving is off — boot "
                                      "with `serve INDEX --mutable on`"})
            return
        body, err, status = self._read_json_body(required=True)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            return
        source = body.get("from")
        if not isinstance(source, str) or not source.startswith(
                ("http://", "https://")):
            self._send(400, {"error": '"from" must be the source '
                                      "replica's base URL"})
            return
        from knn_tpu.fleet.bootstrap import SnapshotInstallError
        from knn_tpu.mutable.compact import CompactionInProgress
        from knn_tpu.mutable.state import MutationConflict

        timeout_s = float(body.get("timeout_s") or 60.0)
        try:
            result = self.app.bootstrap_from(source, timeout_s=timeout_s)
        except (MutationConflict, ReloadInProgress,
                CompactionInProgress) as e:
            self._send(409, {"error": str(e)})
            return
        except SnapshotInstallError as e:
            self._send(502, {"error": str(e), "prior_state_serving": True})
            return
        except DataError as e:
            self._send(409, {"error": str(e), "prior_state_serving": True})
            return
        except OSError as e:
            self._send(502, {"error": f"bootstrap transfer failed: {e}",
                             "prior_state_serving": True})
            return
        except Exception as e:  # noqa: BLE001 — typed JSON, never a
            self._send(500, {"error": f"{type(e).__name__}: {e}",
                             "prior_state_serving": True})
            return
        self._send(200, result)

    def _do_compact(self):
        """``POST /admin/compact``: fold the delta tier + tombstones into
        a fresh generation NOW and swap it in (the admin trigger for the
        background compactor). 404 while ``--mutable off``, 409 while
        another compaction runs, 500 ``rolled_back`` on failure with the
        old generation still serving."""
        if self.app.compactor is None:
            self.close_connection = True
            self._send(404, {"error": "mutable serving is off — boot "
                                      "with `serve INDEX --mutable on`"})
            return
        body, err, status = self._read_json_body(required=False)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            return
        from knn_tpu.mutable.compact import (
            CompactionCommitFailed,
            CompactionInProgress,
        )

        try:
            result = self.app.compactor.run_once(force=True)
        except CompactionInProgress as e:
            self._send(409, {"error": str(e)})
            return
        except CompactionCommitFailed as e:
            # The NEW generation is serving; only the pointer commit
            # failed — claiming rolled_back would be the opposite of the
            # truth (the reboot/replay contract still holds).
            self._send(500, {
                "error": str(e), "rolled_back": False,
                "index_version": self.app.index_version,
            })
            return
        except Exception as e:  # noqa: BLE001 — rollback is implicit
            self._send(500, {
                "error": f"{type(e).__name__}: {e}", "rolled_back": True,
                "index_version": self.app.index_version,
            })
            return
        self._send(200, result)

    def _do_capture_admin(self):
        """``POST /admin/capture`` body ``{"action": "start"|"stop"}``:
        arm / finalize a workload-capture window (docs/OBSERVABILITY.md
        §Workload capture & replay). ``start`` takes optional
        ``max_requests`` and ``window_s``; ``stop`` returns the finalized
        artifact summary (path, counts). 404 while ``--capture-dir`` is
        unset (the layer does not exist), 409 on a state contradiction
        (start while armed / stop while idle)."""
        if self.app.workload is None:
            self.close_connection = True
            self._send(404, {"error": "workload capture is off — boot "
                                      "with `serve INDEX --capture-dir "
                                      "DIR`"})
            return
        body, err, status = self._read_json_body(required=True)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            return
        from knn_tpu.obs.workload import CaptureStateError

        action = body.get("action")
        try:
            if action == "start":
                max_requests = body.get("max_requests")
                window_s = body.get("window_s")
                if max_requests is not None:
                    max_requests = int(max_requests)
                    if max_requests < 1:
                        raise ValueError(
                            f"max_requests must be >= 1, got {max_requests}")
                if window_s is not None:
                    window_s = float(window_s)
                    if not math.isfinite(window_s) or window_s <= 0:
                        raise ValueError(
                            f"window_s must be > 0, got {window_s}")
                result = self.app.workload.start(
                    reason=str(body.get("reason") or "manual")[:64],
                    max_requests=max_requests, window_s=window_s)
            elif action == "stop":
                result = self.app.workload.stop()
            else:
                raise ValueError(
                    f'unknown action {action!r}: want "start" or "stop"')
        except CaptureStateError as e:
            self._send(409, {"error": str(e)})
            return
        except (TypeError, ValueError) as e:
            self._send(400, {"error": f"bad request body: {e}"})
            return
        except OSError as e:
            # The artifact write failed (disk full, permissions): the
            # capture is lost but the server keeps serving.
            self._send(500, {"error": f"capture write failed: {e}"})
            return
        self._send(200, result)

    def _do_reload(self):
        body, err, status = self._read_json_body(required=False)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            return
        try:
            result = self.app.reload(body.get("index"))
        except ReloadInProgress as e:
            self._send(409, {"error": str(e)})
            return
        except DataError as e:
            # Bad/incompatible replacement artifact: rolled back, the old
            # index is still serving — say so explicitly.
            self._send(400, {
                "error": str(e), "rolled_back": True,
                "index_version": self.app.index_version,
            })
            return
        except Exception as e:  # noqa: BLE001 — warmup/compile failures
            self._send(500, {
                "error": f"{type(e).__name__}: {e}", "rolled_back": True,
                "index_version": self.app.index_version,
            })
            return
        self._send(200, result)

    def _account(self, kind: str, status: int, outcome: str, t0: float,
                 trace=None, rung: Optional[str] = None,
                 rows: Optional[int] = None,
                 index_version: Optional[str] = None,
                 req_class: Optional[str] = None) -> None:
        """Terminal-outcome bookkeeping, on the HANDLER thread after the
        response went out: the SLO record (400s excluded — a malformed
        body is the caller's defect, not service unavailability), the
        trace's HTTP status annotation (+ finish, for requests the batcher
        never admitted), and the structured access-log line."""
        ms = (time.monotonic() - t0) * 1e3
        if outcome == "shed":
            # A policy shed of a non-protected class spends NO
            # objective's budget: it is counted in the SLO export's
            # policy_sheds (the operator must see the volume) but
            # excluded from every denominator — the availability-
            # exclusion half of the shed-by-policy contract
            # (docs/RESILIENCE.md §Degradation order). Protected
            # classes are never shed by policy, so their overload 429s
            # still arrive as "rejected" and still burn.
            self.app.slo.record_shed()
        elif status != 400:
            # degraded = not the rung a healthy request is expected to
            # ride: "fast" normally, "ivf" when approximate serving is on
            # (an ivf answer is the designed operating point there, and a
            # FALLBACK to exact is the capacity-burning degradation).
            self.app.slo.record(status == 200, ms,
                                degraded=(rung != self.app.primary_rung))
        if trace is not None:
            trace.annotate(status=status)
            if not trace.finished:
                trace.finish(outcome)
        if self.app.access_log is not None:
            entry = {
                "ts": round(time.time(), 6),
                "request_id": self._rid,
                "kind": kind,
                "status": status,
                "outcome": outcome,
                "ms": round(ms, 3),
                "rows": rows,
                "rung": rung,
                "index_version": index_version,
            }
            if req_class is not None:
                entry["class"] = req_class
            if trace is not None:
                tl = trace.to_dict()
                if "workload_record" in tl:
                    # Capture linkage: a replayed divergence on workload
                    # record N resolves to this line's request_id (and
                    # the flight-recorder timeline, which carries the
                    # same annotation).
                    entry["workload_record"] = tl["workload_record"]
                phases: dict = {}
                for p in tl["phases"]:
                    phases[p["phase"]] = round(
                        phases.get(p["phase"], 0.0) + (p["ms"] or 0.0), 3)
                entry["phases"] = phases
                if tl["attempts"]:
                    entry["attempts"] = [
                        f"{a['rung']}:{'ok' if a['ok'] else a.get('error', 'fail')}"
                        for a in tl["attempts"]
                    ]
                if "batch_requests" in tl:
                    entry["batch_requests"] = tl["batch_requests"]
            self.app.access_log.write(entry)

    def _do_inference(self, kind: str):
        # Two clocks: t_recv covers body upload + parse (access-log only —
        # a client trickling its body is the CLIENT's time), t0 below
        # covers submit -> response (the service-side "ms" field and the
        # latency SLI; a slow uploader must not burn the latency SLO).
        t_recv = time.monotonic()
        body, err, status = self._read_json_body(required=True)
        if err is not None:
            self.close_connection = True
            self._send(status, {"error": err})
            self._account(kind, status, "invalid", t_recv)
            return
        try:
            instances = body["instances"]
            deadline_ms = body.get("deadline_ms", self.app.deadline_ms)
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
                if not math.isfinite(deadline_ms) or deadline_ms <= 0:
                    raise ValueError(f"deadline_ms must be a finite value "
                                     f"> 0, got {deadline_ms}")
            x = np.asarray(instances, dtype=np.float32)
        except (KeyError, TypeError, ValueError) as e:
            self._send(400, {"error": f"bad request body: {e}"})
            self._account(kind, 400, "invalid", t_recv)
            return
        # Request class for cost attribution — parsed ONLY while the
        # accounting layer exists (the default-off contract: no header
        # lookup, no validation, nothing constructed while off). The JSON
        # body's "class" field wins over the x-knn-class header (clients
        # behind header-stripping proxies still get to tag).
        req_class = None
        if self.app.accounting is not None:
            from knn_tpu.obs import accounting as acct_mod

            raw_cls = body.get("class")
            if raw_cls is None:
                # Absent OR an explicit JSON null both fall back to the
                # header: serializers that emit null for unset fields
                # must not silently discard a caller's x-knn-class tag.
                raw_cls = self.headers.get("x-knn-class")
            if raw_cls is not None:
                raw_cls = str(raw_cls).strip()
                if not acct_mod.valid_request_class(raw_cls):
                    self._send(400, {
                        "error": f"invalid request class: want 1-"
                                 f"{acct_mod.MAX_CLASS_LEN} chars of "
                                 f"[a-z0-9_.-] (x-knn-class header or "
                                 f"\"class\" body field), got "
                                 f"{raw_cls[:64]!r}",
                    })
                    self._account(kind, 400, "invalid", t_recv)
                    return
                req_class = raw_cls
            else:
                req_class = acct_mod.DEFAULT_CLASS
        rows = int(x.shape[0]) if x.ndim > 1 else 1
        t0 = time.monotonic()
        trace = None
        if self.app.recorder is not None:
            # The request context: created at admission, carried through
            # the batcher's queue -> batch -> ladder, committed to the
            # flight recorder at its terminal outcome.
            trace = self.app.recorder.new_trace(kind, rows,
                                                request_id=self._rid)
            if deadline_ms is not None:
                trace.annotate(deadline_ms=deadline_ms)
            hop = self.headers.get("x-knn-hop")
            if hop is not None:
                # Cross-tier linkage: WHICH router attempt (first try,
                # retry, hedge) this replica-side timeline belongs to —
                # what lets a stitched trace pair each router attempt
                # slice with the replica work it caused.
                try:
                    trace.annotate(upstream_attempt=int(hop))
                except ValueError:
                    pass  # a garbled hop header must never fail a read
        try:
            handle = self.app.batcher.submit(x, kind, deadline_ms=deadline_ms,
                                             trace=trace,
                                             request_class=req_class)
        except OverloadError as e:
            # While draining, 503 (not 429): the load balancer should take
            # this replica out of rotation, not have the client retry here.
            # A ShedByPolicy carries its own headroom-derived Retry-After
            # and a distinct outcome: a deliberate shed of a
            # non-protected class is the control plane working, not an
            # availability incident (_account routes it to record_shed).
            st = 503 if self.app.draining else 429
            shed = isinstance(e, ShedByPolicy)
            self._send(st, {"error": str(e)},
                       retry_after=(e.retry_after_s if shed else
                                    self.app.overload_retry_after_s()))
            self._account(kind, st, "shed" if shed else "rejected", t0,
                          trace=trace, rows=rows, req_class=req_class)
            return
        except ValueError as e:  # shape/kind rejection
            self._send(400, {"error": str(e)})
            self._account(kind, 400, "invalid", t0, trace=trace, rows=rows,
                          req_class=req_class)
            return
        timeout = deadline_ms / 1e3 if deadline_ms is not None else None
        try:
            value = handle.result(timeout=timeout)
        except DeadlineExceededError as e:
            self._send(504, {"error": str(e)})
            self._account(kind, 504, "expired", t0, trace=trace, rows=rows,
                          rung=(handle.meta or {}).get("rung"),
                          req_class=req_class)
            return
        except Exception as e:  # noqa: BLE001 — the batcher delivers ANY
            # failure to the future (that is its worker-survival contract);
            # whatever arrives must become the documented JSON 500, never a
            # handler traceback + dropped connection.
            self._send(500, {"error": f"{type(e).__name__}: {e}"})
            self._account(kind, 500, "error", t0, trace=trace, rows=rows,
                          rung=(handle.meta or {}).get("rung"),
                          req_class=req_class)
            return
        ms = round((time.monotonic() - t0) * 1e3, 3)
        meta = handle.meta or {}
        if kind == "predict":
            payload = {"predictions": np.asarray(value).tolist(),
                       "index_version": meta.get("index_version"),
                       "ms": ms}
        else:
            dists, idx = value
            payload = {
                "distances": np.asarray(dists).tolist(),
                "indices": np.asarray(idx).tolist(),
                "index_version": meta.get("index_version"),
                "ms": ms,
            }
        if "mutation_seq" in meta:
            # Mutable serving: the read's sequence point — which
            # acknowledged mutations this answer reflects (what the
            # mutable soak's oracle replay verifies against).
            payload["mutation_seq"] = meta["mutation_seq"]
            fleet = self.app.fleet
            if fleet is not None:
                # Read-staleness annotation: a follower that has SEEN
                # primary seq N but only applied seq M < N is serving an
                # answer N-M writes behind — the client-visible face of
                # the replication-lag SLI (0 / primary reads omit it).
                stale = fleet.staleness_seq()
                if stale > 0:
                    payload["staleness_seq"] = stale
                    if trace is not None:
                        trace.annotate(staleness_seq=stale)
        self._send(200, payload)
        self._account(kind, 200, "ok", t0, trace=trace,
                      rung=meta.get("rung"), rows=rows,
                      index_version=meta.get("index_version"),
                      req_class=req_class)


class KNNServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the :class:`ServeApp`. Daemon handler
    threads: a hung client connection must not block process exit."""

    daemon_threads = True

    def __init__(self, address, app: ServeApp):
        super().__init__(address, _Handler)
        self.app = app
        self._stopper = None  # the SIGTERM drain thread, when one runs

    def handle_error(self, request, client_address):
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return  # the client went away mid-response; not a server error
        super().handle_error(request, client_address)


def make_server(app: ServeApp, host: str = "127.0.0.1",
                port: int = 0) -> KNNServer:
    """Bind (port 0 → ephemeral; read ``server.server_address``)."""
    return KNNServer((host, port), app)


def drain_and_stop(server: KNNServer, drain_timeout_s: float) -> dict:
    """The SIGTERM sequence, ordered so a peer's connection-refused
    demotion (the fleet router's passive health signal) fires
    IMMEDIATELY: (1) stop the accept loop, (2) close the LISTENING
    socket — from this instant a new connect is refused at the TCP
    layer — and only THEN (3) flip healthz to draining and answer every
    in-flight request. The old order (flip healthz first, close the
    listener at exit) left a window where a connection accepted between
    the 503 flip and the close raced the shutdown and died untracked.
    In-flight connections ride their own sockets and handler threads, so
    closing the listener cuts off nothing that was admitted.
    tests/test_serve.py pins the ordering."""
    server.shutdown()
    server.server_close()
    return server.app.drain(drain_timeout_s)


def serve_forever(server: KNNServer, *, banner=None,
                  drain_timeout_s: float = 10.0) -> int:
    """Run until a stop signal, then shut down cleanly. Returns 0 — the
    `knn_tpu serve` main loop.

    - SIGINT: fast clean stop (stop accepting, drain the batcher queue).
    - SIGTERM: graceful drain — readiness flips to 503 ``draining``, new
      admissions are refused typed, in-flight requests are answered
      within ``drain_timeout_s`` (remainders 504), then stop. Exit 0
      either way: drained shutdown IS success.
    - SIGHUP: hot index reload from the boot path (rollback on failure;
      the loop keeps serving throughout).
    """
    import signal
    import sys

    def on_sigint(signum, frame):
        # shutdown() must come from another thread than serve_forever's.
        threading.Thread(target=server.shutdown, daemon=True).start()

    def on_sigterm(signum, frame):
        def drain_then_stop():
            summary = drain_and_stop(server, drain_timeout_s)
            print(f"knn-tpu serve: drained "
                  f"(clean={summary['drained_clean']}, "
                  f"expired={summary['expired']}, "
                  f"{summary['ms']:.0f} ms); shutting down",
                  file=sys.stderr, flush=True)

        t = threading.Thread(target=drain_then_stop, daemon=True)
        # Registered BEFORE start: serve_forever's finally must never
        # observe a started-but-unregistered drain and close the app
        # under it.
        server._stopper = t
        t.start()

    def on_sighup(signum, frame):
        def work():
            try:
                r = server.app.reload()
                print(f"knn-tpu serve: reloaded index -> "
                      f"{r['index_version']} "
                      f"(was {r['previous_version']}, {r['ms']:.0f} ms)",
                      file=sys.stderr, flush=True)
            except Exception as e:  # noqa: BLE001 — rollback is implicit
                print(f"warning: reload failed ({type(e).__name__}: {e}); "
                      f"the previous index keeps serving",
                      file=sys.stderr, flush=True)

        threading.Thread(target=work, daemon=True).start()

    def on_sigusr2(signum, frame):
        # TEST-ONLY (armed below iff KNN_TPU_TEST_QUALITY_CORRUPT is set):
        # flip the batcher's index-corruption hook so the quality-soak
        # gate (scripts/quality_soak.py) can prove the shadow scorer
        # detects a silently-wrong index mid-run. Production serves never
        # install this handler.
        server.app.batcher.corrupt_serving = True
        print("warning: TEST HOOK engaged — serving corrupted neighbor "
              "indices (KNN_TPU_TEST_QUALITY_CORRUPT + SIGUSR2)",
              file=sys.stderr, flush=True)

    previous = {}
    handlers = {signal.SIGINT: on_sigint, signal.SIGTERM: on_sigterm}
    if hasattr(signal, "SIGHUP"):
        handlers[signal.SIGHUP] = on_sighup
    if (hasattr(signal, "SIGUSR2")
            and os.environ.get("KNN_TPU_TEST_QUALITY_CORRUPT")):
        handlers[signal.SIGUSR2] = on_sigusr2
    for sig, handler in handlers.items():
        try:
            previous[sig] = signal.signal(sig, handler)
        except ValueError:
            pass  # not the main thread (embedded use): caller manages stop
    if banner:
        print(banner, flush=True)
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        # SIGTERM path: the drain thread owns the shutdown sequence
        # (listener already closed); wait for it to finish answering
        # in-flight requests before tearing the app down under them.
        stopper = getattr(server, "_stopper", None)
        if stopper is not None and stopper.is_alive():
            stopper.join(timeout=drain_timeout_s + 5.0)
        server.server_close()
        server.app.close()
    return 0
