"""Index artifact store: versioned save/load of a fitted model.

A serving process must boot from a PREBUILT index, not from raw ARFF: the
parse (and for huge sets the host pad/transpose) is the batch pipeline's
cost, paid once at build time by ``knn_tpu save-index``, not on every
server start. An artifact is a directory:

    index/
    ├── manifest.json   — format version, model family + hyperparameters
    │                     (k, metric, weights, backend/engine, opts),
    │                     array schema (rows/features/classes/dtype),
    │                     attribute metadata, and a schema hash
    └── arrays.npz      — the train arrays (features, labels, and
    │                     raw_targets when the source kept them)

The manifest is the contract: ``format`` gates forward compatibility
(loaders reject artifacts from a NEWER format rather than misread them),
and ``schema_hash`` — a digest over the attribute schema and array
shapes/dtypes — pins manifest↔arrays consistency, so a hand-edited
manifest or a swapped ``arrays.npz`` fails typed
(:class:`~knn_tpu.resilience.errors.DataError`) instead of serving wrong
answers. Round-trip equality with the in-memory model is pinned per
backend in tests/test_serve.py.

:func:`warmup` is the boot step between load and ready: it runs the
retrieval path at the batch shapes the server is configured to dispatch,
so first-call compilation (seconds at TPU scale) happens before
``/healthz`` reports ready, never inside a user request.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from pathlib import Path
from typing import Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.data.dataset import Attribute, Dataset
from knn_tpu.models.knn import KNNClassifier, KNNRegressor
from knn_tpu.resilience.errors import DataError

#: Bumped on any incompatible change to the manifest or array layout.
#: History: 1 = the original layout; 2 adds the ``drift_sketch`` manifest
#: field (the training distribution's per-feature summary,
#: obs/drift.py) — loaders accept BOTH, and a format-1 (sketch-less)
#: artifact serves normally with drift scoring in its distinct
#: "no baseline" state (never fabricated scores); 3 adds the optional
#: IVF partition (``save-index --ivf-cells``): an ``ivf`` manifest block
#: plus ``ivf_centroids``/``ivf_row_perm``/``ivf_cell_offsets`` in
#: ``arrays.npz`` (knn_tpu/index/ivf.py, docs/INDEXES.md) — loaders
#: accept 1-3, and a format-1/2 (partition-less) artifact serves
#: exact-only with zero IVF machinery constructed
#: (scripts/check_disabled_overhead.py).
ARTIFACT_FORMAT = 3
MANIFEST_NAME = "manifest.json"
ARRAYS_NAME = "arrays.npz"

#: Delta-epoch persistence (the mutable tier, knn_tpu/mutable/): an
#: artifact directory serving with ``--mutable on`` grows a write-ahead
#: epoch log (``epochs/epoch-<N>.jsonl`` — one JSON record per
#: acknowledged mutation, flushed before the ack), compacted generations
#: (``generations/gen-<N>/`` — ordinary format-3 artifacts carrying an
#: additive ``mutable`` manifest block + a ``mutable_stable_ids`` array),
#: and an atomically-replaced ``CURRENT.json`` pointer naming the base
#: generation and the sequence number folded into it. None of this bumps
#: ARTIFACT_FORMAT: the extras are additive, so every format-1..3 loader
#: (including older builds) still reads a compacted generation as a plain
#: exact/IVF artifact, and a never-mutated artifact has none of them.
EPOCHS_DIR = "epochs"
GENERATIONS_DIR = "generations"
CURRENT_NAME = "CURRENT.json"


def schema_hash(ds: Dataset) -> str:
    """Digest over the dataset's SCHEMA — attribute metadata plus array
    shapes/dtypes, not the data values (hashing ~GB of train rows on every
    server boot would be the kind of cost this store exists to avoid)."""
    payload = json.dumps(
        {
            "attributes": [
                {
                    "name": a.name,
                    "type": a.type,
                    "nominal_values": a.nominal_values,
                    "string_values": a.string_values,
                }
                for a in ds.attributes
            ],
            "features": [list(ds.features.shape), str(ds.features.dtype)],
            "labels": [list(ds.labels.shape), str(ds.labels.dtype)],
            "raw_targets": (
                [list(ds.raw_targets.shape), str(ds.raw_targets.dtype)]
                if ds.raw_targets is not None else None
            ),
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def _model_manifest(model) -> dict:
    if isinstance(model, KNNClassifier):
        return {
            "family": "classifier",
            "k": model.k,
            "metric": model.metric,
            "weights": model.weights,
            "backend": model.backend_name,
            "backend_opts": dict(model.backend_opts),
        }
    if isinstance(model, KNNRegressor):
        return {
            "family": "regressor",
            "k": model.k,
            "metric": model.metric,
            "weights": model.weights,
            "engine": model.engine,
        }
    raise TypeError(
        f"cannot save a {type(model).__name__}; expected KNNClassifier or "
        f"KNNRegressor"
    )


def save_index(model, path, ivf=None, mutable_block=None) -> Path:
    """Write a fitted model to ``path`` (a directory; created if missing).

    ``ivf`` — an optional :class:`~knn_tpu.index.ivf.IVFIndex` to persist
    alongside the model (the ``save-index --ivf-cells`` path); when None,
    a partition already attached to the model (``model.ivf_`` — the
    load/re-save round trip) is kept. The partition must span exactly the
    train rows being saved.

    ``mutable_block`` — the compactor's generation metadata (a dict with
    ``stable_ids`` — int64 per train row — plus JSON fields like
    ``folded_seq``/``next_stable``/``generation``): persisted as an
    ADDITIVE ``mutable`` manifest entry and a ``mutable_stable_ids``
    array, ignored by plain loads (no format bump; see EPOCHS_DIR).

    Refuses to clobber a non-empty directory that is not already an
    artifact (no ``manifest.json``) — re-saving over an existing artifact
    is fine. Raises ``ValueError``/``OSError`` for bad inputs/paths (the
    CLI maps both to exit 2).
    """
    from knn_tpu.index.ivf import IVF_ATTR

    train = model.train_  # RuntimeError before fit
    manifest = _model_manifest(model)
    if ivf is None:
        ivf = getattr(model, IVF_ATTR, None)
    if ivf is not None and ivf.num_rows != train.num_instances:
        raise ValueError(
            f"ivf partition spans {ivf.num_rows} rows but the train set "
            f"has {train.num_instances} — rebuild the partition from "
            f"this data"
        )
    if ivf is not None and manifest.get("metric") != "euclidean":
        # The partition's cells are Voronoi regions of the squared-
        # euclidean k-means (index/ivf.py) — probing them under another
        # metric ranks cells by the wrong geometry. The CLI refuses this
        # too, but the contract must hold for library callers.
        raise ValueError(
            f"ivf partitions are euclidean-only; this model uses metric "
            f"{manifest.get('metric')!r}"
        )
    out = Path(path)
    if out.exists():
        if not out.is_dir():
            raise ValueError(f"{out}: exists and is not a directory")
        if any(out.iterdir()) and not (out / MANIFEST_NAME).exists():
            raise ValueError(
                f"{out}: non-empty directory without a {MANIFEST_NAME} — "
                f"refusing to overwrite something that is not an index "
                f"artifact"
            )
    out.mkdir(parents=True, exist_ok=True)
    arrays = {"features": train.features, "labels": train.labels}
    if train.raw_targets is not None:
        arrays["raw_targets"] = train.raw_targets
    if ivf is not None:
        arrays.update(ivf.to_arrays())
        manifest["ivf"] = ivf.manifest_entry()
    if mutable_block is not None:
        block = dict(mutable_block)
        stable = np.asarray(block.pop("stable_ids"), np.int64)
        if stable.shape != (train.num_instances,):
            raise ValueError(
                f"mutable stable_ids must be one int64 per train row "
                f"({train.num_instances}), got shape {stable.shape}"
            )
        arrays["mutable_stable_ids"] = stable
        manifest["mutable"] = block
    np.savez(out / ARRAYS_NAME, **arrays)
    # The reference (training) distribution sketch for query-drift
    # detection (obs/drift.py): one exact numpy pass at build time — the
    # serving process can never afford to recompute it, and without it a
    # drift monitor has nothing honest to compare against.
    from knn_tpu.obs.drift import StreamSketch

    manifest.update(
        format=ARTIFACT_FORMAT,
        drift_sketch=StreamSketch.from_data(train.features).to_dict(),
        created_unix=round(time.time(), 3),
        relation=train.relation,
        attributes=[
            {
                "name": a.name,
                "type": a.type,
                "nominal_values": a.nominal_values,
                "string_values": a.string_values,
            }
            for a in train.attributes
        ],
        train_rows=int(train.num_instances),
        num_features=int(train.num_features),
        num_classes=int(train.num_classes),
        dtype=str(train.features.dtype),
        schema_hash=schema_hash(train),
    )
    tmp = out / (MANIFEST_NAME + ".tmp")
    tmp.write_text(json.dumps(manifest, indent=2, sort_keys=True) + "\n")
    # Manifest lands last and atomically: a crashed save leaves a directory
    # load_index rejects, never a half-artifact that parses.
    os.replace(tmp, out / MANIFEST_NAME)
    return out


def read_manifest(path) -> dict:
    """Read + validate an artifact's manifest without loading the arrays
    (the serving process uses this to learn the :func:`index_version` it
    is about to swap in). Raises :class:`DataError` like
    :func:`load_index`."""
    return _read_manifest(Path(path))


def reference_sketch(manifest: dict) -> Optional[dict]:
    """The artifact's training-distribution sketch, or None for a
    pre-sketch (format 1) artifact — the caller must treat None as the
    distinct "no baseline" drift state, not as a zero-drift baseline."""
    sketch = manifest.get("drift_sketch")
    return sketch if isinstance(sketch, dict) else None


def index_version(manifest: dict) -> str:
    """Opaque version tag for an artifact: ``<created_unix>-<hash8>``.

    Two properties the hot-reload path needs: (1) re-saving an index —
    even with identical data — yields a distinguishable tag (the
    timestamp moves), so an operator can confirm WHICH build is serving;
    (2) it is derived from manifest fields every format-1 artifact already
    has, so no format bump. Carried in ``/healthz`` and every response's
    ``index_version`` field (docs/SERVING.md)."""
    return (f"{manifest.get('created_unix', 0)}-"
            f"{str(manifest.get('schema_hash', ''))[:8]}")


def _read_manifest(root: Path) -> dict:
    mf = root / MANIFEST_NAME
    if not root.exists():
        raise DataError(f"{root}: index artifact not found")
    if not root.is_dir() or not mf.exists():
        raise DataError(
            f"{root}: not an index artifact (no {MANIFEST_NAME}); build one "
            f"with `knn_tpu save-index`"
        )
    try:
        manifest = json.loads(mf.read_text())
    except (OSError, ValueError) as e:
        raise DataError(f"{mf}: unreadable manifest: {e}") from e
    fmt = manifest.get("format")
    if not isinstance(fmt, int) or fmt < 1:
        raise DataError(f"{mf}: missing/invalid format field: {fmt!r}")
    if fmt > ARTIFACT_FORMAT:
        raise DataError(
            f"{mf}: artifact format {fmt} is newer than this build "
            f"supports ({ARTIFACT_FORMAT}); rebuild the index or upgrade"
        )
    return manifest


def load_index(path):
    """Load an artifact into a fitted model (the inverse of
    :func:`save_index`; equality with the saved model is pinned per
    backend). Raises :class:`DataError` — typed, never a traceback — for
    missing/corrupt/newer-format artifacts."""
    root = Path(path)
    manifest = _read_manifest(root)
    import zipfile

    ivf_manifest = manifest.get("ivf")
    ivf_arrays = None
    try:
        with np.load(root / ARRAYS_NAME, allow_pickle=False) as z:
            features = z["features"]
            labels = z["labels"]
            raw_targets = z["raw_targets"] if "raw_targets" in z else None
            if isinstance(ivf_manifest, dict):
                # Read inside the open npz; validated into an IVFIndex
                # below, after the dataset's own schema checks pass.
                ivf_arrays = {k: z[k] for k in
                              ("ivf_centroids", "ivf_row_perm",
                               "ivf_cell_offsets") if k in z}
    # BadZipFile subclasses Exception directly (not OSError/ValueError) and
    # is what a truncated/corrupt .npz actually raises.
    except (OSError, KeyError, ValueError, zipfile.BadZipFile) as e:
        raise DataError(f"{root / ARRAYS_NAME}: unreadable arrays: {e}") from e
    attrs = [
        Attribute(
            a["name"], a["type"], a.get("nominal_values"),
            a.get("string_values"),
        )
        for a in manifest.get("attributes", [])
    ]
    train = Dataset(
        features=features, labels=labels,
        relation=manifest.get("relation", ""), attributes=attrs,
        raw_targets=raw_targets,
    )
    want = manifest.get("schema_hash")
    got = schema_hash(train)
    if want != got:
        raise DataError(
            f"{root}: schema hash mismatch (manifest {want!r}, arrays "
            f"{got!r}) — the manifest and arrays.npz are not from the same "
            f"save; rebuild the index"
        )
    family = manifest.get("family")
    try:
        if family == "classifier":
            model = KNNClassifier(
                k=manifest["k"], backend=manifest.get("backend", "tpu"),
                metric=manifest.get("metric", "euclidean"),
                weights=manifest.get("weights", "uniform"),
                **manifest.get("backend_opts", {}),
            )
        elif family == "regressor":
            model = KNNRegressor(
                k=manifest["k"],
                weights=manifest.get("weights", "uniform"),
                metric=manifest.get("metric", "euclidean"),
                engine=manifest.get("engine", "auto"),
            )
        else:
            raise DataError(f"{root}: unknown model family {family!r}")
        model.fit(train)
    except (KeyError, TypeError, ValueError) as e:
        if isinstance(e, DataError):
            raise
        raise DataError(f"{root}: manifest does not describe a loadable "
                        f"model: {e}") from e
    if isinstance(ivf_manifest, dict):
        # Format 3: attach the validated IVF partition. A structurally
        # corrupt partition is a typed load failure (never wrong answers
        # mid-request); a format-1/2 artifact skips this entirely and the
        # model carries no ivf_ attribute.
        from knn_tpu.index.ivf import IVF_ATTR, IVFIndex

        if manifest.get("metric") != "euclidean":
            # save_index refuses this pairing; an artifact carrying it
            # was hand-edited (schema_hash covers attribute metadata,
            # not the metric field). Probing euclidean cells under
            # another metric would serve wrong-geometry answers.
            raise DataError(
                f"{root}: artifact pairs an ivf partition with metric "
                f"{manifest.get('metric')!r}; ivf partitions are "
                f"euclidean-only — rebuild the index"
            )
        setattr(model, IVF_ATTR, IVFIndex.from_arrays(
            ivf_arrays or {}, ivf_manifest,
            train_rows=train.num_instances,
            num_features=train.num_features, where=str(root),
        ))
    return model


# -- delta-epoch persistence (the mutable tier) -----------------------------


def epoch_path(root, epoch: int) -> Path:
    return Path(root) / EPOCHS_DIR / f"epoch-{epoch:08d}.jsonl"


def generation_path(root, generation: int) -> Path:
    return Path(root) / GENERATIONS_DIR / f"gen-{generation:06d}"


def list_epochs(root) -> "list[tuple[int, Path]]":
    """Epoch-log files under ``root``, sorted by epoch number. Files that
    do not match the naming scheme are a typed refusal — something else
    wrote into the artifact's epochs directory."""
    d = Path(root) / EPOCHS_DIR
    if not d.is_dir():
        return []
    out = []
    for p in sorted(d.iterdir()):
        if p.name.endswith(".jsonl.tmp"):
            # A crash inside repair_epoch's write-then-replace window
            # leaves its temp file behind; the original epoch is intact
            # (the replace never happened), so the leftover is garbage —
            # refusing to boot over it would brick the artifact.
            continue
        if not (p.name.startswith("epoch-") and p.name.endswith(".jsonl")):
            raise DataError(
                f"{p}: not an epoch-log file; the {EPOCHS_DIR}/ directory "
                f"belongs to the mutable tier's write-ahead log"
            )
        try:
            out.append((int(p.name[len("epoch-"):-len(".jsonl")]), p))
        except ValueError as e:
            raise DataError(f"{p}: unparseable epoch number") from e
    out.sort()
    return out


def read_epoch_records(path, tolerate_torn: bool = False):
    """Parse one epoch log. Returns ``(records, torn)`` — ``torn`` is True
    when the FINAL line is an unparseable fragment and ``tolerate_torn``
    allowed it (a crash mid-append; that mutation was never acknowledged,
    so dropping it loses nothing). A bad line anywhere else — or a final
    fragment without tolerance — is a typed :class:`DataError`: the log
    is corrupt, not merely truncated."""
    path = Path(path)
    try:
        lines = path.read_text(encoding="utf-8").splitlines()
    except OSError as e:
        raise DataError(f"{path}: unreadable epoch log: {e}") from e
    records = []
    for n, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict) or "seq" not in rec:
                raise ValueError("not a mutation record")
        except ValueError as e:
            if tolerate_torn and n == len(lines) - 1:
                return records, True
            raise DataError(
                f"{path}:{n + 1}: corrupt epoch-log record: {e}"
            ) from e
        records.append(rec)
    return records, False


def repair_epoch(path, records: "list[dict]") -> None:
    """Rewrite an epoch log as exactly ``records`` (atomic replace) —
    called by boot replay after it DROPPED a tolerated torn final
    fragment. Boot owns the WAL, so repairing here matters: once a later
    epoch exists this one is no longer last and gets no torn-tolerance,
    and without the repair the NEXT boot would refuse (typed DataError) a
    state this boot accepted."""
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class EpochLog:
    """Append-side of one write-ahead epoch file. Every record is written
    and FLUSHED before the mutation is acknowledged: a SIGKILL'd process
    loses at most the in-flight (never-acked) append — the crash-recovery
    half of the mutable-soak gate."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")

    def append(self, record: dict) -> None:
        self._f.write(json.dumps(record, separators=(",", ":")) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def read_current(root) -> Optional[dict]:
    """The compaction pointer, or None for a never-compacted artifact.
    Validated minimally — the named base generation must exist and be a
    loadable artifact (the caller loads it)."""
    p = Path(root) / CURRENT_NAME
    if not p.exists():
        return None
    try:
        doc = json.loads(p.read_text())
        if not isinstance(doc, dict) or "generation" not in doc:
            raise ValueError("not a compaction pointer")
        return doc
    except (OSError, ValueError) as e:
        raise DataError(f"{p}: unreadable compaction pointer: {e}") from e


def write_current(root, doc: dict) -> None:
    """Atomically replace the compaction pointer — the commit point of a
    compaction: a crash before this line leaves the old generation
    serving with every epoch record still replayable."""
    p = Path(root) / CURRENT_NAME
    tmp = p.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    os.replace(tmp, p)


def resolve_mutable_base(root) -> "tuple[Path, Optional[dict]]":
    """Where a mutable-serving boot actually loads its base model from:
    the generation ``CURRENT.json`` points at, or ``root`` itself for a
    never-compacted artifact. Returns ``(base_dir, current_doc)``."""
    root = Path(root)
    cur = read_current(root)
    if cur is None:
        return root, None
    rel = cur.get("base")
    base = root / rel if rel else root
    if not (base / MANIFEST_NAME).exists():
        raise DataError(
            f"{root}: {CURRENT_NAME} points at missing generation "
            f"{rel!r}; the artifact is corrupt"
        )
    return base, cur


def read_mutable_block(base_dir) -> "tuple[Optional[dict], Optional[np.ndarray]]":
    """The generation's mutable metadata: ``(manifest block, stable_ids)``
    — both None for a plain (never-compacted) artifact, whose base rows
    implicitly keep stable ids ``0..N-1``."""
    base_dir = Path(base_dir)
    manifest = _read_manifest(base_dir)
    block = manifest.get("mutable")
    if not isinstance(block, dict):
        return None, None
    import zipfile

    try:
        with np.load(base_dir / ARRAYS_NAME, allow_pickle=False) as z:
            stable = np.asarray(z["mutable_stable_ids"], np.int64)
    except (OSError, KeyError, ValueError, zipfile.BadZipFile) as e:
        raise DataError(
            f"{base_dir}: manifest declares a mutable block but "
            f"mutable_stable_ids is unreadable: {e}"
        ) from e
    return block, stable


def warmup(model, batch_sizes=(1, 256), kinds=("predict",)) -> dict:
    """Trigger first-call compilation for the given dispatch shapes.

    Runs each ``kind`` at each batch size on synthetic rows drawn from the
    fitted train set (real data distribution, so data-dependent branches
    like the finite-input fast path warm the same executable serving will
    use). Returns ``{f"{kind}@{rows}": wall_ms}`` — the server logs these
    and flips ready only afterwards, so no user request ever pays the
    multi-second compile.
    """
    train = model.train_
    out = {}
    with obs.span("serve.warmup", shapes=len(batch_sizes) * len(kinds)):
        for rows in sorted({int(b) for b in batch_sizes}):
            if rows < 1:
                raise ValueError(f"warmup batch sizes must be >= 1: {rows}")
            reps = -(-rows // train.num_instances)  # ceil
            feats = np.tile(train.features, (reps, 1))[:rows]
            ds = Dataset(feats, np.zeros(rows, np.int32))
            for kind in kinds:
                t0 = time.monotonic()
                if kind == "predict":
                    if isinstance(model, KNNClassifier):
                        model.predict_from_candidates(*model.kneighbors(ds))
                    else:
                        model.predict(ds)
                elif kind == "kneighbors":
                    model.kneighbors(ds)
                else:
                    raise ValueError(f"unknown warmup kind {kind!r}")
                out[f"{kind}@{rows}"] = round(
                    (time.monotonic() - t0) * 1e3, 3
                )
    return out
