"""Dynamic micro-batcher: coalesce concurrent requests into device batches.

One TPU dispatch on a 64-row batch costs barely more than one on a single
row (the kernel's grid is train-side; query rows ride the same sweep), so
the way to serve many small concurrent requests fast is to NOT dispatch
them individually: queue them, close a batch when either ``max_batch`` rows
are waiting or the oldest request has waited ``max_wait_ms``, retrieve
candidates for the whole batch in ONE engine dispatch, and scatter each
request its slice. Latency cost: at most ``max_wait_ms`` of added queue
wait; throughput gain: one dispatch amortized over every coalesced request
(measured in bench.py's ``serving`` config).

Correctness contract: every query row's retrieval is row-independent
(per-row distance, per-row top-k, per-row vote — SURVEY.md §3.5), so the
batched path is **bit-identical** to calling the synchronous API per
request, whatever batch its rows landed in (pinned by
tests/test_serve.py::TestBatcherBitIdentity across threads × engines ×
both model families).

Design notes:

- One worker thread owns all device dispatch; HTTP handler threads only
  enqueue and wait on futures. This sidesteps concurrent-dispatch
  contention and makes the dispatch order deterministic (FIFO).
- Both ``predict`` and ``kneighbors`` requests coalesce into the SAME
  retrieval dispatch — predict is kneighbors + a host-side vote
  (:meth:`KNNClassifier.predict_from_candidates`), so mixing kinds costs
  nothing.
- Admission control is row-bounded: ``max_queue_rows`` queued rows → new
  submissions fail fast with :class:`OverloadError` (HTTP 429 upstream).
  A per-request ``deadline_ms`` expires requests still queued when their
  batch closes with :class:`DeadlineExceededError` (HTTP 504) instead of
  dispatching work nobody is waiting for.
- Futures are :class:`~knn_tpu.models.knn.AsyncResult` handles whose
  finish closure waits on a per-request event and is marked
  ``__accepts_timeout__``, so ``result(timeout=...)`` is a bounded wait
  with no extra thread.

Tuning ``max_wait_ms`` (docs/SERVING.md): it is the price of coalescing —
0 disables batching in all but back-to-back arrival, a value near the
per-dispatch wall time roughly doubles worst-case latency for ~max_batch×
fewer dispatches. Start at ~¼ of your per-dispatch latency.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import AsyncResult, KNNClassifier
from knn_tpu.obs import instrument
from knn_tpu.resilience.errors import DeadlineExceededError, OverloadError

KINDS = ("predict", "kneighbors")


class _Request:
    """One queued request: features, kind, timing, and the completion
    event its future waits on."""

    __slots__ = (
        "features", "kind", "rows", "enqueued_ns", "deadline_ns", "event",
        "value", "error",
    )

    def __init__(self, features: np.ndarray, kind: str,
                 deadline_ns: Optional[int]):
        self.features = features
        self.kind = kind
        self.rows = features.shape[0]
        self.enqueued_ns = time.monotonic_ns()
        self.deadline_ns = deadline_ns
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None

    # -- completion (worker side) -----------------------------------------

    def _finish(self, outcome: str) -> None:
        try:
            ms = (time.monotonic_ns() - self.enqueued_ns) / 1e6
            instrument.record_serve_request_done(self.kind, outcome, ms)
        except Exception:  # noqa: BLE001 — metrics must never block
            pass  # completion: a waiter left unsignaled is a hung client
        finally:
            self.event.set()

    def succeed(self, value) -> None:
        self.value = value
        self._finish("ok")

    def fail(self, error: BaseException, outcome: str = "error") -> None:
        self.error = error
        self._finish(outcome)

    # -- future (client side) ----------------------------------------------

    def handle(self) -> AsyncResult:
        def finish(timeout: Optional[float] = None):
            if not self.event.wait(timeout):
                raise DeadlineExceededError(
                    f"{self.kind} request not served within "
                    f"{timeout * 1e3:.0f} ms (still queued or in dispatch; "
                    f"result() again to keep waiting)"
                )
            if self.error is not None:
                raise self.error
            return self.value

        finish.__accepts_timeout__ = True
        return AsyncResult(finish)


class MicroBatcher:
    """Thread-safe dynamic micro-batching front door for a fitted model.

    ``model`` is a fitted :class:`KNNClassifier` or :class:`KNNRegressor`;
    retrieval goes through ``model.kneighbors`` (the model's own engine
    selection and device cache), votes/aggregation through the same host
    twins the async API uses — so results are bit-identical to the
    synchronous per-request calls.

    ``max_batch``      — close a batch at this many queued rows;
    ``max_wait_ms``    — ... or when the oldest queued request has waited
                         this long, whichever first;
    ``max_queue_rows`` — admission bound: queued rows beyond this fail
                         submissions with :class:`OverloadError`.
    """

    def __init__(self, model, *, max_batch: int = 256,
                 max_wait_ms: float = 2.0, max_queue_rows: int = 4096):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_queue_rows < max_batch:
            raise ValueError(
                f"max_queue_rows ({max_queue_rows}) must be >= max_batch "
                f"({max_batch}) or full batches could never form"
            )
        model.train_  # raises RuntimeError before fit — fail at build time
        self._model = model
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue_rows = int(max_queue_rows)
        self._cond = threading.Condition()
        self._queue: deque[_Request] = deque()
        self._queued_rows = 0
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="knn-serve-batcher", daemon=True
        )
        self._worker.start()

    # -- client side -------------------------------------------------------

    def submit(self, features, kind: str = "predict",
               deadline_ms: Optional[float] = None) -> AsyncResult:
        """Enqueue one request; returns the future immediately.

        ``features``: one query row ``[D]`` or a row batch ``[q, D]``
        (float32-coerced). ``deadline_ms`` bounds the QUEUE+DISPATCH time:
        a request still undispatched when it expires fails with
        :class:`DeadlineExceededError` instead of occupying a batch slot.
        Raises :class:`OverloadError` when the queue is full or the
        batcher is closed, :class:`ValueError` for shape mismatches.
        """
        if kind not in KINDS:
            raise ValueError(f"unknown request kind {kind!r}; choose "
                             f"{' or '.join(KINDS)}")
        x = np.ascontiguousarray(features, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        want_d = self._model.train_.num_features
        if x.ndim != 2 or x.shape[1] != want_d:
            raise ValueError(
                f"features must be [q, {want_d}] (or one [{want_d}] row), "
                f"got {np.shape(features)}"
            )
        if x.shape[0] == 0:
            raise ValueError("empty request (0 query rows)")
        deadline_ns = (
            time.monotonic_ns() + int(deadline_ms * 1e6)
            if deadline_ms is not None else None
        )
        req = _Request(x, kind, deadline_ns)
        with self._cond:
            if self._closed:
                instrument.record_serve_rejected("closed")
                raise OverloadError("batcher is shut down")
            if self._queued_rows + req.rows > self.max_queue_rows:
                instrument.record_serve_rejected("queue_full")
                raise OverloadError(
                    f"request queue full ({self._queued_rows} rows queued, "
                    f"bound {self.max_queue_rows}); retry after backoff"
                )
            self._queue.append(req)
            self._queued_rows += req.rows
            self._cond.notify_all()
        instrument.record_serve_request(kind, req.rows)
        return req.handle()

    def predict(self, features, timeout: Optional[float] = None):
        """Synchronous convenience: ``submit(..., 'predict').result()``."""
        return self.submit(features, "predict").result(timeout=timeout)

    def kneighbors(self, features, timeout: Optional[float] = None):
        """Synchronous convenience: ``submit(..., 'kneighbors').result()``."""
        return self.submit(features, "kneighbors").result(timeout=timeout)

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting work, drain the queue, and join the worker.
        Already-queued requests are still dispatched; new submissions
        raise :class:`OverloadError`. Idempotent."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker side -------------------------------------------------------

    def _collect(self) -> "list[_Request]":
        """Block until a batch closes; [] only at shutdown with an empty
        queue. Coalescing rule: from the arrival of the OLDEST queued
        request, wait up to ``max_wait_ms`` for more work, closing early
        at ``max_batch`` rows (or on shutdown). Whole requests only — a
        request larger than ``max_batch`` dispatches alone, oversized."""
        with self._cond:
            while not self._queue and not self._closed:
                self._cond.wait()
            if not self._queue:
                return []
            # The span covers only the coalescing window, not the idle
            # block above — an idle server must not inflate queue totals.
            with obs.span("serve.queue", waiting_rows=self._queued_rows):
                deadline_ns = self._queue[0].enqueued_ns + int(
                    self.max_wait_ms * 1e6
                )
                while not self._closed and self._queued_rows < self.max_batch:
                    wait_s = (deadline_ns - time.monotonic_ns()) / 1e9
                    if wait_s <= 0:
                        break
                    self._cond.wait(wait_s)
            batch, rows = [], 0
            while self._queue:
                nxt = self._queue[0]
                if batch and rows + nxt.rows > self.max_batch:
                    break
                batch.append(self._queue.popleft())
                rows += nxt.rows
            self._queued_rows -= rows
            return batch

    def _run(self) -> None:
        # The worker must survive ANYTHING (an instrumentation bug
        # included — found live: a conflicting-bucket registration): a
        # dead worker strands every queued future until its timeout,
        # which presents as a hung server. _Request._finish is itself
        # exception-proof, so failing the batch here cannot re-raise.
        while True:
            batch = None
            try:
                batch = self._collect()
                if not batch:
                    return
                self._dispatch(batch)
            except BaseException as e:  # noqa: BLE001 — see above
                for req in batch or ():
                    if not req.event.is_set():
                        req.fail(e)
                if batch is None:
                    # _collect itself failed: nothing to deliver the error
                    # to; don't spin hot on a persistently broken path.
                    time.sleep(0.05)

    def _dispatch(self, batch: "list[_Request]") -> None:
        now_ns = time.monotonic_ns()
        live: "list[_Request]" = []
        for req in batch:
            instrument.record_serve_queue_wait(
                (now_ns - req.enqueued_ns) / 1e6, req.kind
            )
            if req.deadline_ns is not None and now_ns > req.deadline_ns:
                instrument.record_serve_deadline_expired()
                req.fail(
                    DeadlineExceededError(
                        f"{req.kind} request expired in queue after "
                        f"{(now_ns - req.enqueued_ns) / 1e6:.1f} ms"
                    ),
                    outcome="expired",
                )
                continue
            live.append(req)
        if not live:
            return
        rows = sum(r.rows for r in live)
        t0 = time.monotonic()
        try:
            with obs.span("serve.batch", requests=len(live), rows=rows):
                features = (
                    live[0].features if len(live) == 1
                    else np.concatenate([r.features for r in live])
                )
                batch_ds = Dataset(features, np.zeros(rows, np.int32))
            with obs.span("serve.dispatch", requests=len(live), rows=rows):
                dists, idx = self._model.kneighbors(batch_ds)
                off = 0
                for req in live:
                    d = dists[off:off + req.rows]
                    i = idx[off:off + req.rows]
                    off += req.rows
                    if req.kind == "kneighbors":
                        req.succeed((d, i))
                    elif isinstance(self._model, KNNClassifier):
                        req.succeed(
                            self._model.predict_from_candidates(d, i)
                        )
                    else:
                        req.succeed(self._model._predict_from((d, i)))
            instrument.record_serve_batch(
                len(live), rows, (time.monotonic() - t0) * 1e3
            )
        except Exception as e:  # noqa: BLE001 — delivered per-future
            obs.counter_add(
                "knn_serve_errors_total",
                help="micro-batch dispatches that raised (typed error "
                     "delivered to every coalesced request)",
                type=type(e).__name__,
            )
            for req in live:
                if not req.event.is_set():
                    req.fail(e)
