"""Dynamic micro-batcher: coalesce concurrent requests into device batches.

One TPU dispatch on a 64-row batch costs barely more than one on a single
row (the kernel's grid is train-side; query rows ride the same sweep), so
the way to serve many small concurrent requests fast is to NOT dispatch
them individually: queue them, close a batch when either ``max_batch`` rows
are waiting or the oldest request has waited ``max_wait_ms``, retrieve
candidates for the whole batch in ONE engine dispatch, and scatter each
request its slice. Latency cost: at most ``max_wait_ms`` of added queue
wait; throughput gain: one dispatch amortized over every coalesced request
(measured in bench.py's ``serving`` config).

Correctness contract: every query row's retrieval is row-independent
(per-row distance, per-row top-k, per-row vote — SURVEY.md §3.5), so the
batched path is **bit-identical** to calling the synchronous API per
request, whatever batch its rows landed in (pinned by
tests/test_serve.py::TestBatcherBitIdentity across threads × engines ×
both model families).

Self-healing (docs/SERVING.md §Ops runbook): the worker's device dispatch
is wrapped in an in-loop **degradation ladder** and a **circuit breaker**
(:mod:`knn_tpu.resilience.breaker`):

- a typed device failure (``DeviceError``/``CompileError``/
  ``CollectiveError``) on the model's configured fast rung walks down
  ``fast → xla → oracle`` — every rung votes bit-identical predictions
  (the ladder contract), so degradation changes *where* the batch is
  retrieved, never *what* the client gets;
- ``DeviceError(oom=True)`` halves ``max_batch`` in place and re-executes
  the same rung in smaller chunks — degrading batch size before backend;
- persistent fast-rung failure trips the breaker open: batches
  short-circuit straight to the last-good degraded rung (no doomed
  dispatch + ladder walk per batch), half-open probes re-try the fast
  rung after the cooldown and re-promote it when the device recovers;
- a request whose ``deadline_ms`` expires *mid-fallback* fails with
  :class:`DeadlineExceededError` rather than getting a slow success from
  a lower rung;
- a **supervisor** thread restarts the worker if it ever dies (counted in
  ``knn_serve_worker_restarts_total`` + logged) — queued futures survive
  the restart instead of hanging until their timeouts.

Design notes:

- One worker thread owns all device dispatch; HTTP handler threads only
  enqueue and wait on futures. This sidesteps concurrent-dispatch
  contention and makes the dispatch order deterministic (FIFO).
- Both ``predict`` and ``kneighbors`` requests coalesce into the SAME
  retrieval dispatch — predict is kneighbors + a host-side vote
  (:meth:`KNNClassifier.predict_from_candidates`), so mixing kinds costs
  nothing.
- Admission control is row-bounded: ``max_queue_rows`` queued rows → new
  submissions fail fast with :class:`OverloadError` (HTTP 429 upstream).
  A per-request ``deadline_ms`` expires requests still queued when their
  batch closes with :class:`DeadlineExceededError` (HTTP 504) instead of
  dispatching work nobody is waiting for.
- Futures are :class:`~knn_tpu.models.knn.AsyncResult` handles whose
  finish closure waits on a per-request event and is marked
  ``__accepts_timeout__``, so ``result(timeout=...)`` is a bounded wait
  with no extra thread. Each handle's ``meta`` dict carries the
  ``index_version`` and the ladder rung that served it.
- :meth:`swap_model` atomically replaces the served model between batches
  (the hot-reload path): every batch snapshots (model, version) once, so
  a response reflects exactly one index — never a mix.
- :meth:`begin_drain` (SIGTERM) refuses new admissions while already
  queued work keeps dispatching; :meth:`fail_pending` gives whatever
  cannot be answered in the drain window a typed terminal outcome.

Tuning ``max_wait_ms`` (docs/SERVING.md): it is the price of coalescing —
0 disables batching in all but back-to-back arrival, a value near the
per-dispatch wall time roughly doubles worst-case latency for ~max_batch×
fewer dispatches. Start at ~¼ of your per-dispatch latency.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import (
    AsyncResult,
    KNNClassifier,
    _kneighbors_arrays,
    normalize_buckets,
    query_padded_rows,
)
from knn_tpu.obs import accounting as acct
from knn_tpu.obs import instrument, reqtrace
from knn_tpu.resilience import faults
from knn_tpu.resilience.breaker import CircuitBreaker
from knn_tpu.resilience.errors import (
    CollectiveError,
    CompileError,
    DeadlineExceededError,
    DeviceError,
    OverloadError,
    ResilienceError,
    ShedByPolicy,
)

KINDS = ("predict", "kneighbors")

MUTATION_OPS = ("insert", "delete")


class _Mutation:
    """One queued mutation: applied by the worker thread between read
    dispatches (mutations serialize against dispatches; read admission
    never blocks on a write). The future contract mirrors
    :class:`_Request` — exactly one terminal outcome."""

    __slots__ = ("op", "payload", "enqueued_ns", "event", "value", "error")

    def __init__(self, op: str, payload: dict):
        self.op = op
        self.payload = payload
        self.enqueued_ns = time.monotonic_ns()
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None

    def succeed(self, value) -> None:
        self.value = value
        self.event.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.event.set()

    def handle(self) -> AsyncResult:
        def finish(timeout: Optional[float] = None):
            if not self.event.wait(timeout):
                raise DeadlineExceededError(
                    f"{self.op} mutation not applied within "
                    f"{timeout * 1e3:.0f} ms (result() again to keep "
                    f"waiting)"
                )
            if self.error is not None:
                raise self.error
            return self.value

        finish.__accepts_timeout__ = True
        return AsyncResult(finish)


class _Request:
    """One queued request: features, kind, timing, the completion event
    its future waits on, and (when request tracing is on) the
    :class:`~knn_tpu.obs.reqtrace.RequestTrace` that owns its timeline."""

    __slots__ = (
        "features", "kind", "rows", "enqueued_ns", "deadline_ns", "event",
        "value", "error", "meta", "trace", "request_class", "accounting",
        "workload",
    )

    def __init__(self, features: np.ndarray, kind: str,
                 deadline_ns: Optional[int],
                 trace: "Optional[reqtrace.RequestTrace]" = None,
                 request_class: Optional[str] = None,
                 accounting: "Optional[acct.CostAccountant]" = None,
                 workload=None):
        self.features = features
        self.kind = kind
        self.rows = features.shape[0]
        self.enqueued_ns = time.monotonic_ns()
        self.deadline_ns = deadline_ns
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.meta: dict = {}
        self.trace = trace
        self.request_class = request_class
        self.accounting = accounting
        self.workload = workload

    # -- completion (worker side) -----------------------------------------

    def _finish(self, outcome: str) -> None:
        try:
            ms = (time.monotonic_ns() - self.enqueued_ns) / 1e6
            instrument.record_serve_request_done(
                self.kind, outcome, ms,
                trace_id=(self.trace.request_id
                          if self.trace is not None else None),
            )
            if self.accounting is not None:
                # Class labels survive every terminal path (ok, expired,
                # error): the per-class outcome counter is what makes a
                # class's 504s visible next to its device spend.
                self.accounting.note_outcome(self.request_class, outcome)
            if self.workload is not None:
                # Workload capture tap (obs/workload.py): one predicate
                # while no window is armed; during one, a seeded RNG draw
                # + an O(1) bounded append — shed when full, NEVER blocks
                # (the ShedQueue contract). Annotates the trace with the
                # workload record id so access-log lines and timelines
                # resolve back to the captured record.
                self.workload.note_request(self, outcome)
            if self.trace is not None:
                if self.error is not None:
                    self.trace.annotate(
                        error=f"{type(self.error).__name__}: {self.error}"
                    )
                self.trace.finish(outcome)
        except Exception:  # noqa: BLE001 — metrics must never block
            pass  # completion: a waiter left unsignaled is a hung client
        finally:
            self.event.set()

    def succeed(self, value) -> None:
        self.value = value
        self._finish("ok")

    def fail(self, error: BaseException, outcome: str = "error") -> None:
        self.error = error
        self._finish(outcome)

    # -- future (client side) ----------------------------------------------

    def handle(self) -> AsyncResult:
        def finish(timeout: Optional[float] = None):
            if not self.event.wait(timeout):
                raise DeadlineExceededError(
                    f"{self.kind} request not served within "
                    f"{timeout * 1e3:.0f} ms (still queued or in dispatch; "
                    f"result() again to keep waiting)"
                )
            if self.error is not None:
                raise self.error
            return self.value

        finish.__accepts_timeout__ = True
        return AsyncResult(finish, meta=self.meta)


class _UploadStager:
    """Per-bucket pinned staging + double-buffered device upload.

    The dispatch worker is single-threaded, so without help batch N+1's
    host→device transfer cannot start until batch N's result is back.
    This stager closes that gap: while batch N's device compute is in
    flight (the fast rung dispatches *deferred* — device work launched,
    host sync postponed), the worker peeks the queue, stages the rows
    that will form batch N+1 into a per-bucket host buffer, and starts
    their upload (``jax.device_put`` returns immediately; the copy
    proceeds while N computes). At dispatch N+1 the padded block is
    already resident and the retrieval core consumes it instead of
    re-padding + re-uploading (``models/knn._kneighbors_arrays``'s
    ``prefetched_queries``).

    Buffers are **pinned per (bucket, parity)**: each compiled bucket
    shape owns two ping-pong host arrays reused for every batch — batch
    N's block stays untouched while N+1 stages into the other parity, and
    the engine sees the same buffers dispatch after dispatch instead of a
    fresh allocation each time (the donate-friendly discipline; on CPU
    jax this is also what lets ``device_put`` alias instead of copy).

    Correctness is by *identity*: a prefetched block is consumed only
    when the next batch is EXACTLY the request list it was staged from
    (same objects, same order) — any divergence (new arrivals reshaping
    the batch, a deadline expiry, a drained queue) silently drops the
    prefetch and the dispatch re-stages from scratch. Padded shape and
    zero tail come from the same ``query_padded_rows`` definition the
    engine pads with, so a consumed block is bit-identical to the pad the
    engine would have built.
    """

    __slots__ = ("_num_features", "_buffers", "_flip", "_pending")

    def __init__(self, num_features: int):
        self._num_features = int(num_features)
        self._buffers: dict = {}
        self._flip = 0
        self._pending = None  # (request id tuple, host rows view, device)

    def _buffer(self, bucket: int) -> np.ndarray:
        key = (bucket, self._flip)
        buf = self._buffers.get(key)
        if buf is None:
            buf = self._buffers[key] = np.zeros(
                (bucket, self._num_features), np.float32)
        return buf

    def prefetch(self, batcher: "MicroBatcher") -> None:
        """Peek the queue, stage the batch it would form next, and start
        its device upload. Called by the fast rung BETWEEN dispatching
        batch N and resolving it — the overlap window. Never raises: a
        failed prefetch costs only the lost overlap."""
        try:
            reqs, rows = [], 0
            with batcher._cond:
                for r in batcher._queue:
                    if rows + r.rows > batcher.max_batch:
                        break
                    reqs.append(r)
                    rows += r.rows
                    if rows >= batcher.max_batch:
                        break
            if not reqs:
                self._pending = None
                return
            bucket = query_padded_rows(rows)
            if bucket < rows:
                self._pending = None
                return
            import jax

            self._flip ^= 1
            buf = self._buffer(bucket)
            off = 0
            for r in reqs:
                buf[off:off + r.rows] = r.features
                off += r.rows
            buf[off:] = 0.0  # the pad contract: zero tail
            dev = jax.device_put(buf)
            # STRONG references to the request objects, matched by `is`
            # at take(): a bare id() tuple would false-match when a
            # pending prefetch outlives its (completed, collected)
            # requests and the allocator hands a later request the same
            # address — which would serve it the OLD queries' answers.
            self._pending = (tuple(reqs), buf[:rows], dev)
        except Exception:  # noqa: BLE001 — prefetch is advisory only
            self._pending = None

    def take(self, live: "list[_Request]"):
        """``(host_rows, device_block)`` iff the prefetch was staged from
        exactly this request list (object identity, in order); else None
        (and the prefetch is dropped either way — single use)."""
        pending, self._pending = self._pending, None
        if pending is None:
            return None
        reqs, host, dev = pending
        if len(reqs) != len(live) or any(a is not b
                                         for a, b in zip(reqs, live)):
            return None
        return host, dev


class MicroBatcher:
    """Thread-safe dynamic micro-batching front door for a fitted model.

    ``model`` is a fitted :class:`KNNClassifier` or :class:`KNNRegressor`;
    retrieval goes through ``model.kneighbors`` (the model's own engine
    selection and device cache), votes/aggregation through the same host
    twins the async API uses — so results are bit-identical to the
    synchronous per-request calls.

    ``max_batch``      — close a batch at this many queued rows (halved in
                         place when a dispatch OOMs);
    ``max_wait_ms``    — ... or when the oldest queued request has waited
                         this long, whichever first;
    ``max_queue_rows`` — admission bound: queued rows beyond this fail
                         submissions with :class:`OverloadError`;
    ``index_version``  — opaque version tag stamped on every response's
                         ``meta`` (the artifact store's version on the
                         serving path; None for embedded use);
    ``recorder``       — an optional
                         :class:`~knn_tpu.obs.reqtrace.FlightRecorder`:
                         when set, every admitted request owns a
                         :class:`~knn_tpu.obs.reqtrace.RequestTrace`
                         timeline (queue_wait/dispatch phases, per-rung
                         attempts, breaker + fallback events) committed to
                         the recorder at its terminal outcome. None (the
                         default) keeps the whole layer at one
                         ``is None`` predicate per call site.
    ``quality``        — an optional
                         :class:`~knn_tpu.obs.quality.ShadowScorer`: each
                         successfully-served request is offered for
                         shadow scoring (one seeded RNG draw + an O(1)
                         bounded-queue append on this worker thread; a
                         full queue sheds, NEVER blocks — the latency
                         acceptance bench.py measures). The sample
                         carries this batch's own (model, version)
                         snapshot so scoring stays correct across hot
                         reloads.
    ``drift``          — an optional
                         :class:`~knn_tpu.obs.drift.DriftMonitor`: served
                         query rows are offered to the drift sketch under
                         the same sampled, shed-on-overload contract.
    ``accounting``     — an optional
                         :class:`~knn_tpu.obs.accounting.CostAccountant`:
                         every ladder-rung attempt's measured wall (and
                         the answering attempt's transferred bytes) is
                         attributed across the batch's live requests
                         proportional to rows, tagged by request class and
                         rung, with padded (compiled-shape) rows counted
                         as waste — the ``knn_cost_*`` instrument set and
                         the per-request ``cost`` block in futures' meta
                         and flight-recorder timelines.
    ``capacity``       — an optional
                         :class:`~knn_tpu.obs.capacity.CapacityTracker`:
                         arrivals, served requests, and dispatch
                         busy-time/occupancy feed its rate rings and the
                         headroom model (``knn_capacity_*``,
                         ``GET /debug/capacity``).
    ``ivf``            — an optional
                         :class:`~knn_tpu.index.ivf.IVFServing`: slots an
                         ``ivf`` rung ABOVE ``fast`` in the ladder —
                         probed approximate retrieval over the model's
                         IVF partition (``model.ivf_``), with the probe
                         policy choosing ``nprobe`` per dispatch. The
                         exact rungs below stay the truth anchor: any
                         typed ivf failure degrades to bit-exact
                         retrieval. None (the default, and always for
                         partition-less models) keeps the ladder exact
                         with one ``is None`` predicate.
    ``workload``       — an optional
                         :class:`~knn_tpu.obs.workload.WorkloadCapture`:
                         every terminal request outcome (ok, expired,
                         error, rejected) and every acknowledged
                         mutation is offered for workload capture under
                         the same sampled, shed-on-overload contract —
                         the replayable traffic record behind
                         ``knn_tpu replay`` (docs/OBSERVABILITY.md
                         §Workload capture & replay).
    ``buckets``        — the compiled-shape bucket ladder the serving
                         boot installed (``models/knn.set_query_buckets``
                         from ``serve --batch-buckets``): enables the
                         per-bucket double-buffered upload stager and is
                         reported in the policy blocks. None (the
                         embedded default) keeps the legacy
                         pad-to-quantum dispatch byte-identical.
    ``result_cache_rows`` — capacity (in cached query rows) of the
                         exact-match result cache
                         (:mod:`knn_tpu.serve.cache`): identical query
                         rows at the same ``(index_version,
                         mutation_seq)`` sequence point are answered
                         without a dispatch, bit-identical by
                         construction; invalidated outright by
                         :meth:`swap_model`. 0 (the default) constructs
                         nothing.
    """

    def __init__(self, model, *, max_batch: int = 256,
                 max_wait_ms: float = 2.0, max_queue_rows: int = 4096,
                 index_version: Optional[str] = None,
                 recorder: "Optional[reqtrace.FlightRecorder]" = None,
                 quality=None, drift=None, accounting=None, capacity=None,
                 ivf=None, mutable=None, workload=None, buckets=None,
                 result_cache_rows: int = 0, admission=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_queue_rows < max_batch:
            raise ValueError(
                f"max_queue_rows ({max_queue_rows}) must be >= max_batch "
                f"({max_batch}) or full batches could never form"
            )
        if result_cache_rows < 0:
            raise ValueError(
                f"result_cache_rows must be >= 0, got {result_cache_rows}")
        model.train_  # raises RuntimeError before fit — fail at build time
        # Shape-bucketed dispatch (docs/SERVING.md §Tuning the bucket
        # ladder): ``buckets`` names the compiled-shape ladder the serving
        # boot installed via models/knn.set_query_buckets — the batcher
        # reads the ONE definition (query_padded_rows) for its bucket
        # boundaries, and uses the ladder here only to (a) report the
        # policy and (b) construct the upload stager. None (the embedded
        # default) keeps the legacy single-quantum pad byte-identical and
        # constructs no stager.
        self.buckets = None if buckets is None else normalize_buckets(
            buckets)
        if self.buckets is not None:
            from knn_tpu.models.knn import query_buckets

            if query_buckets() != self.buckets:
                # The pad is process-global; a batcher reporting one
                # ladder while the engine pads with another would make
                # every waste metric (and the warmed-executable set) lie.
                # ServeApp installs the ladder it is handed; direct
                # embedders must set_query_buckets / query_bucket_ladder
                # first.
                raise ValueError(
                    f"buckets {self.buckets} do not match the installed "
                    f"query bucket ladder {query_buckets()}; call "
                    f"models.knn.set_query_buckets(...) first (the serve "
                    f"boot and ServeApp do this for you)"
                )
        self._stager = (
            _UploadStager(model.train_.num_features)
            if self.buckets is not None else None
        )
        # Worker-confined: set once per _dispatch so a chunked ladder
        # walk prefetches the next batch exactly once (see fast()).
        self._prefetched_this_dispatch = False
        # Exact-match result cache (knn_tpu/serve/cache.py): 0 (the
        # default) constructs NOTHING — no LRU, no hashing, no
        # knn_cache_* instruments; one `is None` predicate per dispatch
        # (scripts/check_disabled_overhead.py pins it).
        if result_cache_rows > 0:
            from knn_tpu.serve.cache import ResultCache

            self.cache = ResultCache(result_cache_rows)
        else:
            self.cache = None
        self._model = model
        self._index_version = index_version
        self.recorder = recorder
        self.quality = quality
        self.drift = drift
        self.accounting = accounting
        self.capacity = capacity
        self.ivf = ivf
        # Mutable serving (knn_tpu/mutable/): an optional MutableEngine.
        # None (the default, and always for --mutable off) constructs
        # NOTHING — no mutation queue work, no per-dispatch snapshot or
        # merge, one `is None` predicate per call site
        # (scripts/check_disabled_overhead.py pins it).
        self.mutable = mutable
        # Workload capture (obs/workload.py): an optional
        # WorkloadCapture. None (the default, and always without
        # --capture-dir) constructs NOTHING — no queue, no consumer
        # thread, no per-request work; one `is None` predicate per
        # terminal outcome (scripts/check_disabled_overhead.py pins it).
        self.workload = workload
        # Priority admission (knn_tpu/control/admission.py): an optional
        # PriorityAdmission. None (the default, and always without
        # --priority) constructs NOTHING — no cutoff evaluation, no
        # priority re-ordering, FIFO semantics byte-identical to
        # pre-control serving; one `is None` predicate per call site
        # (scripts/check_disabled_overhead.py pins it).
        self.admission = admission
        self._mutations: deque = deque()
        # TEST-ONLY corruption hook (scripts/quality_soak.py): when armed
        # (the serve process installs a SIGUSR2 handler only under
        # KNN_TPU_TEST_QUALITY_CORRUPT), served neighbor indices are
        # rotated by one train row — a silently-wrong index whose
        # responses still look healthy to every other SLI. The shadow
        # scorer must catch it; nothing in production ever sets this.
        self.corrupt_serving = False
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue_rows = int(max_queue_rows)
        self.breaker = CircuitBreaker("serve.dispatch")
        self.restarts = 0
        self._last_rung = "fast"
        self._degraded_rung = 1  # ladder position short-circuits start at
        self._cond = threading.Condition()
        self._queue: deque[_Request] = deque()
        self._queued_rows = 0
        self._closed = False
        self._draining = False
        self._worker_error: Optional[BaseException] = None
        self._supervisor = threading.Thread(
            target=self._supervise, name="knn-serve-supervisor", daemon=True
        )
        self._supervisor.start()

    # -- client side -------------------------------------------------------

    def submit(self, features, kind: str = "predict",
               deadline_ms: Optional[float] = None,
               trace: "Optional[reqtrace.RequestTrace]" = None,
               request_class: Optional[str] = None) -> AsyncResult:
        """Enqueue one request; returns the future immediately.

        ``features``: one query row ``[D]`` or a row batch ``[q, D]``
        (float32-coerced). ``deadline_ms`` bounds the QUEUE+DISPATCH time:
        a request still undispatched when it expires fails with
        :class:`DeadlineExceededError` instead of occupying a batch slot.
        ``trace`` attaches a caller-built request context (the HTTP layer
        passes one carrying the ``x-request-id``); with a ``recorder``
        configured and no ``trace``, one is created here at admission.
        ``request_class`` tags the request for cost attribution (the HTTP
        layer parses ``x-knn-class``; default ``interactive``) — ignored
        unless an ``accounting`` layer is wired in.
        Raises :class:`OverloadError` when the queue is full, the batcher
        is draining, or it is closed (the trace, if any, is finished
        ``rejected`` first); :class:`ValueError` for shape mismatches.
        """
        if kind not in KINDS:
            raise ValueError(f"unknown request kind {kind!r}; choose "
                             f"{' or '.join(KINDS)}")
        x = np.ascontiguousarray(features, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        want_d = self._model.train_.num_features
        if x.ndim != 2 or x.shape[1] != want_d:
            raise ValueError(
                f"features must be [q, {want_d}] (or one [{want_d}] row), "
                f"got {np.shape(features)}"
            )
        if x.shape[0] == 0:
            raise ValueError("empty request (0 query rows)")
        deadline_ns = (
            time.monotonic_ns() + int(deadline_ms * 1e6)
            if deadline_ms is not None else None
        )
        if self.accounting is not None:
            # Validate BEFORE the trace is minted: this raise is a plain
            # bad-argument rejection like the shape checks above, and a
            # trace created first would be left forever unresolved
            # (every minted trace must reach finish() — the chaos-soak
            # invariant). The HTTP front door 400s these before submit;
            # embedded callers get the same contract here — class
            # strings become Prometheus label values, so an unvalidated
            # one could corrupt the exposition text.
            request_class = request_class or acct.DEFAULT_CLASS
            if not acct.valid_request_class(request_class):
                raise ValueError(
                    f"invalid request_class {request_class!r}: want 1-"
                    f"{acct.MAX_CLASS_LEN} chars of [a-z0-9_.-]"
                )
            # Cap label cardinality: past MAX_CLASSES distinct values the
            # request folds into the overflow class — a client minting
            # c1, c2, c3, ... must not grow /metrics and the per-class
            # table without bound.
            request_class = self.accounting.admit_class(request_class)
        if trace is None and self.recorder is not None:
            trace = self.recorder.new_trace(kind, x.shape[0])
        req = _Request(x, kind, deadline_ns, trace,
                       request_class=request_class,
                       accounting=self.accounting,
                       workload=self.workload)
        if trace is not None:
            # Embedded callers learn their id from the future's meta (the
            # HTTP layer already knows it — it minted the trace).
            req.meta["request_id"] = trace.request_id
            if self.accounting is not None:
                trace.annotate(request_class=request_class)
        try:
            if self.admission is not None:
                # Priority admission BEFORE the queue bound: a shed is a
                # policy decision about WHO queues, the row bound below
                # is physics about HOW MUCH — and the typed ShedByPolicy
                # (vs plain OverloadError) is what lets the outcome
                # labeling below and the SLO layer tell them apart.
                shed = self.admission.admit(request_class)
                if shed is not None:
                    instrument.record_serve_rejected("shed")
                    raise shed
            with self._cond:
                if self._closed:
                    instrument.record_serve_rejected("closed")
                    raise OverloadError("batcher is shut down")
                if self._draining:
                    instrument.record_serve_rejected("draining")
                    raise OverloadError(
                        "server is draining (shutting down); no new work "
                        "accepted — retry against another replica"
                    )
                if self._queued_rows + req.rows > self.max_queue_rows:
                    instrument.record_serve_rejected("queue_full")
                    raise OverloadError(
                        f"request queue full ({self._queued_rows} rows "
                        f"queued, bound {self.max_queue_rows}); retry "
                        f"after backoff"
                    )
                if trace is not None:
                    trace.phase_start("queue_wait")
                self._queue.append(req)
                self._queued_rows += req.rows
                self._cond.notify_all()
        except OverloadError as e:
            # A refused admission is still a terminal outcome the flight
            # recorder must resolve (every response's request_id maps to a
            # timeline — the chaos-soak invariant). The class label
            # survives the 429 path the same way, and the arrival still
            # counts: the capacity rings track OFFERED load, so the
            # headroom ratio keeps falling past the knee instead of
            # saturating at the admitted (≈ service) rate. A policy shed
            # gets its own outcome label end to end — accounting,
            # workload capture, trace — so a deliberate `bulk` shed
            # never reads as the same event as a queue-full rejection.
            outcome = ("shed" if isinstance(e, ShedByPolicy)
                       else "rejected")
            if self.accounting is not None:
                self.accounting.note_outcome(request_class, outcome)
            if self.capacity is not None:
                self.capacity.note_arrival(req.rows)
            if self.workload is not None:
                # A refused admission is still workload: an incident
                # capture without its 429s would replay as lighter load
                # than the incident actually offered.
                self.workload.note_request(req, outcome)
            if trace is not None:
                trace.annotate(error=f"{type(e).__name__}: {e}")
                trace.finish(outcome)
            raise
        instrument.record_serve_request(kind, req.rows)
        if self.capacity is not None:
            self.capacity.note_arrival(req.rows)
        return req.handle()

    def submit_mutation(self, op: str, payload: dict) -> AsyncResult:
        """Enqueue one mutation for the worker to apply between read
        dispatches (the mutation-admission contract: writes serialize
        against dispatches on the one worker thread; reads never block on
        a write's WAL append). ``payload``: ``{"rows", "values"}`` for
        insert, ``{"ids"}`` for delete. Raises :class:`OverloadError`
        while draining/closed or when the delta tier is already full
        (cheap pre-check; the engine re-checks authoritatively at
        apply)."""
        if self.mutable is None:
            raise ValueError(
                "this batcher serves an immutable index (no mutable "
                "engine wired in)")
        if op not in MUTATION_OPS:
            raise ValueError(f"unknown mutation op {op!r}; choose "
                             f"{' or '.join(MUTATION_OPS)}")
        if op == "insert" and self.mutable.delta_full():
            instrument.record_serve_rejected("delta_full")
            raise OverloadError(
                f"delta tier full ({self.mutable.delta_cap} slots); "
                f"compaction is behind — retry after backoff or trigger "
                f"/admin/compact"
            )
        mut = _Mutation(op, payload)
        with self._cond:
            if self._closed:
                instrument.record_serve_rejected("closed")
                raise OverloadError("batcher is shut down")
            if self._draining:
                instrument.record_serve_rejected("draining")
                raise OverloadError(
                    "server is draining (shutting down); no new "
                    "mutations accepted"
                )
            self._mutations.append(mut)
            self._cond.notify_all()
        return mut.handle()

    def predict(self, features, timeout: Optional[float] = None):
        """Synchronous convenience: ``submit(..., 'predict').result()``."""
        return self.submit(features, "predict").result(timeout=timeout)

    def kneighbors(self, features, timeout: Optional[float] = None):
        """Synchronous convenience: ``submit(..., 'kneighbors').result()``."""
        return self.submit(features, "kneighbors").result(timeout=timeout)

    # -- lifecycle ---------------------------------------------------------

    @property
    def index_version(self) -> Optional[str]:
        return self._index_version

    @property
    def current_rung(self) -> str:
        """The ladder rung that answered the most recent batch."""
        return self._last_rung

    def swap_model(self, model, index_version: Optional[str] = None,
                   hook=None):
        """Atomically replace the served model (the hot-reload path).

        The worker snapshots ``(model, version)`` once per batch under the
        queue lock, so every response reflects exactly one index — the old
        or the new, never a mix. The caller is responsible for warming the
        replacement first (``artifact.warmup``); the swap itself is one
        reference assignment. ``hook`` (compaction's engine rebase) runs
        INSIDE the same critical section, so a dispatch snapshot can never
        pair the new model with a pre-rebase mutable view. Returns the
        previous version tag."""
        model.train_  # fitted-model check, same as the constructor
        with self._cond:
            previous_model = self._model
            previous = self._index_version
            self._model = model
            self._index_version = index_version
            if hook is not None:
                try:
                    hook()
                except BaseException:
                    # A failed rebase must not leave the NEW model paired
                    # with the OLD (un-rebased) mutable view — restore so
                    # "rolled back" means the old generation really keeps
                    # serving (the compaction failure contract).
                    self._model = previous_model
                    self._index_version = previous
                    raise
        if self.cache is not None:
            # The swap/rebase invalidation: every cached answer is keyed
            # on the OLD version tag and would never hit again — drop the
            # memory now. (A dispatch that snapshotted the old model
            # before this swap may still insert old-keyed entries after
            # the clear; they are unreachable and age out of the LRU.)
            self.cache.clear()
        return previous

    def begin_drain(self) -> None:
        """Stop admitting work (submissions raise :class:`OverloadError`)
        while already-queued requests keep dispatching — the SIGTERM
        half-close. Idempotent; :meth:`close` still ends the worker."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def pending_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def fail_pending(self, error: BaseException,
                     outcome: str = "expired") -> int:
        """Give every still-queued request a typed terminal outcome NOW
        (the expired-drain path: remainders become 504s, not hangs).
        Returns how many requests were failed."""
        with self._cond:
            doomed = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            doomed_muts = list(self._mutations)
            self._mutations.clear()
            self._cond.notify_all()
        for req in doomed:
            if not req.event.is_set():
                req.fail(error, outcome=outcome)
        for mut in doomed_muts:
            if not mut.event.is_set():
                mut.fail(error)
        return len(doomed) + len(doomed_muts)

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting work, drain the queue, and join the worker.
        Already-queued requests are still dispatched; new submissions
        raise :class:`OverloadError`. Idempotent.

        Terminal-outcome guarantee: whatever the worker could not drain
        (join timeout, a worker that died mid-shutdown) is failed with a
        typed :class:`OverloadError` — a request accepted by ``submit``
        NEVER ends without an outcome (pinned by
        tests/test_serve.py::TestShutdownUnderLoad)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._supervisor.join(timeout)
        self.fail_pending(
            OverloadError("batcher shut down before this request could be "
                          "dispatched"),
            outcome="error",
        )

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker side -------------------------------------------------------

    def _supervise(self) -> None:
        """Run the worker thread; restart it if it ever dies unexpectedly.

        The worker survives dispatch failures by design (they are fanned
        to the batch's futures), so a dead worker means its own machinery
        failed (`_collect`, the recovery path itself). Before the
        supervisor, that was a silently hung server — every queued future
        stranded until timeout. Now it is a counted, logged restart; the
        queue is untouched, so queued requests get served by the
        replacement."""
        while True:
            self._worker_error = None
            worker = threading.Thread(
                target=self._worker_body, name="knn-serve-batcher",
                daemon=True,
            )
            worker.start()
            worker.join()
            with self._cond:
                if self._closed:
                    # Shutdown — a clean drain, or a death mid-shutdown
                    # (don't restart-loop forever; close() gives whatever
                    # is left a typed outcome either way).
                    return
            err = self._worker_error
            self.restarts += 1
            obs.counter_add(
                "knn_serve_worker_restarts_total",
                help="batcher worker threads restarted by the supervisor",
            )
            print(
                f"warning: serve batcher worker died "
                f"({type(err).__name__ if err else 'no exit status'}: {err}); "
                f"restarting (restart #{self.restarts})",
                file=sys.stderr,
            )
            time.sleep(0.05)  # don't spin hot on a persistently broken path

    def _worker_body(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 — handed to the supervisor
            self._worker_error = e

    def _collect(self) -> "list[_Request]":
        """Block until a batch closes; [] only at shutdown with an empty
        queue. Coalescing rule: from the arrival of the OLDEST queued
        request, wait up to ``max_wait_ms`` for more work, closing early
        at ``max_batch`` rows (or on shutdown/drain — a draining server
        dispatches immediately rather than holding the window open for
        work that can no longer arrive). Whole requests only — a request
        larger than ``max_batch`` dispatches alone, oversized."""
        with self._cond:
            while True:
                while (not self._queue and not self._closed
                       and not self._mutations):
                    self._cond.wait()
                if not self._queue:
                    if self._mutations and not self._closed:
                        # Pending writes, no reads: hand control back to
                        # _run so the mutation batch applies NOW instead
                        # of idling until a read arrives.
                        return None
                    return []
                # The span covers only the coalescing window, not the idle
                # block above — an idle server must not inflate queue
                # totals.
                with obs.span("serve.queue", waiting_rows=self._queued_rows):
                    deadline_ns = self._queue[0].enqueued_ns + int(
                        self.max_wait_ms * 1e6
                    )
                    while (not self._closed and not self._draining
                           and self._queued_rows < self.max_batch):
                        wait_s = (deadline_ns - time.monotonic_ns()) / 1e9
                        if wait_s <= 0:
                            break
                        self._cond.wait(wait_s)
                if self.admission is not None and len(self._queue) > 1:
                    # Priority-aware pickup: the batch fills highest
                    # priority first (stable — FIFO within a class), so
                    # a forming batch never strands `interactive` behind
                    # queued `bulk`. AFTER the coalescing window (whose
                    # deadline anchors to the oldest arrival regardless
                    # of class) and only with an admission policy: the
                    # flagless path keeps the deque untouched, FIFO.
                    self._queue = deque(sorted(
                        self._queue,
                        key=lambda r: (
                            self.admission.priority_of(r.request_class),
                            r.enqueued_ns)))
                batch, rows = [], 0
                while self._queue:
                    nxt = self._queue[0]
                    if batch and rows + nxt.rows > self.max_batch:
                        break
                    batch.append(self._queue.popleft())
                    rows += nxt.rows
                self._queued_rows -= rows
                if batch:
                    return batch
                # The queue was cleared under the window (fail_pending on
                # an expired drain): every request already has its typed
                # outcome — go back to waiting, this is NOT a shutdown
                # (returning [] here would read as one and make the
                # supervisor count a bogus worker death).

    def _run(self) -> None:
        # Dispatch failures are delivered to the batch's futures
        # (_Request._finish is itself exception-proof, so failing the
        # batch cannot re-raise); anything that escapes _collect or the
        # recovery path itself kills the worker — and the supervisor
        # restarts it, counted and logged, with the queue intact.
        while True:
            self._apply_mutations()
            batch = self._collect()
            if batch is None:
                continue  # mutations arrived while idle; apply them
            if not batch:
                return
            try:
                self._dispatch(batch)
            except BaseException as e:  # noqa: BLE001 — fanned per-future
                for req in batch:
                    if not req.event.is_set():
                        req.fail(e)

    def _apply_mutations(self) -> None:
        """Drain the mutation queue on the worker thread — between read
        dispatches, never inside one, which is the whole serialization
        contract. A failed apply (typed validation/conflict/overload)
        goes to THAT mutation's future; the worker survives anything."""
        if self.mutable is None:
            return
        with self._cond:
            if not self._mutations:
                return
            muts = list(self._mutations)
            self._mutations.clear()
        for mut in muts:
            try:
                if mut.op == "insert":
                    out = self.mutable.apply_insert(
                        mut.payload["rows"], mut.payload["values"],
                        mut.enqueued_ns,
                    )
                else:
                    out = self.mutable.apply_delete(
                        mut.payload["ids"], mut.enqueued_ns,
                        expect_version=mut.payload.get("expect_version"))
                # No version stamp here: the ENGINE stamps it under its
                # own lock, so the ack's ids and tag name one generation
                # (reading self._index_version after apply would race a
                # compaction swap).
                if self.workload is not None:
                    # Capture the ACKNOWLEDGED mutation stream (never
                    # sampled — replay needs it complete for
                    # mutation_seq alignment; obs/workload.py).
                    self.workload.note_mutation(
                        mut.op, mut.payload,
                        out.get("seq") if isinstance(out, dict) else None,
                        mut.enqueued_ns,
                    )
                mut.succeed(out)
            except BaseException as e:  # noqa: BLE001 — per-future
                if not mut.event.is_set():
                    mut.fail(e)

    # -- the degradation ladder --------------------------------------------

    def _rungs(self, model, mview=None):
        """The serving ladder for this batch's model snapshot:
        ``ivf`` (probed approximate retrieval over the model's IVF
        partition — present only when this batcher serves approximate AND
        the snapshot carries one), ``fast`` (the model's own configured
        retrieval — engine selection + device cache), ``xla`` (the tiled
        candidate scan, skipped when it IS the fast engine), ``oracle``
        (pure NumPy — cannot fail for device reasons). The exact rungs
        retrieve under the same (distance, train-index) contract, so
        votes are bit-identical down the EXACT ladder; the ivf rung
        trades recall for sub-linear cost and is held to its floor by the
        shadow scorer + probe policy (docs/INDEXES.md).
        """
        train = model.train_
        k, metric = model.k, model.metric
        if isinstance(model, KNNClassifier):
            engine = model._retrieval_engine()
        else:
            engine = model.engine
        sharded = getattr(model, "shard_plan_", None) is not None

        def fast(feats, prefetched=None, merge_tail=None):
            if sharded:
                # Mesh-sharded model (knn_tpu/shard/): the fast rung IS
                # the fanned-out dispatch — per-shard device retrieval,
                # cross-shard lexicographic merge, bit-identical to the
                # single-device rung. The stager's whole-train prefetch
                # does not apply (each shard uploads its own slice); the
                # xla rung below stays single-device, so a shard-layer
                # failure degrades to the unsharded ladder, typed.
                return model.sharded_kneighbors(np.asarray(feats))
            if self._stager is not None or merge_tail is not None:
                # Bucketed serving: dispatch DEFERRED (device work +
                # result copies in flight when _kneighbors_arrays
                # returns), start the NEXT batch's host→device upload in
                # the gap, then resolve — batch N+1's transfer overlaps
                # batch N's compute. Identical arrays to
                # model.kneighbors: same retrieval core, same engine
                # selection, same device cache (submit already validated
                # the feature width the Dataset path re-checks). ONE
                # prefetch per dispatch: a post-OOM chunked dispatch
                # calls this rung once per chunk, and re-staging the
                # same queue head N times would be pure wasted host
                # copies + uploads on the already-degraded path.
                # ``merge_tail`` (the device-resident delta merge) rides
                # the same deferred dispatch — base+delta in one sync.
                resolve = _kneighbors_arrays(
                    train.features, feats, k, metric=metric, engine=engine,
                    cache=train.device_cache, deferred=True,
                    prefetched_queries=prefetched, merge_tail=merge_tail,
                )
                if (self._stager is not None
                        and not self._prefetched_this_dispatch):
                    self._prefetched_this_dispatch = True
                    self._stager.prefetch(self)
                return resolve()
            return model.kneighbors(
                Dataset(feats, np.zeros(feats.shape[0], np.int32))
            )

        def xla(feats, prefetched=None, merge_tail=None):
            return _kneighbors_arrays(
                train.features, feats, k, metric=metric, engine="xla",
                cache=train.device_cache, prefetched_queries=prefetched,
                merge_tail=merge_tail,
            )

        def oracle(feats, prefetched=None):
            from knn_tpu.backends.oracle import oracle_kneighbors

            return oracle_kneighbors(train.features, np.asarray(feats), k,
                                     metric)

        rungs = []
        if self.ivf is not None and getattr(model, "ivf_", None) is not None:
            rungs.append((
                "ivf",
                lambda feats, prefetched=None:
                    self.ivf.kneighbors(model, np.asarray(feats)),
            ))
        rungs.append(("fast", fast))
        if engine != "xla":  # "auto" may resolve to stripe on real TPU
            rungs.append(("xla", xla))
        rungs.append(("oracle", oracle))
        if mview is not None and not mview.empty:
            # Mutable serving with live mutations: every rung's base-only
            # answer is folded with the delta tier + tombstones under the
            # shared (distance, index) order (knn_tpu/mutable/state.py).
            # An EMPTY view never reaches here — the ladder (and its
            # bytes) is exactly the immutable one, the pinned bit-identity
            # contract.
            rungs = [(name, self._merged_rung(name, fn, model, mview))
                     for name, fn in rungs]
        return rungs

    def _merged_rung(self, name: str, fn, model, mview):
        """Wrap one rung closure with the delta/tombstone merge.

        Three realizations of the ONE merge contract, picked per rung:

        - **ivf** — :meth:`IVFServing.kneighbors` owns its merge: the
          delta tail fuses into the segment scorer's device dispatch
          when the view carries a device-resident tail, else the host
          merge with the probed search as the widening family;
        - **fast/xla with a device tail** (and no base tombstones, the
          euclidean XLA engine): the jitted delta merge chains onto the
          retrieval's device outputs (``merge_tail``) — base+delta in
          ONE host sync — and the host re-rank restores the merge's
          bit-exact distances (``mutable/device_tail.rerank_merged``);
        - **everything else** (oracle, stripe, other metrics,
          tombstoned-base views, host-only tails): the host merge,
          with k-coverage widening through the oracle — unchanged
          PR-10 behavior.
        """
        from knn_tpu.mutable import state as mstate

        k = model.k
        if name == "ivf":
            def merged_ivf(feats, prefetched=None):
                return self.ivf.kneighbors(model, np.asarray(feats),
                                           view=mview)

            return merged_ivf
        tview = getattr(mview, "device", None)
        if (name == "fast"
                and getattr(model, "shard_plan_", None) is not None):
            if (tview is not None and mview.tomb_base.size == 0
                    and model.metric in (None, "euclidean")):
                # Sharded fused merge: each shard carries its slice of
                # the device tail in its own dispatch (the sharded
                # dispatch forces the XLA engine — merge_tail is an
                # XLA-path hook), survivors re-rank through the same
                # host exact pass as the single-device fused path.
                def merged_shard(feats, prefetched=None):
                    return model.sharded_kneighbors(
                        np.asarray(feats, np.float32), view=mview)

                return merged_shard
            # Tombstoned-base / non-euclidean views: fall through to the
            # host merge below — ``fn`` is already the sharded base
            # dispatch, and the host merge is topology-blind.
        if (tview is not None and name in ("fast", "xla")
                and mview.tomb_base.size == 0
                and model.metric in (None, "euclidean")
                and (name == "xla"
                     or acct.resolved_retrieval_engine(model) == "xla")):
            from knn_tpu.mutable import device_tail as dtail

            tail_fn = dtail.make_merge_tail(tview, k)

            def merged_dev(feats, prefetched=None):
                d, i = fn(feats, prefetched, merge_tail=tail_fn)
                return dtail.rerank_merged(
                    mview, model.train_.features,
                    np.asarray(feats, np.float32), i, k, model.metric,
                    base_d=d)

            return merged_dev

        def wide(feats, k_wide):
            from knn_tpu.backends.oracle import oracle_kneighbors

            return oracle_kneighbors(model.train_.features, feats,
                                     k_wide, model.metric)

        def merged(feats, prefetched=None):
            d, i = fn(feats, prefetched)
            return mstate.merge_candidates(mview, feats, d, i, k,
                                           model.metric, wide)

        return merged

    def _call_rung(self, fn, feats, prefetched=None):
        """Dispatch ``feats`` through one rung, chunked to the CURRENT
        ``max_batch`` (which OOM recovery may have shrunk below this
        batch's row count — each chunk re-pads to ITS bucket through the
        one query_padded_rows definition, so a halved cap re-clamps onto
        already-compiled ladder shapes instead of dispatching a
        never-compiled one). Row independence makes the chunked result
        identical to the one-shot dispatch."""
        cap = self.max_batch
        if feats.shape[0] <= cap:
            return fn(feats, prefetched)
        dists, idx = [], []
        for s in range(0, feats.shape[0], cap):
            d, i = fn(feats[s:s + cap], None)
            dists.append(d)
            idx.append(i)
        return np.concatenate(dists), np.concatenate(idx)

    def _expire_now(self, live: "list[_Request]") -> "list[_Request]":
        """Deadline re-check between ladder rungs: a request that expired
        while a higher rung was failing gets its 504 NOW — never a slow
        success from a lower rung."""
        now_ns = time.monotonic_ns()
        keep = []
        for req in live:
            if req.deadline_ns is not None and now_ns > req.deadline_ns:
                instrument.record_serve_deadline_expired()
                if req.trace is not None:
                    req.trace.annotate(expired_where="mid-fallback")
                req.fail(
                    DeadlineExceededError(
                        f"{req.kind} request deadline expired after "
                        f"{(now_ns - req.enqueued_ns) / 1e6:.1f} ms while "
                        f"degradation was in progress"
                    ),
                    outcome="expired",
                )
            else:
                keep.append(req)
        return keep

    def _warn(self, msg: str) -> None:
        print(f"warning: {msg}", file=sys.stderr)

    def _padded_rows(self, model, rung: str, rows: int) -> "Optional[int]":
        """Compiled-shape rows for one rung dispatch — what the device
        really sweeps after the engine's shape quantization. None when no
        consumer (accounting/capacity/obs) wants it, so the disabled path
        pays one predicate."""
        if (self.accounting is None and self.capacity is None
                and not obs.enabled()):
            return None
        try:
            return acct.dispatch_padded_rows(model, rung, rows,
                                             self.max_batch)
        except Exception:  # noqa: BLE001 — observability must never fail
            return None    # a dispatch (e.g. an exotic engine opt)

    def _account_attempt(self, model, live, traced, rung: str,
                         t_rung: float, feats, *, error=None, out=None):
        """Shared per-attempt bookkeeping for :meth:`_retrieve`: the
        traced ``attempt`` records and (when accounting is on) the cost
        attribution of this attempt's measured wall across the requests
        live for it. ``out`` (the result arrays) marks the answering
        attempt — bytes count there only. Returns the attempt's
        padded-rows (None when nothing consumes it)."""
        attempt_ms = (time.monotonic() - t_rung) * 1e3
        ok = error is None
        for t in traced:
            if ok:
                t.attempt(rung, True, attempt_ms)
            else:
                t.attempt(rung, False, attempt_ms,
                          error=type(error).__name__)
        pad = self._padded_rows(model, rung, feats.shape[0])
        if self.accounting is not None:
            self.accounting.attribute(
                live, attempt_ms, rung=rung, rows=feats.shape[0],
                padded_rows=pad or feats.shape[0],
                nbytes=(feats.nbytes + out[0].nbytes + out[1].nbytes
                        if ok else 0),
                ok=ok,
            )
        return pad

    def _retrieve(self, model, live: "list[_Request]", mview=None,
                  prefetch=None):
        """Candidate retrieval for the coalesced batch, through the
        breaker + ladder. Returns ``(live, dists, idx, rung,
        padded_rows)`` — ``live`` may have shrunk (mid-fallback deadline
        expiries, already failed typed); ``padded_rows`` is the answering
        dispatch's compiled-shape row count (None when nothing consumes
        it). ``prefetch`` is the stager's ``(host_rows, device_block)``
        double-buffered upload for exactly this batch — consumed while
        ``live`` is unshrunk (the staged content stops matching once a
        deadline expiry rebuilds the feature block). Raises the last
        typed error when every rung fails.

        Cost attribution happens HERE, per rung attempt: each attempt's
        measured wall is split across the requests live for it (a failed
        fast dispatch is device time the surviving requests paid; a
        request that expired mid-fallback is attributed only the attempts
        it rode — tests/test_accounting.py)."""
        rungs = self._rungs(model, mview)
        decision = self.breaker.decide()
        start = 0
        if decision == "open":
            # Short-circuit: the fast rung is known-broken; go straight to
            # the rung that last answered instead of paying a doomed
            # dispatch + ladder walk per batch.
            start = min(max(1, self._degraded_rung), len(rungs) - 1)
        # Request-context weave: `traced` is updated IN PLACE when deadline
        # expiries shrink `live`, so the activation below (the channel the
        # breaker's transition events arrive through) always reflects the
        # requests still being served. Empty when tracing is off.
        traced = [r.trace for r in live if r.trace is not None]
        for t in traced:
            t.annotate(breaker=decision)
            if decision == "open":
                t.event("breaker.short_circuit", to_rung=rungs[start][0])
        last_err: Optional[Exception] = None
        pos = start
        feats = None  # rebuilt only when `live` shrinks, not per attempt
        # The double-buffered upload (one per batch, staged by the
        # PREVIOUS dispatch's overlap window): host rows + resident
        # device block. Valid for every attempt until `live` shrinks —
        # the device rungs share one padded shape, so a fast→xla
        # fallback still rides the same upload.
        dev_block = None
        if prefetch is not None:
            feats, dev_block = prefetch
        with reqtrace.activate(traced):
            while pos < len(rungs):
                if last_err is not None:
                    kept = self._expire_now(live)
                    if len(kept) != len(live):
                        feats = None
                        dev_block = None
                        traced[:] = [r.trace for r in kept
                                     if r.trace is not None]
                    live = kept
                    if not live:
                        return live, None, None, None, None
                name, fn = rungs[pos]
                if feats is None:
                    feats = (
                        live[0].features if len(live) == 1
                        else np.concatenate([r.features for r in live])
                    )
                t_rung = time.monotonic()
                try:
                    if pos == 0:
                        if decision == "probe":
                            with obs.span("breaker.probe",
                                          breaker=self.breaker.name):
                                faults.fault_point("serve.dispatch")
                                out = self._call_rung(fn, feats, dev_block)
                        else:
                            faults.fault_point("serve.dispatch")
                            out = self._call_rung(fn, feats, dev_block)
                        self.breaker.record_success()
                    else:
                        out = self._call_rung(fn, feats, dev_block)
                        self._degraded_rung = pos
                    self._last_rung = name
                    pad = self._account_attempt(model, live, traced, name,
                                                t_rung, feats, out=out)
                    return live, out[0], out[1], name, pad
                except DeviceError as e:
                    self._account_attempt(model, live, traced, name,
                                          t_rung, feats, error=e)
                    if e.oom and self.max_batch > 1:
                        prev, self.max_batch = self.max_batch, max(
                            1, self.max_batch // 2)
                        self._warn(
                            f"serving dispatch OOM on rung '{name}'; halving "
                            f"max_batch {prev} -> {self.max_batch}"
                        )
                        obs.counter_add(
                            "knn_serve_fallback_total",
                            help="serving-ladder moves (rung -> fallback "
                                 "rung; from==to is an in-place max_batch "
                                 "halving)",
                            from_rung=name, to=name, reason="oom_halve_batch",
                        )
                        reqtrace.emit("fallback", from_rung=name, to=name,
                                      reason="oom_halve_batch",
                                      max_batch=self.max_batch)
                        last_err = e
                        continue  # same rung, smaller chunks
                    last_err = e
                except (CompileError, CollectiveError, OSError) as e:
                    self._account_attempt(model, live, traced, name,
                                          t_rung, feats, error=e)
                    last_err = e
                except ResilienceError as e:
                    # The ivf rung degrades on the REST of the taxonomy
                    # too (a DataError from an index/model desync):
                    # approximation is traded away for bit-exact
                    # retrieval, never a failed batch. On exact rungs
                    # these errors stay the request's own typed outcome.
                    if name != "ivf":
                        raise
                    self._account_attempt(model, live, traced, name,
                                          t_rung, feats, error=e)
                    last_err = e
                if pos == 0:
                    self.breaker.record_failure()
                nxt = rungs[pos + 1][0] if pos + 1 < len(rungs) else None
                if nxt is not None:
                    self._warn(
                        f"serving rung '{name}' failed "
                        f"({type(last_err).__name__}: {last_err}); "
                        f"falling back to '{nxt}'"
                    )
                    obs.counter_add(
                        "knn_serve_fallback_total",
                        help="serving-ladder moves (rung -> fallback rung; "
                             "from==to is an in-place max_batch halving)",
                        from_rung=name, to=nxt,
                        reason=type(last_err).__name__,
                    )
                    reqtrace.emit("fallback", from_rung=name, to=nxt,
                                  reason=type(last_err).__name__)
                pos += 1
        assert last_err is not None
        raise last_err

    # -- dispatch ----------------------------------------------------------

    def _admit_topup(self, batch: "list[_Request]") -> None:
        """Continuous batching: top the closed batch up with requests
        that arrived AFTER the coalescing window closed but before this
        dispatch starts, up to the batch's current bucket boundary —
        those rows ride for free (the compiled shape the batch pads to
        does not change), so waiting a whole fresh window + dispatch
        would be pure added latency. The spec is the what-if simulator's
        bucket policy model (obs/whatif.py): a dispatch of ``rows`` pays
        for ``query_padded_rows(rows)`` compiled rows either way.
        Bucketed batchers only: without a ladder the free-rows premise
        belongs to the legacy pad quantum, not the policy the operator
        chose — and the embedded default's dispatch composition stays
        byte-identical to pre-ladder behavior, as documented."""
        if self.buckets is None:
            return
        rows = sum(r.rows for r in batch)
        boundary = min(query_padded_rows(rows), self.max_batch)
        if rows >= boundary:
            return
        with self._cond:
            if self.admission is not None and len(self._queue) > 1:
                # Same priority-aware pickup as _collect: free top-up
                # rows go to the highest-priority waiters first.
                self._queue = deque(sorted(
                    self._queue,
                    key=lambda r: (
                        self.admission.priority_of(r.request_class),
                        r.enqueued_ns)))
            while self._queue and rows + self._queue[0].rows <= boundary:
                nxt = self._queue.popleft()
                self._queued_rows -= nxt.rows
                batch.append(nxt)
                rows += nxt.rows
                instrument.record_serve_topup(nxt.rows)

    def _finish_served(self, req: "_Request", d, i, model, version, mview,
                       merged: bool, rung: str,
                       cache_hit: bool = False) -> None:
        """Complete ONE request from its retrieval slice — the tail every
        served request shares, whether its candidates came from this
        batch's dispatch or the result cache: meta tags, the per-kind
        value (vote/aggregate on host), future signal, capacity/quality/
        drift taps."""
        req.meta["index_version"] = version
        req.meta["rung"] = rung
        if cache_hit:
            req.meta["cache"] = "hit"
        if mview is not None:
            # The read's sequence point: which acknowledged mutations
            # this answer reflects (the anchor the mutable soak's oracle
            # replay verifies against).
            req.meta["mutation_seq"] = mview.seq
        if req.trace is not None:
            req.trace.annotate(index_version=version, rung=rung)
            if cache_hit:
                req.trace.annotate(cache="hit")
        if req.kind == "kneighbors":
            # A cache hit's arrays are the FROZEN shared copies; hand
            # the caller writable private ones so hit and miss behave
            # identically for in-process consumers that mutate results.
            value = (d.copy(), i.copy()) if cache_hit else (d, i)
        elif merged:
            # Candidate ids span base+delta: labels/targets must be
            # gathered across BOTH spaces (a clamped base lookup would
            # vote with the wrong label).
            from knn_tpu.mutable.state import predict_from_view

            value = predict_from_view(model, mview, d, i)
        elif isinstance(model, KNNClassifier):
            value = model.predict_from_candidates(d, i)
        else:
            value = model._predict_from((d, i))
        req.succeed(value)
        if self.capacity is not None:
            self.capacity.note_served(
                req.rows,
                (time.monotonic_ns() - req.enqueued_ns) / 1e6,
            )
        # Quality tap, AFTER the future is signaled: one RNG draw + an
        # O(1) append per layer, shed when full — the response is
        # already on its way to the client.
        if self.quality is not None:
            self.quality.offer(
                features=req.features, kind=req.kind, dists=d, idx=i,
                preds=(value if req.kind == "predict" else None),
                rung=rung, model=model, version=version, mview=mview,
            )
        if self.drift is not None:
            self.drift.offer(req.features)

    def _dispatch(self, batch: "list[_Request]") -> None:
        # Continuous-batching top-up BEFORE the snapshot: a topped-up
        # request was submitted after every mutation this worker has
        # acknowledged so far, so the snapshot taken below (which
        # reflects all of them) preserves read-your-writes — the other
        # order could serve a fresh request at a sequence point older
        # than state it already observed.
        self._admit_topup(batch)
        with self._cond:
            # One snapshot per batch: swap_model can never split a batch
            # across two indexes — and the mutable view snapshots in the
            # SAME critical section compaction's swap+rebase runs in, so
            # (model, version, view) are always one consistent triple.
            model = self._model
            version = self._index_version
            mview = (self.mutable.snapshot()
                     if self.mutable is not None else None)
        now_ns = time.monotonic_ns()
        live: "list[_Request]" = []
        for req in batch:
            instrument.record_serve_queue_wait(
                (now_ns - req.enqueued_ns) / 1e6, req.kind
            )
            if req.trace is not None:
                req.trace.phase_end("queue_wait")
            if req.deadline_ns is not None and now_ns > req.deadline_ns:
                instrument.record_serve_deadline_expired()
                if req.trace is not None:
                    req.trace.annotate(expired_where="queue")
                req.fail(
                    DeadlineExceededError(
                        f"{req.kind} request expired in queue after "
                        f"{(now_ns - req.enqueued_ns) / 1e6:.1f} ms"
                    ),
                    outcome="expired",
                )
                continue
            live.append(req)
        if not live:
            return
        merged_view = mview is not None and not mview.empty
        ivf_active = (self.ivf is not None
                      and getattr(model, "ivf_", None) is not None)
        miss_keys: "Optional[dict]" = None
        if self.cache is not None:
            # Exact-match result cache (knn_tpu/serve/cache.py): keyed on
            # the snapshot's (version, sequence point) plus the live ivf
            # operating point, so a hit is bit-identical to what a fresh
            # dispatch under this snapshot would return. Hits complete
            # HERE — no dispatch, no device time, no occupancy entry.
            seq = mview.seq if mview is not None else None
            nprobe = self.ivf.policy.current() if ivf_active else None
            misses: "list[_Request]" = []
            miss_keys = {}
            for req in live:
                key = self.cache.key(version, seq, nprobe, req.features)
                ent = self.cache.get(key)
                if ent is not None:
                    hit_d, hit_i, hit_rung = ent
                    self._finish_served(req, hit_d, hit_i, model, version,
                                        mview, merged_view, hit_rung,
                                        cache_hit=True)
                else:
                    miss_keys[id(req)] = key
                    misses.append(req)
            live = misses
            if not live:
                return
        rows = sum(r.rows for r in live)
        for req in live:
            if req.trace is not None:
                req.trace.phase_start("dispatch")
                req.trace.annotate(batch_requests=len(live), batch_rows=rows)
        # The double-buffered upload staged during the PREVIOUS dispatch:
        # consumed only when it was built from exactly this request list
        # (identity-matched — cache hits, expiries, or new arrivals
        # between staging and now silently drop it).
        prefetch = (self._stager.take(live)
                    if self._stager is not None else None)
        self._prefetched_this_dispatch = False
        t0 = time.monotonic()
        try:
            with obs.span("serve.dispatch", requests=len(live),
                          rows=rows) as dispatch_span:
                live, dists, idx, rung, padded = self._retrieve(
                    model, live, mview, prefetch=prefetch)
                if not live:
                    # Every request expired mid-fallback — but the failed
                    # rung attempts were real worker busy time the duty
                    # cycle must still see (`rows` is the batch as
                    # dispatched; an all-expiring fault storm at duty ~1.0
                    # is the saturated-and-broken picture).
                    if self.capacity is not None:
                        self.capacity.note_dispatch(
                            (time.monotonic() - t0) * 1e3, rows, rows,
                            self.max_batch, compiled=False,
                        )
                    return
                if padded is not None and hasattr(dispatch_span, "attrs"):
                    # The compiled-shape rows the device really swept —
                    # padding waste visible in the Perfetto timeline, not
                    # just the knn_cost_* counters (a _NullSpan while obs
                    # is off has no attrs and records nothing).
                    dispatch_span.attrs["padded_rows"] = padded
                if self.corrupt_serving:
                    # Test-only (see __init__): every served neighbor is
                    # off by one train row while distances stay plausible.
                    idx = (idx + 1) % model.train_.num_instances
                primary = "ivf" if ivf_active else "fast"
                cacheable = (
                    self.cache is not None and miss_keys is not None
                    and rung == primary and not self.corrupt_serving
                )
                off = 0
                for req in live:
                    d = dists[off:off + req.rows]
                    i = idx[off:off + req.rows]
                    off += req.rows
                    if cacheable:
                        key = miss_keys.get(id(req))
                        if key is not None:
                            # Copies, frozen: the cached arrays outlive
                            # this batch's buffers and are handed to
                            # every later hit — nobody may mutate them.
                            cd, ci = d.copy(), i.copy()
                            cd.flags.writeable = False
                            ci.flags.writeable = False
                            self.cache.put(key, cd, ci, rung)
                    self._finish_served(req, d, i, model, version, mview,
                                        merged_view, rung)
            batch_ms = (time.monotonic() - t0) * 1e3
            served_rows = sum(r.rows for r in live)
            instrument.record_serve_batch(
                len(live), served_rows, batch_ms, padded_rows=padded,
            )
            if self.capacity is not None:
                # Host rungs (ivf/oracle) have no compiled shape:
                # occupancy keeps its rows/max_batch coalescing meaning
                # there instead of a vacuous 1.0 from padded == rows.
                self.capacity.note_dispatch(
                    batch_ms, served_rows, padded or served_rows,
                    self.max_batch, compiled=rung in ("fast", "xla"),
                )
        except Exception as e:  # noqa: BLE001 — delivered per-future
            obs.counter_add(
                "knn_serve_errors_total",
                help="micro-batch dispatches that raised (typed error "
                     "delivered to every coalesced request)",
                type=type(e).__name__,
            )
            # A failed dispatch is still worker busy time the duty cycle
            # must see — an all-failing replica at 100% duty is exactly
            # the saturated-and-broken picture the operator needs.
            if self.capacity is not None:
                self.capacity.note_dispatch(
                    (time.monotonic() - t0) * 1e3,
                    sum(r.rows for r in live),
                    sum(r.rows for r in live), self.max_batch,
                    compiled=False,
                )
            for req in live:
                if not req.event.is_set():
                    req.fail(e)
