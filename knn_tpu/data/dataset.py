"""Dense dataset container.

Replaces the reference's pointer-per-scalar AoS object graph
(libarff/arff_data.h:27, arff_instance.h:18, arff_value.h:45) with a flat
SoA representation that maps directly onto device arrays: ``float32 [N, D-1]``
features + ``int32 [N]`` labels. The class is the *last* declared attribute,
read as float and cast to int, exactly as the reference does
(main.cpp:57,66,93).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Attribute:
    """Attribute metadata (name + type), the analogue of libarff's ArffAttr
    (arff_attr.h:17-49). ``nominal_values`` is set only for ``{a,b,c}`` attrs.

    ``string_values`` is the interned-value table for STRING/DATE attributes:
    data cells of these types are stored in the dense matrix as float32 codes
    indexing this first-seen-ordered table (the reference keeps them as
    heap strings per cell, arff_value.cpp:33-48, and only fails when its KNN
    kernel tries to read one as float, arff_value.cpp:121 — so files with
    string columns LOAD there and must load here; the numeric-only
    requirement is deferred to predict time, Dataset.validate_for_knn)."""

    name: str
    type: str  # "numeric" | "string" | "date" | "nominal"
    nominal_values: Optional[list] = None
    string_values: Optional[list] = None


@dataclasses.dataclass
class Dataset:
    """A parsed ARFF dataset in dense form.

    ``features``: float32 [N, D-1] — all attributes except the last.
    ``labels``:   int32 [N] — the last attribute cast to int.
    ``num_classes``: max(label)+1, the reference's lazily-cached definition
    (libarff/arff_data.cpp:41-58).
    ``raw_targets``: float32 [N] — the last attribute *before* the int cast,
    kept for the regression extension (the reference pipeline only ever casts,
    main.cpp:57). Optional; falls back to ``labels`` via :attr:`targets`.
    Missing values (``?``) are stored as NaN in ``features``.
    """

    features: np.ndarray
    labels: np.ndarray
    relation: str = ""
    attributes: Sequence[Attribute] = dataclasses.field(default_factory=list)
    raw_targets: Optional[np.ndarray] = None
    # Keyed device-side layouts of features/labels (e.g. the stripe kernel's
    # transposed train matrix), populated lazily by the execution backends so
    # repeat predict/kneighbors calls skip the host pad+transpose+upload.
    # Staleness is ENFORCED (VERDICT r3 #8): the array attributes are
    # read-only views — in-place writes raise — and REBINDING an array
    # attribute (``ds.features = new``) clears the cache automatically, so
    # a cached device layout can never silently outlive the host data it
    # was built from. (A caller mutating the original array it passed to
    # the constructor through its own pre-existing reference is outside
    # this guarantee — the views freeze only this object's handles.)
    device_cache: dict = dataclasses.field(
        default_factory=dict, repr=False, compare=False
    )

    _ARRAY_FIELDS = frozenset({"features", "labels", "raw_targets"})

    @staticmethod
    def _frozen_view(value):
        """Read-only view of an ndarray (the caller's own flags are left
        alone); non-arrays and already-frozen arrays pass through."""
        if isinstance(value, np.ndarray) and value.flags.writeable:
            value = value.view()
            value.flags.writeable = False
        return value

    def __setattr__(self, name, value):
        if name in self._ARRAY_FIELDS:
            if self.__dict__.get("_init_done"):
                # Post-init rebind: the sanctioned mutation path. Coerce and
                # validate like the constructor (a rebind must preserve N —
                # changing the instance count means a new Dataset), and
                # clear cached device layouts UNCONDITIONALLY: any rebind,
                # whatever the value's type, makes them stale.
                value = self._coerce(name, value)
                self._check_shape(name, value)
                self.device_cache.clear()
            value = self._frozen_view(value)
        object.__setattr__(self, name, value)

    @staticmethod
    def _coerce(name: str, value):
        if name == "raw_targets" and value is None:
            return None
        dtype = np.int32 if name == "labels" else np.float32
        return np.ascontiguousarray(value, dtype=dtype)

    def _check_shape(self, name: str, value) -> None:
        if name == "features":
            if value.ndim != 2:
                raise ValueError(f"features must be [N, D-1], got {value.shape}")
            want_n = value.shape[0]
        else:
            want_n = self.features.shape[0]
        for field, arr in (
            ("features", value if name == "features" else self.__dict__.get("features")),
            ("labels", value if name == "labels" else self.__dict__.get("labels")),
            ("raw_targets", value if name == "raw_targets" else self.__dict__.get("raw_targets")),
        ):
            if field == "features" or arr is None or not isinstance(arr, np.ndarray):
                continue
            if arr.shape != (want_n,):
                raise ValueError(
                    f"{field} shape {arr.shape} does not match N={want_n}"
                )

    def __post_init__(self):
        self.features = self._coerce("features", self.features)
        self.labels = self._coerce("labels", self.labels)
        self.raw_targets = self._coerce("raw_targets", self.raw_targets)
        self._check_shape("features", self.features)
        if self.device_cache:
            # A populated cache at construction means it was copied from
            # another instance (dataclasses.replace passes the same dict),
            # whose layouts may describe DIFFERENT arrays: start fresh.
            self.device_cache = {}
        object.__setattr__(self, "_init_done", True)

    def __getstate__(self):
        # Pickle carries the DATA, never the device cache: cached layouts
        # are padded/transposed duplicates (~9x bloat on a narrow train
        # set), and unpickled "device" arrays would silently live on
        # whatever backend the loading process has, re-uploading per call.
        state = dict(self.__dict__)
        state["device_cache"] = {}
        return state

    def __setstate__(self, state):
        state = dict(state)
        state["device_cache"] = {}
        for name in self._ARRAY_FIELDS:
            # numpy pickling does not preserve writeable=False: re-freeze
            # so the staleness contract survives a round trip.
            state[name] = self._frozen_view(state.get(name))
        self.__dict__.update(state)

    @property
    def targets(self) -> np.ndarray:
        """float32 regression targets: the uncast class column when the parser
        kept it, else the int labels."""
        if self.raw_targets is not None:
            return self.raw_targets
        return self.labels.astype(np.float32)

    @property
    def num_instances(self) -> int:
        return self.features.shape[0]

    @property
    def num_features(self) -> int:
        return self.features.shape[1]

    @property
    def num_attributes(self) -> int:
        """Declared attribute count including the class column."""
        return self.features.shape[1] + 1

    @property
    def num_classes(self) -> int:
        """max(label) + 1 over *this* dataset — the reference computes this per
        ArffData instance (arff_data.cpp:41-58); the KNN vote uses the train
        set's value and the confusion matrix the test set's."""
        if self.labels.size == 0:
            return 0
        return int(self.labels.max()) + 1

    def validate_for_knn(self, k: int, other: Optional["Dataset"] = None) -> None:
        """Checks the reference leaves as UB (SURVEY.md §3.5.5), plus the
        deferred numeric-only requirement: STRING/DATE columns parse into
        interned codes at load time (matching the reference parser, which
        accepts them, arff_parser.cpp:145-147), but a distance over interned
        codes is meaningless, so *feature* columns of those types are
        rejected here — where the reference instead aborts mid-KNN
        (arff_value.cpp:121). A string-typed *class* column is allowed: the
        interned codes are well-defined class ids (a framework extension;
        the reference aborts on the label cast, main.cpp:57)."""
        for ds in (self, other) if other is not None else (self,):
            for a in list(ds.attributes)[: ds.num_features]:
                if a.type in ("string", "date"):
                    raise ValueError(
                        f"attribute '{a.name}' of type {a.type} is not "
                        f"numeric; KNN distances need numeric feature "
                        f"columns (string/date columns load as interned "
                        f"codes but cannot be compared)"
                    )
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if k > self.num_instances:
            raise ValueError(
                f"k={k} exceeds the number of train instances ({self.num_instances})"
            )
        if (self.labels < 0).any():
            raise ValueError("labels must be non-negative integers")
        if other is not None and other.num_features != self.num_features:
            raise ValueError(
                f"train has {self.num_features} features but test has {other.num_features}"
            )
