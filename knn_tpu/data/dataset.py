"""Dense dataset container.

Replaces the reference's pointer-per-scalar AoS object graph
(libarff/arff_data.h:27, arff_instance.h:18, arff_value.h:45) with a flat
SoA representation that maps directly onto device arrays: ``float32 [N, D-1]``
features + ``int32 [N]`` labels. The class is the *last* declared attribute,
read as float and cast to int, exactly as the reference does
(main.cpp:57,66,93).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Attribute:
    """Attribute metadata (name + type), the analogue of libarff's ArffAttr
    (arff_attr.h:17-49). ``nominal_values`` is set only for ``{a,b,c}`` attrs.

    ``string_values`` is the interned-value table for STRING/DATE attributes:
    data cells of these types are stored in the dense matrix as float32 codes
    indexing this first-seen-ordered table (the reference keeps them as
    heap strings per cell, arff_value.cpp:33-48, and only fails when its KNN
    kernel tries to read one as float, arff_value.cpp:121 — so files with
    string columns LOAD there and must load here; the numeric-only
    requirement is deferred to predict time, Dataset.validate_for_knn)."""

    name: str
    type: str  # "numeric" | "string" | "date" | "nominal"
    nominal_values: Optional[list] = None
    string_values: Optional[list] = None


@dataclasses.dataclass
class Dataset:
    """A parsed ARFF dataset in dense form.

    ``features``: float32 [N, D-1] — all attributes except the last.
    ``labels``:   int32 [N] — the last attribute cast to int.
    ``num_classes``: max(label)+1, the reference's lazily-cached definition
    (libarff/arff_data.cpp:41-58).
    ``raw_targets``: float32 [N] — the last attribute *before* the int cast,
    kept for the regression extension (the reference pipeline only ever casts,
    main.cpp:57). Optional; falls back to ``labels`` via :attr:`targets`.
    Missing values (``?``) are stored as NaN in ``features``.
    """

    features: np.ndarray
    labels: np.ndarray
    relation: str = ""
    attributes: Sequence[Attribute] = dataclasses.field(default_factory=list)
    raw_targets: Optional[np.ndarray] = None
    # Keyed device-side layouts of features/labels (e.g. the stripe kernel's
    # transposed train matrix), populated lazily by the execution backends so
    # repeat predict/kneighbors calls skip the host pad+transpose+upload.
    # Staleness is ENFORCED (VERDICT r3 #8): the array attributes are
    # read-only views — in-place writes raise — and REBINDING an array
    # attribute (``ds.features = new``) clears the cache automatically, so
    # a cached device layout can never silently outlive the host data it
    # was built from. (A caller mutating the original array it passed to
    # the constructor through its own pre-existing reference is outside
    # this guarantee — the views freeze only this object's handles.)
    device_cache: dict = dataclasses.field(
        default_factory=dict, repr=False, compare=False
    )

    _ARRAY_FIELDS = frozenset({"features", "labels", "raw_targets"})

    def __setattr__(self, name, value):
        if name in self._ARRAY_FIELDS and isinstance(value, np.ndarray):
            if value.flags.writeable:
                value = value.view()  # leave the caller's own flags alone
                value.flags.writeable = False
            cache = self.__dict__.get("device_cache")
            if cache:  # rebinding after init: cached layouts are now stale
                cache.clear()
        object.__setattr__(self, name, value)

    def __post_init__(self):
        self.features = np.ascontiguousarray(self.features, dtype=np.float32)
        self.labels = np.ascontiguousarray(self.labels, dtype=np.int32)
        if self.features.ndim != 2:
            raise ValueError(f"features must be [N, D-1], got {self.features.shape}")
        if self.labels.shape != (self.features.shape[0],):
            raise ValueError(
                f"labels shape {self.labels.shape} does not match N={self.features.shape[0]}"
            )
        if self.raw_targets is not None:
            self.raw_targets = np.ascontiguousarray(
                self.raw_targets, dtype=np.float32
            )
            if self.raw_targets.shape != (self.features.shape[0],):
                raise ValueError(
                    f"raw_targets shape {self.raw_targets.shape} does not match "
                    f"N={self.features.shape[0]}"
                )

    @property
    def targets(self) -> np.ndarray:
        """float32 regression targets: the uncast class column when the parser
        kept it, else the int labels."""
        if self.raw_targets is not None:
            return self.raw_targets
        return self.labels.astype(np.float32)

    @property
    def num_instances(self) -> int:
        return self.features.shape[0]

    @property
    def num_features(self) -> int:
        return self.features.shape[1]

    @property
    def num_attributes(self) -> int:
        """Declared attribute count including the class column."""
        return self.features.shape[1] + 1

    @property
    def num_classes(self) -> int:
        """max(label) + 1 over *this* dataset — the reference computes this per
        ArffData instance (arff_data.cpp:41-58); the KNN vote uses the train
        set's value and the confusion matrix the test set's."""
        if self.labels.size == 0:
            return 0
        return int(self.labels.max()) + 1

    def validate_for_knn(self, k: int, other: Optional["Dataset"] = None) -> None:
        """Checks the reference leaves as UB (SURVEY.md §3.5.5), plus the
        deferred numeric-only requirement: STRING/DATE columns parse into
        interned codes at load time (matching the reference parser, which
        accepts them, arff_parser.cpp:145-147), but a distance over interned
        codes is meaningless, so *feature* columns of those types are
        rejected here — where the reference instead aborts mid-KNN
        (arff_value.cpp:121). A string-typed *class* column is allowed: the
        interned codes are well-defined class ids (a framework extension;
        the reference aborts on the label cast, main.cpp:57)."""
        for ds in (self, other) if other is not None else (self,):
            for a in list(ds.attributes)[: ds.num_features]:
                if a.type in ("string", "date"):
                    raise ValueError(
                        f"attribute '{a.name}' of type {a.type} is not "
                        f"numeric; KNN distances need numeric feature "
                        f"columns (string/date columns load as interned "
                        f"codes but cannot be compared)"
                    )
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if k > self.num_instances:
            raise ValueError(
                f"k={k} exceeds the number of train instances ({self.num_instances})"
            )
        if (self.labels < 0).any():
            raise ValueError("labels must be non-negative integers")
        if other is not None and other.num_features != self.num_features:
            raise ValueError(
                f"train has {self.num_features} features but test has {other.num_features}"
            )
