"""Pure-Python ARFF parser implementing the reference libarff dialect.

Dialect (SURVEY.md §3.4, libarff/arff_parser.cpp:23-153, arff_lexer.cpp:60-203):

- ``@relation <name>``, then ``@attribute <name> <type>`` lines, then ``@data``
  followed by one comma-separated row per line. Keywords are case-insensitive
  (arff_utils.cpp:29-43).
- Attribute types: NUMERIC | REAL | STRING | DATE | nominal ``{v1,v2,...}``
  (arff_parser.cpp:69-119). INTEGER is additionally accepted as numeric.
- ``%``-comment lines (arff_lexer.cpp:60-78).
- Single- or double-quoted values, which may contain spaces/commas
  (arff_lexer.cpp:159-188).
- ``?`` denotes a missing value (arff_parser.cpp:139-141) → NaN.
- A partial row at EOF is discarded (arff_parser.cpp:130-133,149-151).
- Sparse ARFF (``{index value, ...}`` rows) is NOT supported, matching the
  reference.

Errors carry ``file:line`` context like libarff's THROW (arff_utils.cpp:8-20).

This is the fallback/oracle implementation; the production path is the native
C++ parser in ``knn_tpu/native/arff`` (bound via ctypes in
``knn_tpu.data.arff``), which emits identical arrays.
"""

from __future__ import annotations

import math
from typing import Iterable, Optional

import numpy as np

from knn_tpu.data.dataset import Attribute, Dataset

_NUMERIC_TYPES = {"numeric", "real", "integer"}


class ArffError(ValueError):
    """Parse error with file:line context, mirroring libarff's THROW style."""

    def __init__(self, path: str, line: int, msg: str):
        super().__init__(f"{path}:{line}: {msg}")
        self.path = path
        self.line = line


def _split_csv(line: str, path: str, lineno: int) -> list:
    """Split a data row on commas, honoring single/double quotes."""
    out, buf, quote = [], [], None
    for ch in line:
        if quote is not None:
            if ch == quote:
                quote = None
            else:
                buf.append(ch)
        elif ch in ("'", '"'):
            quote = ch
        elif ch == ",":
            out.append("".join(buf).strip())
            buf = []
        else:
            buf.append(ch)
    if quote is not None:
        raise ArffError(path, lineno, "unterminated quoted value")
    out.append("".join(buf).strip())
    return out


def _parse_attribute(rest: str, path: str, lineno: int) -> Attribute:
    rest = rest.strip()
    if not rest:
        raise ArffError(path, lineno, "@attribute needs a name and a type")
    # Name may be quoted.
    if rest[0] in ("'", '"'):
        q = rest[0]
        end = rest.find(q, 1)
        if end < 0:
            raise ArffError(path, lineno, "unterminated quoted attribute name")
        name, rest = rest[1:end], rest[end + 1 :].strip()
    else:
        parts = rest.split(None, 1)
        if len(parts) < 2:
            raise ArffError(path, lineno, f"@attribute '{parts[0]}' is missing a type")
        name, rest = parts[0], parts[1].strip()
    if not rest:
        raise ArffError(path, lineno, f"@attribute '{name}' is missing a type")
    if rest.startswith("{"):
        if not rest.endswith("}"):
            raise ArffError(path, lineno, "unterminated nominal value list")
        values = _split_csv(rest[1:-1], path, lineno)
        return Attribute(name, "nominal", values)
    type_word = rest.split()[0].lower()
    if type_word in _NUMERIC_TYPES:
        return Attribute(name, "numeric")
    if type_word == "string":
        return Attribute(name, "string")
    if type_word == "date":
        return Attribute(name, "date")
    raise ArffError(path, lineno, f"unsupported attribute type '{rest}'")


def _cell_to_float(
    tok: str, attr: Attribute, path: str, lineno: int
) -> float:
    if tok == "?":
        return math.nan
    if attr.type == "nominal":
        try:
            return float(attr.nominal_values.index(tok))
        except ValueError:
            raise ArffError(
                path, lineno, f"value '{tok}' not in nominal set for '{attr.name}'"
            ) from None
    if attr.type in ("string", "date"):
        # The reference stores these as strings; they cannot participate in the
        # numeric distance. We reject them in feature columns at load time.
        raise ArffError(
            path, lineno, f"attribute '{attr.name}' of type {attr.type} is not numeric"
        )
    try:
        return float(tok)
    except ValueError:
        raise ArffError(
            path, lineno, f"cannot parse '{tok}' as a number for '{attr.name}'"
        ) from None


def parse_arff_lines(
    lines: Iterable[str], path: str = "<memory>"
) -> Dataset:
    relation = ""
    attributes: list = []
    rows: list = []
    in_data = False
    pending: list = []  # cells carried across physical lines (multi-line rows)
    pending_line = 0

    for lineno, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line or line.startswith("%"):
            continue
        if not in_data and line.startswith("@"):
            parts = line.split(None, 1)  # any whitespace separates the keyword
            word = parts[0]
            rest = parts[1] if len(parts) > 1 else ""
            key = word.lower()
            if key == "@relation":
                relation = rest.strip().strip("'\"")
            elif key == "@attribute":
                attributes.append(_parse_attribute(rest, path, lineno))
            elif key == "@data":
                if not attributes:
                    raise ArffError(path, lineno, "@data before any @attribute")
                in_data = True
            else:
                raise ArffError(path, lineno, f"unknown keyword '{word}'")
            continue
        if not in_data:
            raise ArffError(path, lineno, f"unexpected content before @data: '{line}'")
        if line.startswith("{"):
            raise ArffError(path, lineno, "sparse ARFF rows are not supported")
        cells = _split_csv(line, path, lineno)
        if pending:
            cells = pending + cells
            pending = []
        # The reference's token-stream reader consumes exactly num_attributes
        # tokens per instance regardless of line breaks (arff_parser.cpp:121-153);
        # carry short rows forward rather than erroring immediately.
        if len(cells) < len(attributes):
            pending = cells
            pending_line = lineno
            continue
        if len(cells) > len(attributes):
            raise ArffError(
                path,
                lineno,
                f"row has {len(cells)} values but {len(attributes)} attributes declared",
            )
        rows.append(
            [_cell_to_float(tok, attr, path, lineno) for tok, attr in zip(cells, attributes)]
        )
    # A partial row at EOF is discarded, matching arff_parser.cpp:130-133.

    if not attributes:
        raise ArffError(path, 0, "no @attribute declarations found")

    d = len(attributes)
    if rows:
        mat = np.asarray(rows, dtype=np.float32)
    else:
        mat = np.zeros((0, d), dtype=np.float32)
    features = mat[:, : d - 1]
    raw_labels = mat[:, d - 1]
    if np.isnan(raw_labels).any():
        bad = int(np.isnan(raw_labels).argmax())
        raise ArffError(path, 0, f"instance {bad} has a missing class label")
    labels = raw_labels.astype(np.int32)
    return Dataset(features=features, labels=labels, relation=relation, attributes=attributes)


def parse_arff_file(path: str) -> Dataset:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return parse_arff_lines(f, path=str(path))
