"""Pure-Python ARFF parser implementing the reference libarff dialect.

Dialect (SURVEY.md §3.4, libarff/arff_parser.cpp:23-153, arff_lexer.cpp:60-203):

- ``@relation <name>``, then ``@attribute <name> <type>`` lines, then ``@data``
  followed by one comma-separated row per line. Keywords are case-insensitive
  (arff_utils.cpp:29-43).
- Attribute types: NUMERIC | REAL | STRING | DATE | nominal ``{v1,v2,...}``
  (arff_parser.cpp:69-119). INTEGER is additionally accepted as numeric.
- ``%``-comment lines (arff_lexer.cpp:60-78).
- Single- or double-quoted values, which may contain spaces/commas
  (arff_lexer.cpp:159-188). Deliberate deviation: the reference's instance
  reader silently drops every data row containing a quoted value (the
  STRING-typed token breaks its row loop — verified against the built
  reference binary, which reports 0 rows for ``'1','2'``); here quoted data
  cells parse normally, with quoted content preserved verbatim.
- ``?`` denotes a missing value (arff_parser.cpp:139-141) → NaN.
- A partial row at EOF is discarded (arff_parser.cpp:130-133,149-151).
- Sparse ARFF (``{index value, ...}`` rows) is NOT supported, matching the
  reference.
- STRING/DATE data cells parse into per-attribute interned float32 codes
  (first-seen order, table on ``Attribute.string_values``). The reference
  stores them as heap strings (arff_value.cpp:33-48) and only fails when KNN
  reads one as float (arff_value.cpp:121), so such files LOAD there; here the
  numeric-only requirement is deferred to ``Dataset.validate_for_knn``.
- A quoted value may span physical lines, preserving the newline inside the
  value (``_read_str`` reads to the matching quote through newlines,
  arff_lexer.cpp:159-188), and an open ``{`` nominal list continues on the
  following line(s) — newlines are ordinary inter-token whitespace to the
  reference lexer. An unterminated quote at EOF is a located error.

Errors carry ``file:line`` context like libarff's THROW (arff_utils.cpp:8-20);
tokens carried across physical lines by multi-line rows are reported with the
line they appeared on, not the line that completed the row.

This is the fallback/oracle implementation; the production path is the native
C++ parser in ``knn_tpu/native/arff`` (bound via ctypes in
``knn_tpu.data.arff``), which emits identical arrays.
"""

from __future__ import annotations

import math
import re
from typing import Iterable, Optional

import numpy as np

from knn_tpu.data.dataset import Attribute, Dataset
from knn_tpu.resilience.errors import DataError

_NUMERIC_TYPES = {"numeric", "real", "integer"}

# The ASCII whitespace set the native parser strips (arff_c.cc::strip);
# using str.strip() default would also eat Unicode whitespace (\x0c, NBSP)
# and silently diverge from the C++ implementation.
_WS = " \t\r\n"


# Numeric cells must parse bit-identically to the native parser, which uses C
# strtof with a full-consumption check (arff_c.cc::cell_to_float). Python's
# float() diverges three ways: acceptance (digit-group underscores, non-ASCII
# digits accepted; hex floats, nan(...) rejected), rounding (decimal → float64
# → float32 double-rounds near-halfway tokens where strtof single-rounds to
# float32), and NaN sign/payload. So the primary path calls libc strtof itself
# via ctypes; the regex path below is the fallback for platforms where libc
# isn't loadable by name and matches strtof's acceptance set (though not its
# last-ulp rounding).
_STRTOF_RE = re.compile(
    r"[ \t\n\v\f\r]*"
    r"[+-]?"
    r"(?:"
    r"(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?"
    r"|(?P<hex>0[xX](?:[0-9a-fA-F]+\.?[0-9a-fA-F]*|\.[0-9a-fA-F]+)(?:[pP][+-]?\d+)?)"
    r"|inf(?:inity)?"
    r"|nan(?:\([0-9a-zA-Z_]*\))?"
    r")\Z",
    re.ASCII | re.IGNORECASE,
)


def _load_libc_strtof():
    import ctypes

    try:
        fn = ctypes.CDLL(None).strtof
    except (OSError, AttributeError):
        return None
    fn.restype = ctypes.c_float
    fn.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p)]
    return fn


_LIBC_STRTOF = _load_libc_strtof()


def _strtof(tok: str) -> float:
    """Parse `tok` exactly as the native parser does (C strtof + "entire token
    consumed" check, arff_c.cc::cell_to_float) or raise ValueError."""
    if _LIBC_STRTOF is not None:
        import ctypes

        raw = tok.encode("utf-8")
        buf = ctypes.create_string_buffer(raw)
        endp = ctypes.c_char_p()
        val = _LIBC_STRTOF(buf, ctypes.byref(endp))
        consumed = ctypes.cast(endp, ctypes.c_void_p).value - ctypes.addressof(buf)
        # Mirror the native parser's full-consumption rule on the token's
        # EXPLICIT length: a token with an embedded NUL is rejected (strtof
        # stops at the NUL, so it can never consume the whole view) —
        # ADVICE r2: the two parsers previously disagreed here.
        if consumed != len(raw) or consumed == 0:
            raise ValueError(tok)
        return val
    m = _STRTOF_RE.match(tok)
    if m is None:
        raise ValueError(tok)
    s = tok.lstrip(" \t\n\v\f\r")
    if m.group("hex") is not None:
        return float.fromhex(s)
    if s.lower().lstrip("+-").startswith("nan"):
        return math.nan
    return float(s)


class ArffError(DataError):
    """Parse error with file:line context, mirroring libarff's THROW style.
    A :class:`knn_tpu.resilience.errors.DataError` (and still a ValueError),
    so resilience-aware callers branch on the taxonomy while pre-existing
    ``except ValueError`` handling keeps working."""

    def __init__(self, path: str, line: int, msg: str):
        super().__init__(f"{path}:{line}: {msg}")
        self.path = path
        self.line = line


def _split_csv(line: str, path: str, lineno: int) -> list:
    """Tokenize a data/nominal segment the way the reference lexer does:
    unquoted whitespace and commas BOTH end a token (next_token skips
    whitespace between tokens, arff_lexer.cpp:93-97; a comma terminates
    ``_read_str``, :190), so ``1 2`` and ``1,2`` are the same two tokens and
    several rows may share one physical line. Quoted content is preserved
    verbatim (``' '`` is the one-space token, not empty). A comma with no
    token since the previous comma yields an empty cell, which callers
    reject — the reference silently truncates the dataset there
    (arff_lexer.cpp:125-127), a defect replaced with a located error. A
    comma directly after its token is that token's terminator, so a single
    trailing comma is absorbed (``1,2,`` tokenizes like ``1,2``).

    Returns ``(token, lineno)`` pairs: ``line`` may be a quote-joined
    logical line whose '\\n's advance the physical line count, and each
    token cites the line it STARTED on — same attribution as the native
    scanner's per-token line."""
    out: list = []
    buf: list = []
    active = False            # a token is in progress
    token_since_comma = False  # a completed token awaits its comma
    quote = None
    cur_line = lineno
    tok_line = lineno

    def flush():
        nonlocal buf, active, token_since_comma
        out.append(("".join(buf), tok_line))
        buf = []
        active = False
        token_since_comma = True

    for ch in line:
        if quote is not None:
            if ch == quote:
                quote = None
            else:
                if ch == "\n":
                    cur_line += 1
                buf.append(ch)
            continue
        if ch == "\n":
            cur_line += 1
            # A newline outside quotes acts as inter-token whitespace
            # (only quote-joined logical lines contain one).
            if active:
                flush()
            continue
        if ch in ("'", '"'):
            quote = ch
            if not active:
                tok_line = cur_line
            active = True
            continue
        if ch in " \t":
            if active:
                flush()
            continue
        if ch == ",":
            if active:
                flush()
                token_since_comma = False  # comma terminated its own token
            elif token_since_comma:
                token_since_comma = False  # separator for the flushed token
            else:
                out.append(("", cur_line))  # ",," or leading comma: empty cell
            continue
        if not active:
            tok_line = cur_line
        active = True
        buf.append(ch)
    if quote is not None:
        raise ArffError(path, tok_line, "unterminated quoted value")
    if active:
        flush()
    return out


def _parse_attribute(rest: str, path: str, lineno: int) -> Attribute:
    rest = rest.strip(_WS)
    if not rest:
        raise ArffError(path, lineno, "@attribute needs a name and a type")
    # Name may be quoted.
    if rest[0] in ("'", '"'):
        q = rest[0]
        end = rest.find(q, 1)
        if end < 0:
            raise ArffError(path, lineno, "unterminated quoted attribute name")
        name, rest = rest[1:end], rest[end + 1 :].strip(_WS)
    else:
        parts = re.split(r"[ \t]+", rest, maxsplit=1)
        if len(parts) < 2:
            raise ArffError(path, lineno, f"@attribute '{parts[0]}' is missing a type")
        name, rest = parts[0], parts[1].strip(_WS)
    if not rest:
        raise ArffError(path, lineno, f"@attribute '{name}' is missing a type")
    if rest.startswith("{"):
        if not rest.endswith("}"):
            raise ArffError(path, lineno, "unterminated nominal value list")
        inner = rest[1:-1]
        # "{a,b,}" is reference-valid: the comma before "}" is consumed as
        # the previous token's terminator (arff_lexer.cpp:190, then
        # next_token's unconditional advance) and "}" lexes as BRKT_CLOSE.
        # Only a literal trailing comma is absorbed — a quoted-empty final
        # value ({a,''}) still hits the empty-value error below. "{}" is an
        # empty nominal set (reference: BRKT_CLOSE immediately ends the
        # value loop).
        values = (
            [] if inner.strip(_WS) == ""
            else [tok for tok, _ in _split_csv(inner, path, lineno)]
        )
        if any(v == "" for v in values):
            raise ArffError(path, lineno, "empty value in nominal list")
        return Attribute(name, "nominal", values)
    type_word = re.split(r"[ \t]+", rest, maxsplit=1)[0].lower()
    if type_word in _NUMERIC_TYPES:
        return Attribute(name, "numeric")
    if type_word == "string":
        return Attribute(name, "string")
    if type_word == "date":
        return Attribute(name, "date")
    raise ArffError(path, lineno, f"unsupported attribute type '{rest}'")


def _cell_to_float(
    tok: str, attr: Attribute, intern: dict, path: str, lineno: int
) -> float:
    if tok == "?":
        return math.nan
    if attr.type == "nominal":
        try:
            return float(attr.nominal_values.index(tok))
        except ValueError:
            raise ArffError(
                path, lineno, f"value '{tok}' not in nominal set for '{attr.name}'"
            ) from None
    if attr.type in ("string", "date"):
        # Intern in first-seen order (module docstring): the cell stores the
        # code; the table lands on attr.string_values after the parse.
        return float(intern.setdefault(tok, len(intern)))
    try:
        return _strtof(tok)
    except ValueError:
        raise ArffError(
            path, lineno, f"cannot parse '{tok}' as a number for '{attr.name}'"
        ) from None


def _scan_quote(s: str, quote: Optional[str] = None) -> Optional[str]:
    """Fold quote state over ``s``: returns the open quote char if the text
    ends inside a quoted value, else None. The carry for multi-line quoted
    values (arff_lexer.cpp:159-188 reads through newlines to the matching
    quote)."""
    for ch in s:
        if quote is not None:
            if ch == quote:
                quote = None
        elif ch in ("'", '"'):
            quote = ch
    return quote


def _fold_nominal(state: tuple, seg: str) -> tuple:
    """Fold nominal-list bracket/quote state over ``seg`` incrementally —
    ``state`` is ``(quote, opened, closed)``. The declaration continues on
    the next physical line while a ``{`` has opened (outside quotes) and no
    unquoted ``}`` has closed it, as in the reference's token-stream reader
    (newlines are ordinary whitespace between tokens, arff_lexer.cpp:93-97).
    Folding per appended segment keeps multi-line declarations linear in
    their total length (rescanning the accumulation is quadratic)."""
    quote, opened, closed = state
    if closed:
        return state
    for ch in seg:
        if quote is not None:
            if ch == quote:
                quote = None
        elif ch in ("'", '"'):
            quote = ch
        elif ch == "{":
            opened = True
        elif ch == "}" and opened:
            return (quote, opened, True)
    return (quote, opened, closed)


def parse_arff_lines(
    lines: Iterable[str], path: str = "<memory>"
) -> Dataset:
    relation = ""
    attributes: list = []
    interns: list = []  # per-attribute first-seen intern maps (string/date)
    rows: list = []
    in_data = False
    # (cell, lineno) pairs carried across physical lines (multi-line rows);
    # carrying the lineno keeps error locations on the token's own line.
    pending: list = []

    it = iter(lines)
    lineno = 0
    while True:
        raw = next(it, None)
        if raw is None:
            break
        lineno += 1
        # '%' starts a comment only at the true line start (the reference
        # lexer skips comments only when '%' is the first character after a
        # newline, arff_lexer.cpp:60-78); an indented or trailing '%' is
        # DATA and typically a located type error downstream.
        if raw.startswith("%"):
            continue
        # A quoted value may span physical lines (arff_lexer.cpp:159-188
        # reads to the matching quote through newlines): join lines into one
        # logical line while a quote is open, preserving the line break
        # inside the value VERBATIM — a '\r' before the newline stays, as in
        # the native parser's zero-copy slice and the reference's raw-byte
        # scanner (the file reader splits at '\n' only). Comment skipping
        # never applies inside a quote (the reference skips '%' lines only
        # BETWEEN tokens). The quote state folds incrementally over each
        # appended segment, so the join is linear in the value's length.
        logical = raw
        start_line = lineno
        open_q = _scan_quote(raw)
        while open_q is not None:
            nxt = next(it, None)
            if nxt is None:
                raise ArffError(path, start_line, "unterminated quoted value")
            lineno += 1
            logical += "\n" + nxt
            open_q = _scan_quote("\n" + nxt, open_q)
        line = logical.strip(_WS)
        if not line:
            continue
        if not in_data and line.startswith("@"):
            # ASCII space/tab separates the keyword — same set as the
            # native parser (arff_c.cc find_first_of(" \t")), NOT
            # Unicode whitespace.
            parts = re.split(r"[ \t]+", line, maxsplit=1)
            word = parts[0]
            rest = parts[1] if len(parts) > 1 else ""
            key = word.lower()
            if key == "@relation":
                # Strip exactly one matched outer quote pair (same rule as
                # the native parser) — not a greedy strip of quote chars.
                relation = rest.strip(_WS)
                if (
                    len(relation) >= 2
                    and relation[0] in ("'", '"')
                    and relation[-1] == relation[0]
                ):
                    relation = relation[1:-1]
            elif key == "@attribute":
                # An open nominal list continues on the next physical
                # line(s): the reference reads the {...} value tokens from
                # the lexer stream, where a newline is ordinary whitespace
                # (arff_parser.cpp:69-119). '%' comment lines between the
                # value tokens are skipped as usual; a quoted value inside
                # the continued list may itself span further lines.
                nom_state = _fold_nominal((None, False, False), rest)
                pieces = [rest]
                while nom_state[1] and not nom_state[2]:
                    nxt = next(it, None)
                    if nxt is None:
                        break  # _parse_attribute raises its located error
                    lineno += 1
                    if nxt.startswith("%"):
                        continue
                    seg = nxt
                    seg_q = _scan_quote(seg)
                    while seg_q is not None:
                        nx2 = next(it, None)
                        if nx2 is None:
                            raise ArffError(
                                path, lineno, "unterminated quoted value"
                            )
                        lineno += 1
                        seg += "\n" + nx2
                        seg_q = _scan_quote("\n" + nx2, seg_q)
                    piece = seg.strip(_WS)
                    pieces.append(piece)
                    # Quote state at each boundary is None (both rest and
                    # seg join to quote-balanced logical lines above), so
                    # folding just the appended piece matches a rescan; a
                    # single join below keeps the whole declaration linear
                    # (chained `rest += piece` recopies the accumulation).
                    nom_state = _fold_nominal(nom_state, " " + piece)
                rest = " ".join(pieces)
                attributes.append(_parse_attribute(rest, path, start_line))
                interns.append({})
            elif key == "@data":
                if not attributes:
                    raise ArffError(path, start_line, "@data before any @attribute")
                in_data = True
            else:
                raise ArffError(path, start_line, f"unknown keyword '{word}'")
            continue
        if not in_data:
            raise ArffError(
                path, start_line, f"unexpected content before @data: '{line}'"
            )
        if line.startswith("{"):
            raise ArffError(path, start_line, "sparse ARFF rows are not supported")
        cells = _split_csv(line, path, start_line)
        for tok, tok_line in cells:
            if tok == "":
                raise ArffError(path, tok_line, "empty value in data row")
        # The reference's reader consumes exactly num_attributes tokens per
        # instance from the @data token stream regardless of line breaks
        # (arff_parser.cpp:121-153): rows may span physical lines AND several
        # rows may share one line, so accumulate tokens and emit every full
        # group of num_attributes. Each token carries the physical line it
        # started on (quote-joined logical lines span several), matching the
        # native scanner's attribution.
        pending.extend(cells)
        d = len(attributes)
        off = 0
        while len(pending) - off >= d:
            rows.append(
                [_cell_to_float(tok, attr, intern, path, tok_line)
                 for (tok, tok_line), attr, intern in zip(
                     pending[off : off + d], attributes, interns)]
            )
            off += d
        if off:  # consume emitted rows once per line, like the C++ twin
            del pending[:off]
    # A partial row at EOF is discarded, matching arff_parser.cpp:130-133.

    if not attributes:
        raise ArffError(path, 0, "no @attribute declarations found")
    for attr, intern in zip(attributes, interns):
        if attr.type in ("string", "date"):
            attr.string_values = list(intern)  # insertion order = code order

    d = len(attributes)
    if rows:
        mat = np.asarray(rows, dtype=np.float32)
    else:
        mat = np.zeros((0, d), dtype=np.float32)
    features = mat[:, : d - 1]
    raw_labels = mat[:, d - 1]
    if np.isnan(raw_labels).any():
        bad = int(np.isnan(raw_labels).argmax())
        raise ArffError(path, 0, f"instance {bad} has a missing class label")
    labels = raw_labels.astype(np.int32)
    return Dataset(
        features=features, labels=labels, relation=relation,
        attributes=attributes, raw_targets=raw_labels.astype(np.float32),
    )


# First line whose stripped start is the @data keyword (word-bounded, so
# "@database" stays an unknown-keyword error for the full parser).
_DATA_RE = re.compile(r"(?mi)^[ \t\r]*@data(?=[ \t\r]|\r?$)")
# Empty-cell comma patterns the comma->space translation would silently
# swallow: ",,", a line-leading comma (",  ," covered by the first).
_BAD_COMMA_RE = re.compile(r",[ \t\r]*,|^[ \t\r]*,|\n[ \t\r]*,")


def _parse_numeric_fast(raw: str, path: str) -> "Dataset | None":
    """Vectorized parse for the common all-numeric case (~25x the
    token-by-token path): headers go through the full parser, then the @data
    section becomes one ``str.split`` + ``np.array(..., float32)`` — bitwise
    identical to the slow path (both convert decimal text at float64 and
    round once to float32). Returns None whenever ANY dialect subtlety might
    apply — quotes, comments, missing values, sparse braces, empty-cell
    comma patterns, non-numeric attributes, non-finite values, conversion
    failures — so every error case falls through to the full parser and its
    located messages."""
    m = _DATA_RE.search(raw)
    if m is None:
        return None
    data_end = raw.find("\n", m.end())
    if data_end < 0:
        return None
    # The match may lie INSIDE a multi-line header value — a quoted value
    # (quotes span physical lines, arff_lexer.cpp:159-188) or an open {...}
    # nominal list (newlines are ordinary whitespace between value tokens,
    # arff_parser.cpp:69-119) — and the @data line's own trailing content
    # can open a quote that joins the first data row into the header's
    # logical line. Fold quote AND brace state over everything up to and
    # including the @data physical line — skipping '%' comment lines only
    # while outside a quote, as parse_arff_lines does both at top level and
    # between continuation lines — and defer to the full parser when the
    # region ends inside either. Nominal lists don't nest, so one
    # open/close flag mirrors the per-declaration continuation state.
    head_lines = raw[: m.start()].split("\n")
    quote = None
    brace = False
    for ln in head_lines:
        if quote is None and ln.startswith("%"):
            continue
        for ch in ln:
            if quote is not None:
                if ch == quote:
                    quote = None
            elif ch in ("'", '"'):
                quote = ch
            elif ch == "{":
                brace = True
            elif ch == "}":
                brace = False
    if quote is not None or brace:
        return None  # the @data match itself lies inside a header value
    if _scan_quote(raw[m.end() : data_end]) is not None:
        return None  # the @data line's own tail opens a quote
    if head_lines and head_lines[-1] == "":
        # The slice ends at the newline BEFORE the @data line; drop the
        # phantom empty piece so the appended "@data" keeps its real line
        # number (errors like "@data before any @attribute" cite it).
        head_lines.pop()
    header = parse_arff_lines(head_lines + ["@data"], path)
    if not all(a.type == "numeric" for a in header.attributes):
        return None
    sec = raw[data_end + 1 :]
    # Eligible content is exactly the plain ASCII float charset plus the
    # separators the dialect shares with str.split(): anything else — quotes,
    # comments, '?', sparse braces, letters (inf/nan/unicode digits, which
    # numpy and _strtof accept differently), '_' (Python float accepts,
    # _strtof rejects), '\f'/'\v' (str.split() whitespace but dialect token
    # chars), or a '\r' outside a CRLF ending (token char, split() whitespace:
    # test_interior_cr_is_a_token_char) — defers to the full parser.
    if re.search(r"[^0-9eE+\-. \t\r\n,]|\r(?!\n)", sec) or _BAD_COMMA_RE.search(sec):
        return None
    toks = sec.replace(",", " ").split()
    try:
        arr64 = np.array(toks, dtype=np.float64)
    except (ValueError, OverflowError):
        return None  # a malformed token: the full parser owns the error
    with np.errstate(over="ignore"):
        # f32-range overflow (e.g. '1e40') clamps to inf like strtof; the
        # non-finite check below then defers to the full parser without the
        # cast warning escaping (it would crash under warnings-as-errors).
        arr = arr64.astype(np.float32)
    d = len(header.attributes)
    n = arr.size // d  # partial row at EOF discarded (arff_parser.cpp:130-133)
    if n == 0 or not np.isfinite(arr[: n * d]).all():
        return None  # inf/nan cells: defer to the full parser's handling
    # Double-rounding repair: the contract is C strtof's correctly-rounded
    # decimal->f32 (what the native twin and _strtof produce). Going through
    # f64 diverges ONLY when the f64 value lands exactly on an f32 midpoint
    # (any true value near a midpoint rounds TO that midpoint in f64, so a
    # non-midpoint f64 decides the f32 the same way the true value would).
    # Those rare tokens re-parse through _strtof.
    cast64 = arr.astype(np.float64)
    mid_hi = (cast64 + np.nextafter(arr, np.float32(np.inf)).astype(np.float64)) / 2
    mid_lo = (cast64 + np.nextafter(arr, np.float32(-np.inf)).astype(np.float64)) / 2
    amb = np.nonzero((arr64 == mid_hi) | (arr64 == mid_lo))[0]
    for i in amb:
        try:
            arr[i] = _strtof(toks[i])
        except ValueError:
            return None
    mat = arr[: n * d].reshape(n, d)
    raw_labels = mat[:, d - 1]
    return Dataset(
        features=mat[:, : d - 1],
        labels=raw_labels.astype(np.int32),
        relation=header.relation,
        attributes=header.attributes,
        raw_targets=raw_labels.astype(np.float32),
    )


def parse_arff_file(path: str) -> Dataset:
    # newline="" + manual split: physical lines end at '\n' ONLY, like the
    # reference scanner (NEWLINE = '\n', arff_scanner.cpp:4) and the native
    # twin. Universal-newline mode would turn a lone '\r' into a line break,
    # where the dialect treats interior '\r' as a token character ('\r\n'
    # endings still work — the trailing '\r' strips as whitespace).
    with open(path, "r", encoding="utf-8", errors="replace", newline="") as f:
        raw = f.read()
    fast = _parse_numeric_fast(raw, str(path))
    if fast is not None:
        return fast
    return parse_arff_lines(raw.split("\n"), path=str(path))
