from knn_tpu.data.dataset import Dataset
from knn_tpu.data.arff import load_arff

__all__ = ["Dataset", "load_arff"]
