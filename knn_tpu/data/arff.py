"""ARFF loading front-end.

Dispatch order:
1. the native C++ parser (``knn_tpu/native/arff`` via ctypes) when its shared
   library has been built — the production path, mirroring the reference's
   native libarff (libarff/arff_parser.h:18);
2. the pure-Python dialect implementation (``knn_tpu.data.pyarff``).

Both emit identical dense arrays. An optional ``.npz`` cache keyed on the ARFF
file's size+mtime+hash skips re-parsing (the reference re-parses on every run,
and under MPI on every *rank* — mpi.cpp:136-139; the cache is our replacement
for that replicated-IO cost).
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Optional

import numpy as np

from knn_tpu.data.dataset import Attribute, Dataset
from knn_tpu.data import pyarff

_CACHE_ENV = "KNN_TPU_ARFF_CACHE"
# Above this size, silently falling back to the pure-Python parser costs
# real wall time (~15 MB/s vs the native parser's ~270-300 MB/s — the
# measured ~19x gap, docs/PARITY.md), so the fallback announces itself
# once per parse instead of letting a pip-only (no-compiler) install eat
# it wordlessly on every first load (VERDICT.md #8; the .npz cache only
# helps repeats).
_PY_PARSER_WARN_BYTES = 10 * 1024 * 1024
# Bumped when the cached array schema changes (v2: + raw_targets; v3:
# + Attribute.string_values for interned STRING/DATE columns), so caches
# written by older code are simply never found rather than silently read
# without the newer fields.
_CACHE_SCHEMA = 3


def _cache_path(path: str) -> Optional[Path]:
    cache_dir = os.environ.get(_CACHE_ENV, "")
    if not cache_dir:
        return None
    st = os.stat(path)
    key = f"v{_CACHE_SCHEMA}:{os.path.abspath(path)}:{st.st_size}:{st.st_mtime_ns}"
    digest = hashlib.sha256(key.encode()).hexdigest()[:24]
    return Path(cache_dir) / f"{Path(path).stem}-{digest}.npz"


def load_arff(path: str, use_native: Optional[bool] = None) -> Dataset:
    """Parse an ARFF file into a dense :class:`Dataset`.

    ``use_native``: force the C++ parser (True), force pure Python (False), or
    auto-detect (None, default).
    """
    from knn_tpu import obs
    from knn_tpu.resilience.errors import DataError
    from knn_tpu.resilience.retry import guarded_call

    cached = False
    if obs.enabled():
        # Determine cache-hit BEFORE the load (the load itself may write
        # the cache), so the counters can distinguish a real parse from an
        # .npz fast path. ``cached`` is pre-initialized above because
        # enabled() is re-read after the load and may flip mid-call.
        c = _cache_path(path)
        cached = bool(c is not None and c.exists())
    with obs.span("ingest", file=os.path.basename(path)):
        # ``arff.parse``: the ingest fault point. OSErrors (injected or a
        # real transient FS blip) retry with backoff; what survives is
        # typed — parse failures are already DataError (ArffError / the
        # native binding), and a missing/unreadable file classifies into
        # one — so callers branch on DataError, not libc message text.
        try:
            ds = guarded_call(
                "arff.parse", lambda: _load_arff(path, use_native),
                classify=False,
            )
        except DataError:
            raise
        except OSError as e:
            raise DataError(f"{path}: {e.strerror or e}") from e
    if obs.enabled():
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        label = "true" if cached else "false"
        obs.counter_add("knn_ingest_bytes_total", size,
                        help="ARFF bytes ingested (cached=true: served from "
                             "the .npz cache, not re-parsed)", cached=label)
        obs.counter_add("knn_ingest_rows_total", ds.num_instances,
                        help="ARFF data rows ingested", cached=label)
    return ds


def _load_arff(path: str, use_native: Optional[bool] = None) -> Dataset:
    cache = _cache_path(path)
    if cache is not None and cache.exists():
        with np.load(cache, allow_pickle=False) as z:
            attrs = [
                Attribute(
                    a["name"], a["type"], a.get("nominal_values"),
                    a.get("string_values"),
                )
                for a in json.loads(str(z["attributes"]))
            ]
            return Dataset(
                features=z["features"],
                labels=z["labels"],
                relation=str(z["relation"]),
                attributes=attrs,
                raw_targets=z["raw_targets"] if "raw_targets" in z else None,
            )

    ds: Optional[Dataset] = None
    if use_native is not False:
        try:
            from knn_tpu.resilience.faults import fault_point

            # Losing the native parser degrades to the pure-Python twin —
            # its own mini-ladder (identical arrays, slower parse).
            fault_point("native.load")
            from knn_tpu.native import arff_native

            ds = arff_native.parse(path)
        except (ImportError, OSError):
            if use_native is True:
                raise
    if ds is None:
        if use_native is None:  # wanted native, fell back — say so when
            try:                # the file is big enough to hurt
                size = os.path.getsize(path)
            except OSError:
                size = 0
            if size > _PY_PARSER_WARN_BYTES:
                import sys

                print(
                    f"warning: {path}: parsing {size / 2**20:.0f} MB with "
                    f"the pure-Python ARFF parser (~15 MB/s; the native "
                    f"parser measures ~19x faster — build it with "
                    f"`make native`, docs/PARITY.md)",
                    file=sys.stderr,
                )
        ds = pyarff.parse_arff_file(path)

    if cache is not None:
        cache.parent.mkdir(parents=True, exist_ok=True)
        np.savez(
            cache,
            features=ds.features,
            labels=ds.labels,
            raw_targets=ds.targets,
            relation=ds.relation,
            attributes=json.dumps(
                [
                    {
                        "name": a.name,
                        "type": a.type,
                        "nominal_values": a.nominal_values,
                        "string_values": a.string_values,
                    }
                    for a in ds.attributes
                ]
            ),
        )
    return ds


def _quote(value: str) -> str:
    """Quote with whichever quote char the value doesn't contain — neither
    our parsers nor the reference lexer support backslash escapes, so a
    value containing BOTH quote chars is unrepresentable in the dialect."""
    if "'" not in value:
        return "'" + value + "'"
    if '"' not in value:
        return '"' + value + '"'
    raise ValueError(
        f"value {value!r} contains both quote characters and cannot be "
        f"represented in the ARFF dialect (no escape syntax exists)"
    )


def _quote_if_needed(name: str) -> str:
    # Leading %, { or @ must be quoted: a bare value opening a data line
    # re-reads as a comment, a sparse row, or a header directive (r2 review —
    # '%pct,0' written unquoted silently drops the row as a comment).
    if name and name[0] not in "%{@" \
            and not any(c.isspace() for c in name) and "," not in name \
            and "'" not in name and '"' not in name:
        return name
    return _quote(name)


def write_arff(ds: Dataset, path: str) -> None:
    """Serialize a :class:`Dataset` back to ARFF.

    The reference *declares* this capability (``ArffData::write_arff``,
    libarff/arff_data.h:131) but never implements it (arff_data.cpp:167);
    here it exists. The output round-trips through :func:`load_arff` to
    identical arrays: features with NaN written as ``?``, labels as integers,
    nominal cells mapped back to their declared value strings.
    """
    n, d = ds.features.shape
    attrs = list(ds.attributes)
    if not attrs:
        attrs = [Attribute(f"attr{i}", "numeric") for i in range(d)] + [
            Attribute("class", "numeric")
        ]
    if len(attrs) != d + 1:
        raise ValueError(
            f"dataset declares {len(attrs)} attributes but has {d} feature "
            f"columns + 1 class column"
        )

    def data_value(raw: str) -> str:
        # A value equal to "?" cannot round-trip: the dialect strips quotes
        # before the missing-value check (both our parsers and the reference
        # lexer, arff_lexer.cpp:159-188), so even '?' reads back as missing.
        # Raise like _quote's both-quotes case rather than silently writing
        # a cell that re-ingests as NaN and shifts every later intern code.
        if raw == "?":
            raise ValueError(
                'the value "?" cannot be represented in the ARFF dialect: '
                "quoted or not, it parses back as a missing value"
            )
        return _quote_if_needed(raw)

    def attr_line(a: Attribute) -> str:
        if a.type == "nominal":
            vals = ",".join(data_value(v) for v in (a.nominal_values or []))
            return f"@attribute {_quote_if_needed(a.name)} {{{vals}}}"
        return f"@attribute {_quote_if_needed(a.name)} {a.type.upper()}"

    def cell(value: float, a: Attribute) -> str:
        if np.isnan(value):
            return "?"
        if a.type == "nominal" and a.nominal_values:
            # Quote when needed so values with spaces/commas survive.
            return data_value(str(a.nominal_values[int(value)]))
        if a.type in ("string", "date") and a.string_values:
            # Interned code -> original value, quoted so embedded
            # spaces/commas survive the round trip.
            return data_value(str(a.string_values[int(value)]))
        f = float(value)
        return str(int(f)) if f.is_integer() else repr(f)

    with open(path, "w", encoding="utf-8") as out:
        out.write(f"@relation {_quote_if_needed(ds.relation or 'dataset')}\n\n")
        for a in attrs:
            out.write(attr_line(a) + "\n")
        out.write("\n@data\n")
        for r in range(n):
            row = [cell(ds.features[r, c], attrs[c]) for c in range(d)]
            row.append(cell(float(ds.targets[r]), attrs[d]))
            out.write(",".join(row) + "\n")
