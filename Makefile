# Build + backend-selection convention, preserving the reference's
# target-per-backend interface (reference Makefile:1-9: main | multi-thread |
# mpi | clean) and adding the native libs and the tpu target. Each backend
# target emits a wrapper script with the reference's positional CLI.

CXX      ?= g++
CXXFLAGS ?= -O3 -std=c++17 -fPIC -Wall -Wextra
LIB_DIR  := knn_tpu/native/lib

.PHONY: all native main multi-thread mpi tpu datasets test verify chaos serve-smoke chaos-soak quality-soak ivf-soak mutable-soak fleet-soak shard-soak overload-soak capacity-probe replay-gate bench bench-gate parity device-parity ref-diff clean

all: native main multi-thread mpi tpu datasets

# Synthetic fixture ladder with the reference datasets' shape characteristics
# (SURVEY.md §2.4) — generated, not copied, so a standalone checkout has
# runnable data for the README quick start. Freshness lives in the script
# (--if-stale: regenerate only when a file is missing or older than the
# generator) so this works on any make and is parallel-safe.
FIXTURES := $(foreach s,small medium large,$(foreach t,train test,datasets/$(s)-$(t).arff))

datasets:
	python3 scripts/make_fixtures.py --if-stale datasets

native: $(LIB_DIR)/libknn_arff.so $(LIB_DIR)/libknn_runtime.so

$(LIB_DIR)/libknn_arff.so: knn_tpu/native/arff/arff_c.cc
	@mkdir -p $(LIB_DIR)
	$(CXX) $(CXXFLAGS) -shared -o $@ $< -lpthread

$(LIB_DIR)/libknn_runtime.so: knn_tpu/native/runtime/knn_runtime.cc
	@mkdir -p $(LIB_DIR)
	$(CXX) $(CXXFLAGS) -shared -o $@ $< -lpthread

# Wrapper scripts: ./main train test k | ./multi-thread train test k T |
# ./mpi train test k | ./tpu train test k
define WRAPPER
	@printf '#!/bin/sh\nexec python3 -m knn_tpu.cli --persona $(1) "$$@"\n' > $(2)
	@chmod +x $(2)
	@echo "wrote ./$(2)"
endef

main: native
	$(call WRAPPER,main,main)

multi-thread: native
	$(call WRAPPER,multi-thread,multi-thread)

mpi:
	$(call WRAPPER,mpi,mpi)

tpu:
	$(call WRAPPER,tpu,tpu)

test:
	python3 -m pytest tests/ -q

# The tier-1 gate (ROADMAP.md): the not-slow suite on CPU with the 8-device
# virtual mesh, plus a bytecode-compile of the package so syntax errors in
# rarely-imported modules can't hide, plus the disabled-path overhead gate
# (observability/tracing must record NOTHING and cost ~nothing while off —
# docs/OBSERVABILITY.md §Overhead). CI runs exactly this target.
verify:
	python3 -m compileall -q knn_tpu bench.py
	JAX_PLATFORMS=cpu python3 -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider
	JAX_PLATFORMS=cpu python3 scripts/check_disabled_overhead.py

# The chaos gate (docs/RESILIENCE.md): the deterministic fault-injection
# suite — every (fault point, mode) pair must end in recovery with
# bit-identical predictions or a typed error, never a raw traceback.
# KNN_TPU_RETRY_BASE_MS=0 removes backoff sleeps so chaos runs at full
# speed; the schedule itself is covered by unit tests.
chaos:
	JAX_PLATFORMS=cpu KNN_TPU_RETRY_BASE_MS=0 python3 -m pytest \
		tests/test_resilience.py tests/test_arff_malformed.py -q \
		-p no:cacheprovider

# The serving lifecycle gate (docs/SERVING.md): build a fixture index,
# boot `knn_tpu serve` as a subprocess, probe /predict (bit-identical to
# the in-process model) + /healthz + /metrics, then SIGINT and require a
# clean exit. stdlib-only probing; covers what the in-process server
# tests cannot (signals, the ready banner, a real ephemeral-port bind).
serve-smoke:
	JAX_PLATFORMS=cpu python3 scripts/serve_smoke.py

# The self-healing gate (docs/SERVING.md §Ops runbook): boot the server
# under a seeded fault burst, hammer it with concurrent closed-loop
# clients, and assert the soak invariants — every request one terminal
# outcome with a request_id that resolves to a consistent flight-recorder
# timeline, 200s bit-identical to the oracle, no traceback bodies, the
# breaker opens then re-closes with availability back to 100%, the SLO
# burn rate rises under the burst and recovers to ~0, and a final SIGTERM
# under load drains cleanly (exit 0). Short mode ~20 s. The per-request
# Perfetto trace lands in build/ (CI uploads it as a workflow artifact).
chaos-soak:
	JAX_PLATFORMS=cpu KNN_TPU_RETRY_BASE_MS=0 python3 scripts/chaos_soak.py \
		--short --perfetto-out build/chaos-soak-trace.json

# The answer-quality gate (docs/OBSERVABILITY.md §Quality & drift): boot
# the server with shadow scoring at rate 1.0 under the chaos-soak fault
# burst and assert (1) the recall SLI holds exactly 1.0 across every
# exact rung the burst exercised — any divergence is a real bug — then
# (2) inject index corruption via the SIGUSR2 test hook and assert the
# quality burn rate rises and /debug/quality localizes it to the
# answering rung. The verdict JSON lands in build/ (CI uploads it).
quality-soak:
	JAX_PLATFORMS=cpu KNN_TPU_RETRY_BASE_MS=0 python3 scripts/quality_soak.py \
		--short --json-out build/quality-soak-verdict.json

# The approximate-serving gate (docs/INDEXES.md): build a format-3 IVF
# artifact over the large fixture and assert both enforced promises —
# (1) speed x recall: under identical closed-loop load with shadow
# scoring at rate 1.0, the ivf rung sustains >= 3x the exact fast rung's
# row throughput while the shadow-scored recall SLI on the ivf rung
# holds >= the recall floor; (2) the quality loop closes: with nprobe
# starved to 1 the quality burn rises above 1, the probe policy widens
# toward exact, and the short-window burn recovers. The verdict JSON
# lands in build/ (CI uploads it as a workflow artifact).
ivf-soak:
	JAX_PLATFORMS=cpu python3 scripts/ivf_soak.py --short \
		--json-out build/ivf-soak-verdict.json

# The online-mutation gate (docs/INDEXES.md §Mutable tier): boot serve
# --mutable on and assert the four mutable contracts — (1) under the
# chaos fault burst, every read's indices are bit-identical to an oracle
# replay of the acknowledged mutation history at that read's
# mutation_seq (distances inside float32 ulp — the rung-form rule) and
# write-to-visible freshness p99 stays bounded; (2) a compaction swap
# under concurrent load is atomic (every response carries exactly the
# old or the new index_version) and replay holds across the fold in
# BOTH generations' positional spaces; (3) a fault-armed compaction
# rolls back with the old generation serving and every write intact;
# (4) a SIGKILL mid-compaction recovers with zero acknowledged writes
# lost. The verdict JSON lands in build/ (CI uploads it).
mutable-soak:
	JAX_PLATFORMS=cpu KNN_TPU_RETRY_BASE_MS=0 python3 scripts/mutable_soak.py \
		--short --json-out build/mutable-soak-verdict.json

# The replica-set gate (docs/SERVING.md §Running a replica set): 3
# mutable replicas (primary + 2 WAL-shipped followers) behind a
# `knn_tpu route` router with auto-failover. Four legs — (1) a follower's
# process group is SIGKILLed under concurrent load: ZERO failed reads,
# every read bit-identical to the oracle replay of the primary's durable
# WAL; (2) the PRIMARY is SIGKILLed: writes 503 typed until the router
# promotes the most-caught-up follower, then resume, with zero
# acknowledged writes lost (every acked (seq, rows) pair present
# bit-identical in the new primary's WAL); (3) the ex-primary rejoins as
# a follower — unacked tail truncated at the takeover seq, catch-up over
# wal-append with no divergence; (4) a crash-stopped replica aborts a
# coordinated reload all-or-nothing (rolled back fleet-wide), and the
# retry flips every replica. The verdict JSON lands in build/ (CI
# uploads it).
fleet-soak:
	JAX_PLATFORMS=cpu KNN_TPU_RETRY_BASE_MS=0 python3 scripts/fleet_soak.py \
		--short --json-out build/fleet-soak-verdict.json

# Mesh-sharded serving held to its contracts (docs/SERVING.md §Sharded
# serving): a --shards 2 serve vs an unsharded twin under closed-loop
# load (bit-identity live, not just in tests), mutation lockstep over
# the sharded delta tail, straggler gauges on every surface, and the
# shard-group kill drill behind the router.
shard-soak:
	JAX_PLATFORMS=cpu KNN_TPU_RETRY_BASE_MS=0 python3 scripts/shard_soak.py \
		--short --json-out build/shard-soak-verdict.json

# The overload gate (docs/RESILIENCE.md §Degradation order): the control
# plane under fire, both halves. Phase 1 drives one replica past its
# queue bound with mixed-class clients and asserts the ladder engages in
# order and reverses — bulk sheds with the typed policy 429 (interactive
# never does), every overload response carries Retry-After >= 1 s, the
# brownout ladder applies then fully reverts (apply == revert, level 0),
# the admission cutoff restores, and the SLO layer counted the sheds in
# policy_sheds. Phase 2 puts a router with --scale-cmd over two live
# replicas plus an empty slot and asserts the autoscaler drives `up` at
# the slot under load and `down` at a live non-primary replica when the
# load stops, with the full begin/complete audit trail in the fleet
# event log. The verdict JSON lands in build/ (CI uploads it).
overload-soak:
	JAX_PLATFORMS=cpu KNN_TPU_RETRY_BASE_MS=0 python3 scripts/overload_soak.py \
		--short --json-out build/overload-soak-verdict.json

# The cost & capacity gate (docs/OBSERVABILITY.md §Cost & capacity): boot
# serve with cost accounting on and assert (1) every 200's timeline
# carries an attributed cost block, (2) attribution CONSERVES — summed
# per-class knn_cost_device_ms_total equals the measured dispatch walls
# to float tolerance, from both /debug/capacity and the Prometheus text —
# and (3) an open-loop ramp finds the real load knee within the
# documented tolerance band of the headroom model's low-load
# sustainable-QPS estimate. The verdict JSON lands in build/ (CI uploads
# it as a workflow artifact).
capacity-probe:
	JAX_PLATFORMS=cpu python3 scripts/capacity_probe.py --short \
		--json-out build/capacity-probe-verdict.json

# The workload replay gate (docs/OBSERVABILITY.md §Workload capture &
# replay): capture a seeded bursty open-loop workload (reads + an
# insert/delete stream) against a live in-process mutable serving stack,
# replay it against a pristine byte-identical twin, and assert — zero
# read/mutation errors, every replayed mutation on its captured
# mutation_seq, ZERO answer divergences at matching index_version/
# mutation_seq (bit-identical digests), and the what-if simulator's
# predicted p50 for the live policy within the documented band of the
# measured replay p50. The verdict JSON (including a candidate-policy
# frontier) lands in build/ (CI uploads it as a workflow artifact).
replay-gate:
	JAX_PLATFORMS=cpu python3 scripts/replay_gate.py \
		--json-out build/replay-gate-verdict.json

bench:
	python3 bench.py

# The perf-regression gate (docs/OBSERVABILITY.md §Device & fleet):
# measure the CPU-runnable gate record (bench.bench_gate_config — medium
# predict/kneighbors walls, serving c8 p50, ingest) and compare it
# against this environment's committed baseline with the best-of-mins +
# MAD-tolerance rule (knn_tpu/obs/regress.py). No baseline for this
# environment -> unarmed pass with a candidate record saved; refresh a
# baseline with `python3 scripts/bench_gate.py --write-baseline`. The
# verdict JSON lands in build/ (CI uploads it as a workflow artifact).
bench-gate:
	JAX_PLATFORMS=cpu python3 scripts/bench_gate.py \
		--out build/bench_gate_verdict.json

parity:
	python3 scripts/parity_report.py

device-parity:
	python3 scripts/device_parity_sweep.py

ref-diff:
	python3 scripts/reference_differential.py

clean:
	rm -rf $(LIB_DIR) main multi-thread mpi tpu build/fixtures
	rm -f $(FIXTURES)
	-rmdir datasets 2>/dev/null
