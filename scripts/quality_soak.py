"""Quality-soak gate (`make quality-soak`): shadow scoring under fire — the
answer-quality acceptance run (docs/OBSERVABILITY.md §Quality & drift).

Two phases prove two halves of the contract:

**Phase 1 — no false alarms.** Boot `knn_tpu serve` with shadow scoring at
rate 1.0 and a seeded fault burst armed (``KNN_TPU_FAULTS=serve.dispatch=N``
with tight breaker knobs — the chaos-soak recipe), hammer it with
concurrent closed-loop clients through the burst and the breaker's
open→re-close cycle. Every ladder rung is EXACT, so whatever rung answered
— fast, degraded, or breaker-short-circuited — the recall SLI must hold
exactly 1.0: zero divergence on every rung the soak exercised, quality
burn rate pinned at 0. Any divergence here is a real bug, not noise.

**Phase 2 — real corruption detected and localized.** Send SIGUSR2 (the
test-only hook, armed by ``KNN_TPU_TEST_QUALITY_CORRUPT`` at boot): the
batcher starts serving neighbor indices rotated by one train row — every
response still 200, availability/latency/fast-rung all green, predictions
silently wrong. The gate asserts the shadow scorer catches it: the
``quality`` burn rate rises, ``knn_quality_divergence_total`` counts
neighbors-kind divergence, and ``/debug/quality`` localizes it to the
answering rung — the detection that will catch a bad approximate rung
before ROADMAP item 4 ships one.

Plus the latency half of the acceptance: per-request p50 measured by the
phase-1 clients (shadow ON, rate 1.0) is recorded in the verdict JSON
alongside a shadow-off reference run, and the gate asserts the shadow path
never produced a non-200 of its own — the provably-never-blocks contract
(the noise-bounded p50 comparison itself lives in bench.py's
``c8_shadow_p50_ms`` row, where trials repeat enough to bound variance).

Exit 0 when every invariant holds; 1 with a diagnosis. stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 120


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: ~6 s fault-burst window")
    p.add_argument("--window-s", type=float, default=None)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--faults", type=int, default=None,
                   help="KNN_TPU_FAULTS=serve.dispatch=<N> burst size")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--json-out", default=None, metavar="FILE")
    args = p.parse_args()
    if args.window_s is None:
        args.window_s = 6.0 if args.short else 15.0
    if args.faults is None:
        args.faults = 12 if args.short else 25
    return args


def fail(msg: str, proc=None) -> int:
    print(f"quality-soak: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    return 1


def http(base: str, path: str, payload=None, timeout=30):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"} if payload else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def boot(index: str, env: dict, extra_flags):
    proc = procgroup.popen_group(
        [sys.executable, "-m", "knn_tpu.cli", "serve", index,
         "--port", "0", "--max-batch", "8", "--max-wait-ms", "1",
         *extra_flags],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    import queue

    lines: "queue.Queue[str]" = queue.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout], daemon=True,
    ).start()
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(1.0, max(
                0.01, deadline - time.monotonic())))
        except Exception:  # noqa: BLE001 — queue.Empty
            if proc.poll() is not None:
                return proc, None
            continue
        m = READY_RE.search(line)
        if m:
            print(f"quality-soak: server: {line.rstrip()}")
            return proc, m.group(1)
    return proc, None


def run_clients(base, rows, n_clients, stop, lats, lock, violations):
    def loop(cid):
        q = len(rows)
        i = cid
        mine = []
        while not stop.is_set():
            lo = (3 * i) % (q - 2)
            i += 1
            t0 = time.monotonic()
            try:
                st, body = http(base, "/predict",
                                {"instances": rows[lo:lo + 2].tolist()})
            except Exception as e:  # noqa: BLE001 — recorded
                with lock:
                    violations.append(f"client {cid} transport error: {e}")
                continue
            mine.append((time.monotonic() - t0) * 1e3)
            if st == 500:
                with lock:
                    violations.append(f"client {cid}: 500: {body[:200]}")
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=loop, args=(c,), daemon=True)
               for c in range(n_clients)]
    for t in threads:
        t.start()
    return threads


def quality_doc(base):
    st, body = http(base, "/debug/quality", timeout=30)
    if st != 200:
        raise RuntimeError(f"/debug/quality: status {st}: {body[:200]}")
    return json.loads(body)


def wait_queue_drained(base, timeout_s=30):
    """Shadow scoring is asynchronous: assertions about scored totals must
    wait for the background queue to empty."""
    deadline = time.monotonic() + timeout_s
    doc = None
    while time.monotonic() < deadline:
        doc = quality_doc(base)
        sh = doc["shadow"]
        if sh["queue_depth"] == 0 and sh["scored"] + sh["shed"] > 0:
            return doc
        time.sleep(0.2)
    return doc


def pct(vals, p):
    if not vals:
        return None
    vals = sorted(vals)
    return round(vals[min(len(vals) - 1, int(len(vals) * p / 100))], 2)


def main() -> int:
    args = parse_args()
    from tests import fixtures  # noqa: E402 — repo-root import

    d = fixtures.datasets_dir()
    train_arff = str(d / "small-train.arff")
    test_arff = str(d / "small-test.arff")

    from knn_tpu.data.arff import load_arff

    test = load_arff(test_arff)

    fault_plan = f"serve.dispatch={args.faults}:device"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KNN_TPU_RETRY_BASE_MS="0",
        KNN_TPU_FAULTS=fault_plan,
        KNN_TPU_FAULT_SEED=str(args.seed),
        KNN_TPU_BREAKER_WINDOW="8",
        KNN_TPU_BREAKER_THRESHOLD="3",
        KNN_TPU_BREAKER_COOLDOWN_MS="400",
        KNN_TPU_BREAKER_PROBES="1",
        KNN_TPU_TEST_QUALITY_CORRUPT="1",  # arm the SIGUSR2 hook
    )
    quality_flags = [
        "--shadow-rate", "1", "--drift-rate", "1",
        "--quality-queue", "16384", "--quality-seed", str(args.seed),
        "--slo-windows", "5,60",
    ]

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "3"],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        print(f"quality-soak: {build.stdout.strip()}")
        print(f"quality-soak: fault plan {fault_plan} (seed {args.seed}), "
              f"{args.clients} clients, {args.window_s:.0f} s burst window, "
              f"shadow-rate 1.0")

        proc, base = boot(index, env, quality_flags)
        if base is None:
            return fail(f"no ready banner (rc={proc.poll()})", proc)

        # -- phase 1: fault burst + degraded rungs, recall must hold 1.0 ---
        stop = threading.Event()
        lock = threading.Lock()
        lats_on: list = []
        violations: list = []
        clients = run_clients(base, test.features, args.clients, stop,
                              lats_on, lock, violations)
        breaker_opened = False
        t_end = time.monotonic() + args.window_s
        while time.monotonic() < t_end:
            try:
                _, body = http(base, "/healthz", timeout=5)
                if json.loads(body).get("breaker") == "open":
                    breaker_opened = True
            except Exception:  # noqa: BLE001 — keep polling
                pass
            time.sleep(0.05)
        # Keep load until the breaker re-closes so degraded AND recovered
        # rungs both land in the shadow sample.
        reclose_deadline = time.monotonic() + 30
        state = None
        while time.monotonic() < reclose_deadline:
            try:
                _, body = http(base, "/healthz", timeout=5)
                state = json.loads(body).get("breaker")
                if state == "closed":
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.1)
        stop.set()
        for t in clients:
            t.join(timeout=35)
            if t.is_alive():
                return fail("a phase-1 client thread hung", proc)
        if not breaker_opened:
            return fail("the fault burst never tripped the breaker — the "
                        "soak did not exercise degraded rungs", proc)
        if state != "closed":
            return fail(f"breaker did not re-close (state {state})", proc)
        if violations:
            for v in violations[:10]:
                print(f"quality-soak: VIOLATION: {v}", file=sys.stderr)
            return fail(f"{len(violations)} serving violation(s) in "
                        f"phase 1", proc)

        doc = wait_queue_drained(base)
        sh = doc["shadow"]
        if sh["scored"] < 20:
            return fail(f"too few shadow-scored requests in phase 1 "
                        f"({sh['scored']}) to trust the verdict", proc)
        rungs_seen = sorted(sh["rungs"])
        for rung, st in sh["rungs"].items():
            if st["recall"] != 1.0:
                return fail(f"recall SLI broke on EXACT rung {rung!r}: "
                            f"{st['recall']} — a real serving bug, not "
                            f"noise", proc)
            if st["divergence"]:
                return fail(f"divergence on exact rung {rung!r}: "
                            f"{st['divergence']}", proc)
        burns = doc["slo_quality"]["burn_rates"]
        if any(b > 0 for b in burns.values()):
            return fail(f"quality burn rate nonzero across an all-exact "
                        f"fault burst: {burns}", proc)
        drift = doc["drift"]
        if drift["baseline"] != "present" or drift["scores"] is None:
            return fail(f"drift baseline missing from a format-2 artifact: "
                        f"{drift}", proc)
        print(f"quality-soak: phase 1 ok — {sh['scored']} scored across "
              f"rungs {rungs_seen}, recall 1.0 everywhere, quality burn 0, "
              f"shed {sh['shed']}, drift baseline present "
              f"(max score {drift['scores']['max']})")

        # -- phase 2: corrupt the index; the scorer must catch it ----------
        proc.send_signal(signal.SIGUSR2)
        time.sleep(0.2)
        stop2 = threading.Event()
        lats2: list = []
        violations2: list = []
        clients2 = run_clients(base, test.features, args.clients, stop2,
                               lats2, lock, violations2)
        detected = None
        burn_seen = 0.0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            doc = quality_doc(base)
            burns = doc["slo_quality"]["burn_rates"]
            burn_seen = max(burn_seen,
                            max((b for b in burns.values()), default=0.0))
            div_rungs = {
                r: st["divergence"] for r, st in doc["shadow"]["rungs"].items()
                if st["divergence"].get("neighbors")
            }
            if burn_seen > 1.0 and div_rungs:
                detected = (doc, div_rungs)
                break
            time.sleep(0.2)
        stop2.set()
        for t in clients2:
            t.join(timeout=35)
            if t.is_alive():
                return fail("a phase-2 client thread hung", proc)
        if detected is None:
            return fail(f"injected index corruption NOT detected within "
                        f"30 s (peak quality burn {burn_seen})", proc)
        doc, div_rungs = detected
        rung, div = next(iter(div_rungs.items()))
        recall_after = doc["shadow"]["rungs"][rung]["recall"]
        if recall_after >= 1.0:
            return fail(f"divergence counted but recall gauge still 1.0 "
                        f"on rung {rung!r}", proc)
        print(f"quality-soak: phase 2 ok — corruption detected and "
              f"localized: rung {rung!r} recall {recall_after}, "
              f"divergence {div}, quality burn peak "
              f"{round(burn_seen, 2)}")

        # -- shutdown ------------------------------------------------------
        proc.send_signal(signal.SIGINT)
        try:
            rc = proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            return fail("server did not exit after SIGINT", proc)
        if rc != 0:
            return fail(f"server exited rc={rc} after SIGINT")

        report = {
            "quality_soak": {
                "window_s": args.window_s,
                "clients": args.clients,
                "fault_plan": fault_plan,
                "seed": args.seed,
            },
            "phase1": {
                "scored": sh["scored"],
                "shed": sh["shed"],
                "rungs_seen": rungs_seen,
                "recall_sli": 1.0,
                "quality_burn": 0.0,
                "p50_ms_shadow_on": pct(lats_on, 50),
                "p99_ms_shadow_on": pct(lats_on, 99),
                "requests": len(lats_on),
            },
            "phase2": {
                "detected": True,
                "rung": rung,
                "recall_after": recall_after,
                "divergence": div,
                "quality_burn_peak": round(burn_seen, 3),
            },
            "drift": {"baseline": "present"},
        }
        out = json.dumps(report, indent=2)
        print(out)
        if args.json_out:
            Path(args.json_out).parent.mkdir(parents=True, exist_ok=True)
            Path(args.json_out).write_text(out + "\n")
        print("quality-soak: PASS")
        return 0


if __name__ == "__main__":
    sys.exit(main())
