"""Capacity probe (`make capacity-probe`): find a live replica's load knee
and cross-check the headroom model against it.

The capacity layer (docs/OBSERVABILITY.md §Cost & capacity) REPORTS a
sustainable-QPS estimate from its fitted dispatch-cost model; this gate
proves the estimate means something by measuring the real knee:

1. **Boot** `knn_tpu serve --cost-accounting on` over the large fixture
   index (big enough that one dispatch costs tens of ms on a CPU box, so
   the knee sits at a rate a Python client can comfortably exceed).
2. **Low load** — a trickle of tagged requests, then:
   - every 200's flight-recorder timeline must carry a ``cost`` block
     with the request's class and attributed device-ms;
   - ``GET /debug/capacity`` must report a positive ``sustainable_qps``
     (the headroom estimate under test, read at LOW load — before the
     ramp teaches the model anything about saturation).
3. **Ramp** — open-loop arrival (a scheduler fires requests on a clock,
   never waiting for responses) at geometrically increasing rates until
   the knee: sustained shedding (429s), p99 blowup vs the low-rate
   baseline, or the client's schedule collapsing under ballooned
   latencies. The measured knee is the geometric mean of the last clean
   rate and the first saturated rate.
4. **Verdict** — the measured knee must fall within the tolerance band of
   the low-load estimate, attribution conservation must hold over the
   WHOLE run (sum of per-class ``knn_cost_device_ms_total`` equals
   ``knn_cost_dispatch_wall_ms_total`` to float tolerance — checked from
   both ``/debug/capacity`` and the Prometheus text), and the server must
   drain cleanly. The verdict JSON is the CI artifact.

**Tolerance band** (the documented contract): measured_knee / estimate in
``[0.2, 3.0]`` by default. The band is deliberately wide in CI-short mode:
on a shared-core CPU box the probe client, the HTTP handlers, JSON
parsing, and the XLA dispatch all compete for the same two vCPUs, so the
real knee lands well below the pure dispatch-model estimate — the gate
asserts the model is order-of-magnitude honest plus margin, which is what
replica-count sizing needs. On dedicated serving hardware tighten with
``--band-lo/--band-hi``.

Exit 0 when every invariant holds; 1 with a diagnosis. stdlib-only client.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import queue
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 180

#: Rows per request == max_batch: each request is one full dispatch, so
#: the knee in requests/s is ~1/w(max_batch) — low enough for a Python
#: client to exceed 3x over even on a 2-vCPU box.
REQUEST_ROWS = 128
MAX_BATCH = 128

SHED_FRAC_KNEE = 0.05
MISSED_FRAC_KNEE = 0.25
P99_BLOWUP_FACTOR = 4.0
P99_BLOWUP_FLOOR_MS = 500.0


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: 1.2 s ramp steps, wide [0.2, 3.0] band")
    p.add_argument("--step-s", type=float, default=None,
                   help="seconds per ramp step (default 1.2 short / 3.0)")
    p.add_argument("--band-lo", type=float, default=None,
                   help="lower bound on measured_knee/estimate "
                   "(default 0.2)")
    p.add_argument("--band-hi", type=float, default=None,
                   help="upper bound on measured_knee/estimate "
                   "(default 3.0)")
    p.add_argument("--seed", type=int, default=7,
                   help="row-selection seed (deterministic payloads)")
    p.add_argument("--workers", type=int, default=16,
                   help="client worker threads for the open-loop generator")
    p.add_argument("--json-out", default=None, metavar="FILE")
    args = p.parse_args()
    if args.step_s is None:
        args.step_s = 1.2 if args.short else 3.0
    if args.band_lo is None:
        args.band_lo = 0.2
    if args.band_hi is None:
        args.band_hi = 3.0
    return args


def fail(msg: str, proc=None) -> int:
    print(f"capacity-probe: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    return 1


def http(base: str, path: str, payload_bytes=None, headers=None,
         timeout=60):
    req = urllib.request.Request(
        base + path, data=payload_bytes,
        headers={"Content-Type": "application/json", **(headers or {})}
        if payload_bytes is not None else (headers or {}),
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def boot(index: str, env: dict, extra_flags):
    proc = procgroup.popen_group(
        [sys.executable, "-m", "knn_tpu.cli", "serve", index,
         "--port", "0", *extra_flags],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    lines: "queue.Queue[str]" = queue.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout], daemon=True,
    ).start()
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(1.0, max(
                0.01, deadline - time.monotonic())))
        except queue.Empty:
            if proc.poll() is not None:
                return proc, None
            continue
        m = READY_RE.search(line)
        if m:
            print(f"capacity-probe: server: {line.rstrip()}")
            return proc, m.group(1)
    return proc, None


class OpenLoopClient:
    """Fire requests on a clock, never waiting for responses: a scheduler
    thread enqueues at the target rate, a bounded worker pool executes.
    When the workers fall behind (server latencies ballooned past what
    the pool can absorb), scheduled fires are counted as ``missed`` —
    saturation evidence, not silently dropped load."""

    def __init__(self, base: str, payloads, workers: int):
        self.base = base
        self.payloads = payloads
        self._jobs: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._results: list = []
        self._workers = [
            threading.Thread(target=self._work, daemon=True)
            for _ in range(workers)
        ]
        for w in self._workers:
            w.start()
        self.max_backlog = 2 * workers

    def _work(self):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            i = job
            t0 = time.monotonic()
            try:
                st, _ = http(self.base, "/predict",
                             self.payloads[i % len(self.payloads)],
                             headers={"x-knn-class": "ramp"}, timeout=60)
            except Exception:  # noqa: BLE001 — transport error = saturation
                st = -1
            ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                self._results.append((st, ms))

    def run_step(self, rate_qps: float, step_s: float) -> dict:
        """One open-loop step at ``rate_qps`` for ``step_s`` seconds;
        blocks until every fired request completed (so per-step latencies
        include the queue the step itself built)."""
        with self._lock:
            self._results.clear()
        fired = missed = 0
        interval = 1.0 / rate_qps
        t_next, t_end = time.monotonic(), time.monotonic() + step_s
        i = 0
        while time.monotonic() < t_end:
            now = time.monotonic()
            if now < t_next:
                time.sleep(min(interval, t_next - now))
                continue
            t_next += interval
            if self._jobs.qsize() > self.max_backlog:
                missed += 1  # the pool is drowning: saturation, counted
            else:
                self._jobs.put(i)
                fired += 1
            i += 1
        drain_deadline = time.monotonic() + 90
        while time.monotonic() < drain_deadline:
            with self._lock:
                done = len(self._results)
            if done >= fired:
                break
            time.sleep(0.05)
        with self._lock:
            results = list(self._results)
        lats_ok = sorted(ms for st, ms in results if st == 200)
        n429 = sum(1 for st, _ in results if st == 429)
        nbad = sum(1 for st, _ in results if st not in (200, 429))
        total = max(1, fired + missed)

        def pct(vals, p):
            if not vals:
                return None
            return round(vals[min(len(vals) - 1,
                                  int(len(vals) * p / 100))], 1)

        return {
            "rate_qps": round(rate_qps, 2),
            "fired": fired,
            "missed": missed,
            "ok": len(lats_ok),
            "shed_429": n429,
            "other": nbad,
            "shed_frac": round(n429 / max(1, len(results)), 4),
            "missed_frac": round(missed / total, 4),
            "p50_ms": pct(lats_ok, 50),
            "p99_ms": pct(lats_ok, 99),
        }

    def close(self):
        for _ in self._workers:
            self._jobs.put(None)


def prom_cost_sums(metrics_text: str):
    """``(sum of knn_cost_device_ms_total samples, the
    knn_cost_dispatch_wall_ms_total sample)`` from the Prometheus text."""
    dev = wall = 0.0
    for line in metrics_text.splitlines():
        if line.startswith("knn_cost_device_ms_total{"):
            dev += float(line.rsplit(" ", 1)[1])
        elif line.startswith("knn_cost_dispatch_wall_ms_total"):
            wall = float(line.rsplit(" ", 1)[1])
    return dev, wall


def main() -> int:
    args = parse_args()
    from tests import fixtures  # noqa: E402 — repo-root import

    d = fixtures.datasets_dir()
    train_arff = str(d / "large-train.arff")
    test_arff = str(d / "large-test.arff")

    from knn_tpu.data.arff import load_arff

    test = load_arff(test_arff)
    rng_lo = (args.seed * 131) % max(1, test.num_instances - REQUEST_ROWS)
    # Four precomputed payloads (rotated per fire): the client's JSON
    # serialization cost must not be part of the measured knee.
    payloads = []
    for v in range(4):
        lo = (rng_lo + v * 17) % max(1, test.num_instances - REQUEST_ROWS)
        rows = test.features[lo:lo + REQUEST_ROWS].tolist()
        # Class rides the x-knn-class header per phase ("probe" low-load,
        # "ramp" during the ramp), so one payload set serves both.
        payloads.append(json.dumps(
            {"instances": rows}, separators=(",", ":"),
        ).encode())

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    serve_flags = [
        "--cost-accounting", "on",
        "--max-batch", str(MAX_BATCH),
        "--max-wait-ms", "2",
        "--max-queue-rows", str(8 * MAX_BATCH),
        "--capacity-window-s", "30",
        "--flight-recorder-size", "512",
    ]

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "5"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        print(f"capacity-probe: {build.stdout.strip()}")

        proc, base = boot(index, env, serve_flags)
        if base is None:
            return fail(f"no ready banner (rc={proc.poll()})", proc)

        # -- phase 1: low load — cost blocks + the headroom estimate ------
        cost_ids = []
        for i in range(6):
            rid = f"probe-cost-{i}"
            st, body = http(
                base, "/predict", payloads[i % len(payloads)],
                headers={"x-request-id": rid, "x-knn-class": "probe"},
            )
            if st != 200:
                return fail(f"low-load request {rid} -> {st}: "
                            f"{body[:200]}", proc)
            cost_ids.append(rid)
            time.sleep(0.3)
        missing = []
        for rid in cost_ids:
            st, body = http(base, f"/debug/requests?id={rid}")
            if st != 200:
                return fail(f"/debug/requests?id={rid} -> {st}", proc)
            tl = json.loads(body)["requests"][0]
            cost = tl.get("cost")
            if (not cost or cost.get("device_ms", 0) <= 0
                    or cost.get("class") != "probe"):
                missing.append((rid, cost))
        if missing:
            return fail(f"200 timelines WITHOUT a usable cost block: "
                        f"{missing}", proc)
        print(f"capacity-probe: {len(cost_ids)}/{len(cost_ids)} low-load "
              f"200s carry attributed cost blocks (class 'probe')")

        st, body = http(base, "/debug/capacity")
        if st != 200:
            return fail(f"/debug/capacity -> {st}: {body[:200]}", proc)
        cap_doc = json.loads(body)
        estimate = (cap_doc.get("capacity") or {}).get("sustainable_qps")
        model = (cap_doc.get("capacity") or {}).get("dispatch_model")
        if not estimate or estimate <= 0:
            return fail(f"no positive sustainable_qps estimate at low "
                        f"load: {cap_doc.get('capacity')}", proc)
        print(f"capacity-probe: low-load headroom estimate "
              f"{estimate:.1f} req/s of {REQUEST_ROWS}-row requests "
              f"(dispatch model {model})")

        # -- phase 2: the open-loop ramp -----------------------------------
        client = OpenLoopClient(base, payloads, args.workers)
        steps = []
        knee = None
        base_p99 = None
        rate = max(1.0, estimate * 0.15)
        max_rate = estimate * args.band_hi * 1.5
        try:
            while rate <= max_rate:
                step = client.run_step(rate, args.step_s)
                steps.append(step)
                if base_p99 is None and step["p99_ms"] is not None:
                    base_p99 = step["p99_ms"]
                blowup = (
                    base_p99 is not None and step["p99_ms"] is not None
                    and step["p99_ms"] > max(
                        P99_BLOWUP_FACTOR * base_p99,
                        base_p99 + P99_BLOWUP_FLOOR_MS)
                )
                saturated = (
                    step["shed_frac"] > SHED_FRAC_KNEE
                    or step["missed_frac"] > MISSED_FRAC_KNEE
                    or blowup
                )
                reason = ("shed" if step["shed_frac"] > SHED_FRAC_KNEE
                          else "client_schedule_collapse"
                          if step["missed_frac"] > MISSED_FRAC_KNEE
                          else "p99_blowup" if blowup else None)
                print(f"capacity-probe: step {step['rate_qps']:>7.2f} q/s: "
                      f"ok {step['ok']}, shed {step['shed_429']}, missed "
                      f"{step['missed']}, p50 {step['p50_ms']} ms, p99 "
                      f"{step['p99_ms']} ms"
                      + (f" -> KNEE ({reason})" if saturated else ""))
                if saturated:
                    prev = steps[-2]["rate_qps"] if len(steps) > 1 else rate
                    knee = {
                        "measured_qps": round(math.sqrt(prev * rate), 2),
                        "reason": reason,
                        "last_clean_qps": prev,
                        "first_saturated_qps": step["rate_qps"],
                    }
                    break
                rate *= 1.5
        finally:
            client.close()
        if knee is None:
            return fail(
                f"no knee found up to {max_rate:.1f} q/s "
                f"({args.band_hi}x the {estimate:.1f} q/s estimate +50% — "
                f"the headroom model underestimates beyond the band)",
                proc,
            )

        # -- phase 3: conservation over the whole run ----------------------
        # Quiesce first: requests the saturated step abandoned client-side
        # can still be dispatching server-side, and the per-class device-ms
        # counter adds are not atomic with the wall-counter add — the
        # Prometheus-text invariant below is only true of a server at
        # rest. Poll the cost totals until two consecutive reads agree.
        totals, prev_wall = None, -1.0
        quiesce_deadline = time.monotonic() + 60
        while time.monotonic() < quiesce_deadline:
            st, body = http(base, "/debug/capacity")
            if st != 200:
                return fail(f"/debug/capacity -> {st} post-ramp", proc)
            totals = json.loads(body)["cost"]["totals"]
            if totals["dispatch_wall_ms"] == prev_wall:
                break
            prev_wall = totals["dispatch_wall_ms"]
            time.sleep(0.5)
        else:
            return fail("server never quiesced after the ramp (cost "
                        "totals still moving after 60 s)", proc)
        attributed, wall = totals["attributed_ms"], totals["dispatch_wall_ms"]
        if wall <= 0 or not math.isclose(attributed, wall, rel_tol=1e-6):
            return fail(f"attribution conservation broke: attributed "
                        f"{attributed} ms vs measured walls {wall} ms",
                        proc)
        st, metrics_text = http(base, "/metrics")
        dev_sum, wall_metric = prom_cost_sums(metrics_text)
        if wall_metric <= 0 or not math.isclose(dev_sum, wall_metric,
                                                rel_tol=1e-6):
            return fail(f"metric-level conservation broke: "
                        f"sum(knn_cost_device_ms_total)={dev_sum} vs "
                        f"knn_cost_dispatch_wall_ms_total={wall_metric}",
                        proc)
        # Every 200 the recorder still holds must carry a cost block.
        st, body = http(base, "/debug/requests?n=50")
        sampled = json.loads(body)["requests"]
        bad = [tl["request_id"] for tl in sampled
               if tl.get("outcome") == "ok" and not tl.get("cost")]
        if bad:
            return fail(f"{len(bad)} 200 timeline(s) without a cost block "
                        f"post-ramp: {bad[:5]}", proc)
        classes = set(json.loads(
            http(base, "/debug/capacity")[1])["cost"]["classes"])
        print(f"capacity-probe: conservation ok ({attributed:.3f} of "
              f"{wall:.3f} ms attributed; metrics agree to 1e-6), "
              f"{len(sampled)} sampled timelines all costed, classes "
              f"{sorted(classes)}")

        # -- verdict -------------------------------------------------------
        ratio = knee["measured_qps"] / estimate
        within = args.band_lo <= ratio <= args.band_hi
        report = {
            "capacity_probe": {
                "request_rows": REQUEST_ROWS,
                "max_batch": MAX_BATCH,
                "step_s": args.step_s,
                "workers": args.workers,
                "seed": args.seed,
            },
            "estimate": {
                "sustainable_qps": estimate,
                "dispatch_model": model,
            },
            "knee": {**knee, "ratio": round(ratio, 3),
                     "band": [args.band_lo, args.band_hi],
                     "within_band": within},
            "ramp": steps,
            "conservation": {
                "attributed_ms": attributed,
                "dispatch_wall_ms": wall,
                "metric_device_ms_sum": round(dev_sum, 6),
                "ok": True,
            },
            "cost_blocks": {"checked": len(cost_ids) + len(sampled),
                            "ok": True},
            "classes_seen": sorted(classes),
        }

        # -- shutdown ------------------------------------------------------
        proc.send_signal(signal.SIGINT)
        try:
            rc = proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            return fail("server did not exit after SIGINT", proc)
        if rc != 0:
            return fail(f"server exited rc={rc} after SIGINT")

        out = json.dumps(report, indent=2)
        print(out)
        if args.json_out:
            Path(args.json_out).parent.mkdir(parents=True, exist_ok=True)
            Path(args.json_out).write_text(out + "\n")
        if not within:
            return fail(
                f"measured knee {knee['measured_qps']} q/s is "
                f"{ratio:.2f}x the {estimate:.1f} q/s headroom estimate — "
                f"outside the documented [{args.band_lo}, {args.band_hi}] "
                f"band"
            )
        print(f"capacity-probe: PASS (knee {knee['measured_qps']} q/s = "
              f"{ratio:.2f}x the model's {estimate:.1f} q/s, inside "
              f"[{args.band_lo}, {args.band_hi}])")
        return 0


if __name__ == "__main__":
    sys.exit(main())
