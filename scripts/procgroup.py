"""Process-group hygiene for the soak/gate scripts (PR 13's noted flake).

Every gate that boots `knn_tpu serve` (or `route`) as a subprocess MUST
spawn it through :func:`popen_group`: the child gets its own session (=
its own process group), and an ``atexit`` sweep SIGKILLs every group
that is still alive — so an assertion failure, an uncaught exception, or
a plain ``sys.exit(1)`` mid-gate can never strand a serving process that
skews the next bench-gate run on a shared box.

Deliberate in-gate kills keep working unchanged: ``proc.kill()`` /
``proc.send_signal`` target the child directly, and
:func:`kill_group` SIGKILLs a whole group on demand (what the fleet soak
uses for its crash-stops). The sweep is a no-op for groups that already
exited cleanly (``ProcessLookupError`` is the success case).
"""

from __future__ import annotations

import atexit
import os
import signal
import subprocess

_SPAWNED: "list[subprocess.Popen]" = []


def popen_group(cmd, **kwargs) -> subprocess.Popen:
    """``subprocess.Popen`` in a fresh session/process group, registered
    for the atexit sweep. Same signature as Popen otherwise."""
    kwargs.setdefault("start_new_session", True)
    proc = subprocess.Popen(cmd, **kwargs)
    _SPAWNED.append(proc)
    return proc


def kill_group(proc: subprocess.Popen,
               sig: int = signal.SIGKILL) -> None:
    """Signal the child's WHOLE process group (with start_new_session
    the group id is the child's pid). Already-gone groups are a no-op."""
    try:
        os.killpg(proc.pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        pass


def _sweep() -> None:
    # Even for a leader that exited, sweep the group: a grandchild may
    # linger in it (killpg on an empty group is the no-op success case).
    for proc in _SPAWNED:
        kill_group(proc)


atexit.register(_sweep)
