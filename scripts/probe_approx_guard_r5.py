"""r5 on-device validation of the approx sampled-recall guard.

Runs ``predict_arrays(approx=True)`` on the 33x-tiled set and a random set
of the same shape, printing the guard's sampled recall and whether the
fallback warning fires. MEASURED OUTCOME (r5, v5e): the tiled set's
same-values recall is ~0.99 — r4's alarming 0.002 was approx-on-matmul
indices scored against exact-STRIPE (subtraction-form) indices, i.e. tie
ORDER divergence between distance forms on 33-way-duplicate rows, which
cannot change predictions (duplicates share labels). The worst genuine
selection degradation found is ~0.92 with CONTIGUOUS duplicates
(np.repeat layout — duplicates collide in approx_max_k's positional
bins). The guard therefore measures approx-vs-exact on the SAME distance
values (what approx selection actually loses) and fires only on real
collapse; the CPU suite pins the fallback plumbing with an injected low
recall (tests/test_approx_guard.py).
"""

import sys
import warnings
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from knn_tpu.backends.tpu import predict_arrays, sampled_approx_recall
from knn_tpu.data.arff import load_arff

REF = Path("/root/reference/datasets")


def main():
    train = load_arff(str(REF / "large-train.arff"))
    test = load_arff(str(REF / "large-test.arff"))
    rng = np.random.default_rng(0)
    tiled = np.tile(train.features, (33, 1))
    tiled += 1e-3 * rng.standard_normal(tiled.shape, dtype=np.float32)
    tiled_y = np.tile(train.labels, 33)
    k, c = 10, train.num_classes

    r_tiled = sampled_approx_recall(tiled, test.features, k, 0.95)
    print(f"sampled recall, 33x-tiled train: {r_tiled:.4f}")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        preds_guarded = predict_arrays(
            tiled, tiled_y, test.features, k, c, approx=True, engine="xla",
        )
    fired = [x for x in w if issubclass(x.category, RuntimeWarning)]
    print(f"guard warning fired: {bool(fired)}"
          + (f" ({fired[0].message})" if fired else ""))
    exact = predict_arrays(tiled, tiled_y, test.features, k, c, engine="xla")
    print(f"guarded predictions == exact: {np.array_equal(preds_guarded, exact)}")

    rnd = rng.random(tiled.shape, np.float32)
    r_rnd = sampled_approx_recall(rnd, test.features, k, 0.95)
    print(f"sampled recall, random train:    {r_rnd:.4f}")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        predict_arrays(
            rnd, tiled_y, test.features, k, c, approx=True, engine="xla",
        )
    fired = [x for x in w if issubclass(x.category, RuntimeWarning)]
    print(f"guard stayed silent on random data: {not fired}")


if __name__ == "__main__":
    main()
