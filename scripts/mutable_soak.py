"""Mutable-soak gate (`make mutable-soak`): online mutation held to its
contracts (docs/INDEXES.md §Mutable tier).

Four phases, every one against a real `knn_tpu serve --mutable on`
subprocess:

**Phase 1 — oracle replay under chaos.** Concurrent writers (inserts +
deletes) and readers under the chaos fault burst
(``KNN_TPU_FAULTS=serve.dispatch=N`` — the degradation ladder is
exercised mid-mutation). Every read carries its ``mutation_seq`` sequence
point; the gate replays the acknowledged mutation history to exactly that
seq through an independent fold/merge mirror and requires the served
indices BIT-IDENTICAL to the replay (the selection/tie-order truth — the
same contract every ladder rung is pinned to) with distances inside
float32 ulp of it (the rung distance forms differ in the last ulp) — on
every rung the burst pushed the ladder through. Freshness p99 (write-ack
to
visible-in-snapshots, /healthz) must stay under the bound.

**Phase 2 — atomic compaction swap under load.** Writers and readers
stay hot while ``POST /admin/compact`` folds the tier into a fresh
generation. Every response must carry exactly the old or the new
``index_version`` (never a mix, never a 500), reads under BOTH versions
must replay bit-identical against their own generation's positional
space, and writes acknowledged mid-compaction must survive the swap
(the fresh-epoch re-anchor).

**Phase 3 — rollback.** With the seeded ``mutable.compact`` fault armed
(``once``), the first compaction attempt fails AFTER fold+warm: the gate
requires HTTP 500 with ``rolled_back: true``, the old generation still
serving, every acknowledged write still answering, and the NEXT attempt
(fault exhausted) succeeding.

**Phase 4 — crash recovery.** SIGKILL the server while a compaction is
in flight, reboot over the same artifact directory, and require zero
acknowledged writes lost: the rebooted ``mutation_seq`` equals the last
acknowledged seq and a fresh read replays bit-identical (whether the
kill landed before or after the CURRENT.json commit point).

Exit 0 when every invariant holds; 1 with a diagnosis.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 180


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: ~6 s load windows")
    p.add_argument("--window-s", type=float, default=None)
    p.add_argument("--writers", type=int, default=2)
    p.add_argument("--readers", type=int, default=2)
    p.add_argument("--rows", type=int, default=4,
                   help="query rows per read request")
    p.add_argument("--faults", type=int, default=3,
                   help="phase-1 serve.dispatch fault burst size")
    p.add_argument("--freshness-p99-ms", type=float, default=2000.0)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--json-out", default=None, metavar="FILE")
    args = p.parse_args()
    if args.window_s is None:
        args.window_s = 6.0 if args.short else 15.0
    return args


def fail(msg: str, *procs) -> int:
    print(f"mutable-soak: FAIL: {msg}", file=sys.stderr)
    for proc in procs:
        if proc is not None and proc.poll() is None:
            proc.kill()
    return 1


def http(base: str, path: str, payload=None, timeout=60):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"} if payload else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def boot(index: str, env: dict, extra_flags=()):
    proc = procgroup.popen_group(
        [sys.executable, "-m", "knn_tpu.cli", "serve", index,
         "--port", "0", "--max-batch", "32", "--max-wait-ms", "1",
         "--mutable", "on", "--compact-interval-s", "0",
         "--compact-threshold", "100000", *extra_flags],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    import queue

    lines: "queue.Queue[str]" = queue.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout], daemon=True,
    ).start()
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(1.0, max(
                0.01, deadline - time.monotonic())))
        except Exception:  # noqa: BLE001 — queue.Empty
            if proc.poll() is not None:
                return proc, None
            continue
        m = READY_RE.search(line)
        if m:
            print(f"mutable-soak: server: {line.rstrip()}")
            return proc, m.group(1)
    return proc, None


def shutdown(proc) -> "int | None":
    proc.send_signal(signal.SIGINT)
    try:
        return proc.wait(timeout=20)
    except subprocess.TimeoutExpired:
        proc.kill()
        return None


def healthz(base) -> dict:
    st, body = http(base, "/healthz")
    if st != 200:
        raise RuntimeError(f"/healthz: status {st}")
    return json.loads(body)


# -- the replay mirror ------------------------------------------------------


class Mirror:
    """Independent oracle replay of the acknowledged mutation history.

    ``history``: seq -> ("insert", rows[f32]) | ("delete", [positional
    ids]) — exactly what the server acknowledged, keyed by the seq it
    acknowledged with (mutations are serialized, so seqs are a total
    order). ``folds``: the seqs at which compactions committed, in order
    — the fold is a deterministic function of the history (survivor
    order: base positions ascending, then live delta rows in insert
    order), so each generation's positional space is re-derivable."""

    def __init__(self, base_features, k, metric):
        import numpy as np

        self.np = np
        self.base0 = np.asarray(base_features, np.float32)
        self.k = k
        self.metric = metric
        self.lock = threading.Lock()
        self.history: "dict[int, tuple]" = {}
        self._gen_cache: "dict[tuple, object]" = {(): self.base0}

    def ack(self, seq: int, op: str, payload) -> None:
        with self.lock:
            if seq in self.history:
                raise AssertionError(
                    f"two mutations acknowledged with seq {seq} — the "
                    f"serialization contract is broken")
            self.history[seq] = (op, payload)

    def _window(self, lo: int, hi: int):
        with self.lock:
            seqs = sorted(s for s in self.history if lo < s <= hi)
            missing = [s for s in range(lo + 1, hi + 1) if s not in
                       self.history]
            if missing:
                # A seq we never saw an ack for (e.g. its HTTP response
                # raced a kill): the replay cannot cover this window.
                raise KeyError(f"unacknowledged seq(s) {missing[:5]} in "
                               f"({lo}, {hi}]")
            return [(s, *self.history[s]) for s in seqs]

    def base_at(self, folds: "tuple[int, ...]"):
        """The generation's base features after folding the history at
        each seq in ``folds`` (cached — folds repeat across reads)."""
        np = self.np
        if folds in self._gen_cache:
            return self._gen_cache[folds]
        base = self.base_at(folds[:-1])
        lo = folds[-2] if len(folds) > 1 else 0
        tomb = set()
        ins = []
        for _s, op, payload in self._window(lo, folds[-1]):
            if op == "insert":
                ins.append(payload)
            else:
                tomb.update(payload)
        delta = (np.concatenate(ins) if ins
                 else np.zeros((0, base.shape[1]), np.float32))
        base_n = base.shape[0]
        keep_base = [p for p in range(base_n) if p not in tomb]
        keep_delta = [j for j in range(delta.shape[0])
                      if base_n + j not in tomb]
        folded = np.concatenate([base[keep_base], delta[keep_delta]])
        self._gen_cache[folds] = folded
        return folded

    def expect(self, folds: "tuple[int, ...]", seq: int, queries):
        """The bit-exact answer the live view at ``seq`` (over the
        generation ``folds`` names) must serve."""
        import numpy as np

        from knn_tpu.backends.oracle import oracle_kneighbors
        from knn_tpu.mutable.state import MutableView, merge_candidates

        base = self.base_at(folds)
        lo = folds[-1] if folds else 0
        ins, tomb = [], set()
        for _s, op, payload in self._window(lo, seq):
            if op == "insert":
                ins.append(payload)
            else:
                tomb.update(payload)
        delta = (np.concatenate(ins) if ins
                 else np.zeros((0, base.shape[1]), np.float32))
        count = delta.shape[0]
        base_n = base.shape[0]
        q = np.asarray(queries, np.float32)
        base_d, base_i = oracle_kneighbors(base, q, self.k, self.metric)
        if count == 0 and not tomb:
            return np.asarray(base_d, np.float32), np.asarray(base_i)
        view = MutableView(
            features=delta, values=np.zeros(count, np.float32),
            stable=np.zeros(count, np.int64), count=count,
            tomb_pos=frozenset(tomb),
            tomb_base=np.array(sorted(p for p in tomb if p < base_n),
                               np.int64),
            tomb_delta_slots=np.array(
                sorted(p - base_n for p in tomb if p >= base_n), np.int64),
            seq=seq, base_n=base_n, generation=len(folds),
        )
        d, i = merge_candidates(
            view, q, base_d, base_i, self.k, self.metric,
            lambda f, kw: oracle_kneighbors(base, f, kw, self.metric),
        )
        return np.asarray(d, np.float32), np.asarray(i)

    def verify_reads(self, reads, version_folds, where: str):
        """``reads``: (instances, seq, version, distances, indices);
        ``version_folds``: index_version -> folds tuple. Returns the
        list of violation strings (empty = every read bit-identical)."""
        import numpy as np

        bad = []
        for n, (inst, seq, version, dists, idx) in enumerate(reads):
            if version not in version_folds:
                bad.append(f"{where} read {n}: unknown index_version "
                           f"{version!r}")
                continue
            want_d, want_i = self.expect(version_folds[version], seq, inst)
            got_d = np.asarray(dists, np.float64).astype(np.float32)
            got_i = np.asarray(idx, np.int64)
            # Indices BIT-identical (the selection/tie-order truth, the
            # same contract every ladder rung is pinned to); distances
            # within float32 ulp of the replay (the rung distance FORMS
            # differ in the last ulp — tests/test_serve_resilience.py's
            # degrades_with_identical_indices is the existing precedent).
            if not (np.array_equal(got_i, want_i)
                    and np.allclose(got_d, want_d.astype(np.float32),
                                    rtol=1e-5, atol=1e-5)):
                bad.append(
                    f"{where} read {n} (seq {seq}, version {version}): "
                    f"served {got_i.tolist()}/{got_d.tolist()} != replay "
                    f"{want_i.tolist()}/{want_d.tolist()}")
                if len(bad) >= 3:
                    break
        return bad


# -- load generation --------------------------------------------------------


class Load:
    """Concurrent writers + readers against one server; collects the
    acknowledged history into the mirror and every read for replay."""

    def __init__(self, base, mirror, test_x, num_classes, args, *,
                 deletes: bool, seed: int):
        import numpy as np

        self.base = base
        self.mirror = mirror
        self.test_x = test_x
        self.num_classes = num_classes
        self.args = args
        self.deletes = deletes
        self.rng = np.random.default_rng(seed)
        self.stop = threading.Event()
        self.lock = threading.Lock()
        self.reads: list = []
        self.violations: list = []
        self.acked_seqs: list = []
        self.my_live_ids: list = []  # positional ids we may delete
        self.versions_seen: set = set()
        self.threads: list = []

    def _writer(self, wid: int):
        import numpy as np

        rng = np.random.default_rng(self.args.seed * 1000 + wid)
        d = self.test_x.shape[1]
        while not self.stop.is_set():
            do_delete = False
            if self.deletes:
                with self.lock:
                    do_delete = (len(self.my_live_ids) > 4
                                 and rng.random() < 0.3)
            try:
                if do_delete:
                    with self.lock:
                        pick = self.my_live_ids.pop(
                            int(rng.integers(len(self.my_live_ids))))
                    st, body = http(self.base, "/delete", {"ids": [pick]})
                    if st == 200:
                        doc = json.loads(body)
                        self.mirror.ack(doc["seq"], "delete", [pick])
                        with self.lock:
                            self.acked_seqs.append(doc["seq"])
                    elif st not in (409, 429):
                        with self.lock:
                            self.violations.append(
                                f"delete: status {st}: {body[:160]}")
                else:
                    m = int(rng.integers(1, 4))
                    rows = rng.uniform(0, 4, (m, d)).astype(np.float32)
                    labels = rng.integers(
                        0, self.num_classes, m).tolist()
                    st, body = http(self.base, "/insert",
                                    {"rows": rows.tolist(),
                                     "labels": labels})
                    if st == 200:
                        doc = json.loads(body)
                        self.mirror.ack(doc["seq"], "insert", rows)
                        with self.lock:
                            self.acked_seqs.append(doc["seq"])
                            self.my_live_ids.extend(doc["ids"])
                    elif st not in (429,):
                        with self.lock:
                            self.violations.append(
                                f"insert: status {st}: {body[:160]}")
            except Exception as e:  # noqa: BLE001 — recorded
                with self.lock:
                    self.violations.append(f"writer transport: {e}")
            time.sleep(0.002)

    def _reader(self, rid: int):
        import numpy as np

        rng = np.random.default_rng(self.args.seed * 2000 + rid)
        q = self.test_x.shape[0]
        r = self.args.rows
        while not self.stop.is_set():
            lo = int(rng.integers(0, max(1, q - r)))
            inst = self.test_x[lo:lo + r]
            try:
                st, body = http(self.base, "/kneighbors",
                                {"instances": inst.tolist()})
            except Exception as e:  # noqa: BLE001
                with self.lock:
                    self.violations.append(f"reader transport: {e}")
                continue
            if st != 200:
                if st == 500:
                    with self.lock:
                        self.violations.append(f"read 500: {body[:160]}")
                continue
            doc = json.loads(body)
            if "mutation_seq" not in doc:
                with self.lock:
                    self.violations.append(
                        "a 200 read carried no mutation_seq")
                continue
            with self.lock:
                self.versions_seen.add(doc["index_version"])
                self.reads.append((np.asarray(inst), doc["mutation_seq"],
                                   doc["index_version"], doc["distances"],
                                   doc["indices"]))

    def run_for(self, seconds: float) -> None:
        self.start()
        time.sleep(seconds)
        self.finish()

    def start(self) -> None:
        self.threads = (
            [threading.Thread(target=self._writer, args=(w,), daemon=True)
             for w in range(self.args.writers)]
            + [threading.Thread(target=self._reader, args=(r,),
                                daemon=True)
               for r in range(self.args.readers)])
        for t in self.threads:
            t.start()

    def finish(self) -> None:
        self.stop.set()
        for t in self.threads:
            t.join(timeout=90)
            if t.is_alive():
                self.violations.append("a load thread hung")


def wait_seq_visible(base, want_seq: int, timeout_s=30) -> dict:
    deadline = time.monotonic() + timeout_s
    blk = {}
    while time.monotonic() < deadline:
        blk = healthz(base).get("mutable") or {}
        if blk.get("seq", -1) >= want_seq:
            return blk
        time.sleep(0.2)
    return blk


def main() -> int:
    args = parse_args()
    from bench import _load_medium  # noqa: E402 — repo-root import
    from knn_tpu.serve.artifact import load_index

    train, test = _load_medium()
    d = Path(__file__).parent.parent / "build" / "fixtures"
    ref = Path("/root/reference/datasets")
    train_arff = str((ref if ref.exists() else d) / "medium-train.arff")

    # Device tail forced ON (not the lazy auto threshold): the soak's
    # short-mode delta never reaches the auto activation size, and the
    # whole point of this gate is that the DEVICE merge path replays
    # bit-identically under chaos too (docs/INDEXES.md §The
    # device-resident delta tail). KNN_TPU_DEVICE_TAIL in the caller's
    # env still overrides for debugging the host path.
    env = dict(os.environ, JAX_PLATFORMS="cpu", KNN_TPU_RETRY_BASE_MS="0")
    env.setdefault("KNN_TPU_DEVICE_TAIL", "on")
    report = {"mutable_soak": {
        "device_tail": env["KNN_TPU_DEVICE_TAIL"],
        "train_rows": train.num_instances, "writers": args.writers,
        "readers": args.readers, "rows_per_read": args.rows,
        "window_s": args.window_s, "faults": args.faults,
    }}

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "5"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        model = load_index(index)

        # ---- phase 1: oracle replay under the chaos fault burst ----------
        env1 = dict(env, KNN_TPU_FAULTS=f"serve.dispatch={args.faults}:"
                                        f"device",
                    KNN_TPU_FAULT_SEED=str(args.seed))
        proc, base = boot(index, env1)
        if base is None:
            return fail(f"phase-1 serve: no ready banner "
                        f"(rc={proc.poll()})", proc)
        v0 = healthz(base)["index_version"]
        mirror = Mirror(model.train_.features, model.k, model.metric)
        load = Load(base, mirror, test.features, train.num_classes, args,
                    deletes=True, seed=args.seed)
        load.run_for(args.window_s)
        if load.violations:
            return fail(f"phase-1 violations: {load.violations[:3]}", proc)
        max_seq = max(load.acked_seqs, default=0)
        blk = wait_seq_visible(base, max_seq)
        if blk.get("seq", -1) < max_seq:
            return fail(f"acknowledged seq {max_seq} never became visible "
                        f"(healthz seq {blk.get('seq')})", proc)
        if len(load.reads) < 20 or max_seq < 10:
            return fail(f"too little load to trust the verdict "
                        f"({len(load.reads)} reads, {max_seq} mutations)",
                        proc)
        bad = mirror.verify_reads(load.reads, {v0: ()}, "phase-1")
        if bad:
            return fail("; ".join(bad), proc)
        fresh = blk.get("freshness") or {}
        p99 = fresh.get("p99_ms")
        if p99 is None or p99 > args.freshness_p99_ms:
            return fail(f"freshness p99 {p99} ms over the "
                        f"{args.freshness_p99_ms} ms bound "
                        f"({fresh.get('count')} writes)", proc)
        rc = shutdown(proc)
        if rc != 0:
            return fail(f"phase-1 serve exited rc={rc}")
        report["phase1"] = {
            "reads_verified": len(load.reads),
            "mutations": max_seq,
            "tombstones": blk.get("tombstones"),
            "delta_rows": blk.get("delta_rows"),
            "freshness_p99_ms": p99,
        }
        print(f"mutable-soak: phase 1 ok — {len(load.reads)} reads "
              f"bit-identical to the replay of {max_seq} mutations under "
              f"the fault burst; freshness p99 {p99} ms")

        # ---- phase 2: atomic compaction swap under load ------------------
        index2 = os.path.join(tmp, "index2")
        subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index2, "--k", "5"],
            env=env, capture_output=True, text=True, cwd=REPO, check=True)
        proc, base = boot(index2, env)
        if base is None:
            return fail(f"phase-2 serve: no ready banner "
                        f"(rc={proc.poll()})", proc)
        v0 = healthz(base)["index_version"]
        mirror = Mirror(model.train_.features, model.k, model.metric)
        load = Load(base, mirror, test.features, train.num_classes, args,
                    deletes=False, seed=args.seed + 1)
        load.start()
        time.sleep(args.window_s / 3)
        st, body = http(base, "/admin/compact", {}, timeout=300)
        if st != 200:
            load.finish()
            return fail(f"/admin/compact under load: status {st}: "
                        f"{body[:200]}", proc)
        compact = json.loads(body)
        v1 = compact["index_version"]
        time.sleep(args.window_s / 3)
        load.finish()
        if load.violations:
            return fail(f"phase-2 violations: {load.violations[:3]}", proc)
        stray = load.versions_seen - {v0, v1}
        if stray:
            return fail(f"responses carried version(s) {sorted(stray)} — "
                        f"neither the old {v0} nor the new {v1} "
                        f"(the swap was not atomic)", proc)
        if v0 not in load.versions_seen or v1 not in load.versions_seen:
            return fail(f"the swap was not observed under load (saw "
                        f"{sorted(load.versions_seen)}; wanted both {v0} "
                        f"and {v1})", proc)
        max_seq = max(load.acked_seqs, default=0)
        blk = wait_seq_visible(base, max_seq)
        folded = int(blk.get("folded_seq", -1))
        if blk.get("seq", -1) < max_seq:
            return fail(f"phase-2: acked seq {max_seq} not visible after "
                        f"the swap (healthz {blk.get('seq')}) — a "
                        f"mid-compaction write was lost", proc)
        try:
            bad = mirror.verify_reads(
                load.reads, {v0: (), v1: (folded,)}, "phase-2")
        except KeyError as e:
            return fail(f"phase-2 replay hole: {e}", proc)
        if bad:
            return fail("; ".join(bad), proc)
        rc = shutdown(proc)
        if rc != 0:
            return fail(f"phase-2 serve exited rc={rc}")
        old_reads = sum(1 for r in load.reads if r[2] == v0)
        report["phase2"] = {
            "reads_verified": len(load.reads),
            "reads_old_version": old_reads,
            "reads_new_version": len(load.reads) - old_reads,
            "mutations": max_seq, "folded_seq": folded,
            "compaction_ms": compact.get("ms"),
        }
        print(f"mutable-soak: phase 2 ok — swap atomic under load "
              f"({old_reads} reads on {v0}, "
              f"{len(load.reads) - old_reads} on {v1}, all bit-identical "
              f"across the fold at seq {folded})")

        # ---- phase 3: rollback, then ---- phase 4: kill + recover --------
        index3 = os.path.join(tmp, "index3")
        subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index3, "--k", "5"],
            env=env, capture_output=True, text=True, cwd=REPO, check=True)
        env3 = dict(env, KNN_TPU_FAULTS="mutable.compact=once")
        proc, base = boot(index3, env3)
        if base is None:
            return fail(f"phase-3 serve: no ready banner "
                        f"(rc={proc.poll()})", proc)
        v0 = healthz(base)["index_version"]
        mirror = Mirror(model.train_.features, model.k, model.metric)
        import numpy as np

        rng = np.random.default_rng(args.seed)
        dim = test.features.shape[1]
        for _ in range(5):
            rows = rng.uniform(0, 4, (2, dim)).astype(np.float32)
            st, body = http(base, "/insert", {
                "rows": rows.tolist(),
                "labels": rng.integers(0, train.num_classes, 2).tolist()})
            if st != 200:
                return fail(f"phase-3 insert: status {st}", proc)
            mirror.ack(json.loads(body)["seq"], "insert", rows)
        st, body = http(base, "/admin/compact", {}, timeout=300)
        doc = json.loads(body)
        if st != 500 or not doc.get("rolled_back"):
            return fail(f"fault-armed compact: wanted 500 rolled_back, "
                        f"got {st}: {body[:200]}", proc)
        if doc.get("index_version") != v0:
            return fail(f"rollback did not keep {v0} serving "
                        f"(got {doc.get('index_version')})", proc)
        blk = healthz(base)["mutable"]
        if blk["generation"] != 0 or blk["seq"] != 5:
            return fail(f"rollback corrupted state: {blk}", proc)
        st, body = http(base, "/kneighbors",
                        {"instances": test.features[:args.rows].tolist()})
        doc = json.loads(body)
        bad = mirror.verify_reads(
            [(test.features[:args.rows], doc["mutation_seq"],
              doc["index_version"], doc["distances"], doc["indices"])],
            {v0: ()}, "post-rollback")
        if bad:
            return fail("; ".join(bad), proc)
        st, body = http(base, "/admin/compact", {}, timeout=300)
        if st != 200:
            return fail(f"retry compact after rollback: status {st}: "
                        f"{body[:200]}", proc)
        v1 = json.loads(body)["index_version"]
        f1 = healthz(base)["mutable"]["folded_seq"]
        print(f"mutable-soak: phase 3 ok — fault-armed compaction rolled "
              f"back with {v0} serving and every write intact; retry "
              f"swapped to {v1}")
        report["phase3"] = {"rolled_back": True, "retry_version": v1}

        # Phase 4: more writes (all acked), then SIGKILL mid-compaction.
        for _ in range(3):
            rows = rng.uniform(0, 4, (2, dim)).astype(np.float32)
            st, body = http(base, "/insert", {
                "rows": rows.tolist(),
                "labels": rng.integers(0, train.num_classes, 2).tolist()})
            if st != 200:
                return fail(f"phase-4 insert: status {st}", proc)
            mirror.ack(json.loads(body)["seq"], "insert", rows)
        max_seq = 8  # 5 phase-3 + 3 phase-4 insert requests, one seq each
        killer = threading.Thread(
            target=lambda: http(base, "/admin/compact", {}, timeout=10),
            daemon=True)
        killer.start()
        time.sleep(0.05)  # land inside fold/save/warm/swap
        proc.kill()  # SIGKILL — no drain, no flush beyond the WAL's own
        proc.wait(timeout=20)
        proc2, base2 = boot(index3, env)
        if base2 is None:
            return fail(f"phase-4 reboot: no ready banner "
                        f"(rc={proc2.poll()})", proc2)
        blk = healthz(base2)["mutable"]
        if blk["seq"] != max_seq:
            return fail(f"recovery lost acknowledged writes: rebooted seq "
                        f"{blk['seq']} != acked {max_seq}", proc2)
        gen = blk["generation"]
        folds = {1: (f1,), 2: (f1, blk["folded_seq"])}.get(gen)
        if folds is None:
            return fail(f"unexpected rebooted generation {gen}", proc2)
        v2 = healthz(base2)["index_version"]
        st, body = http(base2, "/kneighbors",
                        {"instances": test.features[:args.rows].tolist()})
        if st != 200:
            return fail(f"phase-4 read: status {st}", proc2)
        doc = json.loads(body)
        bad = mirror.verify_reads(
            [(test.features[:args.rows], doc["mutation_seq"], v2,
              doc["distances"], doc["indices"])],
            {v2: folds}, "post-recovery")
        if bad:
            return fail("; ".join(bad), proc2)
        rc = shutdown(proc2)
        if rc != 0:
            return fail(f"phase-4 serve exited rc={rc}")
        kill_point = ("after the commit" if gen == 2
                      else "before the commit")
        report["phase4"] = {
            "killed_mid_compaction": True,
            "recovered_generation": gen,
            "kill_landed": kill_point,
            "acked_seq_recovered": blk["seq"],
        }
        print(f"mutable-soak: phase 4 ok — SIGKILL mid-compaction landed "
              f"{kill_point}; reboot recovered every acknowledged write "
              f"(seq {blk['seq']}) and replays bit-identical")

    out = json.dumps(report, indent=2)
    print(out)
    if args.json_out:
        Path(args.json_out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.json_out).write_text(out + "\n")
    print("mutable-soak: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
