"""Randomized prediction-parity sweep on the real device.

The CPU test suite runs the Pallas kernels in interpret mode; this script
hammers the actual Mosaic-compiled kernels (and the XLA paths) with random
problems — integer grids for tie density, random shapes straddling every
padding boundary, k up to the stripe limit — and asserts bit-exact prediction
equality against the NumPy oracle.

Usage: python scripts/device_parity_sweep.py [trials]
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(trials: int = 30) -> int:
    import jax

    from knn_tpu.backends.oracle import knn_oracle
    from knn_tpu.backends.tpu import predict_arrays
    from knn_tpu.ops.pallas_knn import predict_pallas
    from knn_tpu.parallel.query_sharded import predict_query_sharded
    from knn_tpu.parallel.train_sharded import predict_train_sharded

    print(f"device: {jax.devices()[0].device_kind}", file=sys.stderr)
    rng = np.random.default_rng(20260730)
    failures = 0
    for t in range(trials):
        n = int(rng.integers(3, 6000))
        q = int(rng.integers(1, 700))
        # Up to the stripe auto-eligibility boundary (128): wide-d trials
        # compile slower (the exact unroll scales with d) but exercise the
        # widths the auto rule now routes to the kernel.
        d = int(rng.integers(1, 129))
        k = int(rng.integers(1, min(n, 16) + 1))
        c = int(rng.integers(2, 11))
        hi = int(rng.integers(2, 6))  # small grid => dist==0 ties abound
        train_x = rng.integers(0, hi, (n, d)).astype(np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        dup = min(q // 2, n)
        test_x = np.concatenate([
            train_x[rng.choice(n, dup, replace=False)] if dup else
            np.empty((0, d), np.float32),
            rng.integers(0, hi, (q - dup, d)).astype(np.float32),
        ])
        if t % 3 == 0:
            # NaN-poisoned trial: fails the stripe_inputs_finite gate, so the
            # stripe paths run FULL index retirement — the branch the
            # finite-input trials never compile on real hardware. The oracle
            # pins the NaN->+inf policy incl. the index-ordered inf tail.
            nan_rows = rng.choice(n, max(1, n // 10), replace=False)
            train_x[nan_rows, rng.integers(0, d, nan_rows.size)] = np.nan
            test_x[rng.choice(q, max(1, q // 20), replace=False)] = np.nan
        want = knn_oracle(train_x, train_y, test_x, k, c)

        paths = {
            "tpu-auto": lambda: predict_arrays(train_x, train_y, test_x, k, c),
            "tpu-xla": lambda: predict_arrays(
                train_x, train_y, test_x, k, c, engine="xla"),
            "tpu-tiled": lambda: predict_arrays(
                train_x, train_y, test_x, k, c, force_tiled=True,
                query_tile=64, train_tile=256, engine="xla"),
            "pallas-merge": lambda: predict_pallas(
                train_x, train_y, test_x, k, c, engine="merge",
                block_q=64, block_n=256, interpret=False),
            # Mosaic-compiled stripe kernel in its fast/bf16 MXU branches
            # (ADVICE r1: these lower differently from the exact branch and
            # were previously hardware-untested). On these small-integer
            # grids every term of |q|^2 - 2 q.t + |t|^2 is exactly
            # representable (values < 2^8 even in bf16, f32 accumulation),
            # so prediction equality is exact here too.
            "stripe-fast": lambda: predict_pallas(
                train_x, train_y, test_x, k, c, engine="stripe",
                precision="fast", interpret=False),
            "stripe-bf16": lambda: predict_pallas(
                train_x, train_y, test_x, k, c, engine="stripe",
                precision="bf16", interpret=False),
            # Stripe kernel composed with shard_map on a 1-device mesh — the
            # real-chip compile check for the distributed stripe routing
            # (VERDICT r1 #1); the multi-device behavior is covered by the
            # CPU-mesh tests and dryrun_multichip.
            "qs-1dev-stripe": lambda: predict_query_sharded(
                train_x, train_y, test_x, k, c, num_devices=1,
                engine="stripe", interpret=False),
            "ts-1dev-stripe": lambda: predict_train_sharded(
                train_x, train_y, test_x, k, c, mesh_shape=(1, 1),
                engine="stripe", interpret=False),
        }
        for name, fn in paths.items():
            got = fn()
            if not np.array_equal(got, want):
                failures += 1
                bad = int((got != want).sum())
                print(f"FAIL trial {t} [{name}]: n={n} q={q} d={d} k={k} "
                      f"c={c} hi={hi} ({bad}/{q} mismatches)")
        if (t + 1) % 10 == 0:
            print(f"{t + 1}/{trials} trials clean", file=sys.stderr)
    print("device parity sweep:",
          f"{trials} trials x {len(paths)} paths",
          "ALL EXACT" if failures == 0 else f"{failures} FAILURES")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 30))
