"""Regenerate the committed replay workload fixture.

``tests/data/replay-workload/`` is a small workload artifact
(docs/OBSERVABILITY.md §Workload capture & replay) captured against the
deterministic synthetic model in ``tests.fixtures.replay_fixture_model``:
~120 read events (predict/kneighbors mix, 1-4 query rows each) fired
open-loop over ~2 s with seeded bursty inter-arrivals. ``bench.py
--config replay`` re-drives it as a perf record and
``tests/test_workload.py`` pins replay mechanics on it.

Two determinism tiers, deliberately different:

- the QUERY ROWS and arrival schedule come from pinned Generator seeds
  and reproduce everywhere (NumPy stream-compatibility policy);
- the ANSWER DIGESTS are environment-pinned like
  ``BENCH_GATE_BASELINE.json`` — a different jax/numpy build may order
  float reductions differently. Consumers therefore treat fixture
  digest divergences as a REPORTED number, not a failure; the strict
  zero-divergence assertion lives in ``make replay-gate``, which
  captures and replays within one process.

Run from the repo root: ``python3 scripts/make_workload_fixture.py``
(rewrites tests/data/replay-workload in place).
"""

from __future__ import annotations

import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

READS = 120
POLICY = {"max_batch": 16, "max_wait_ms": 1.0}


def main() -> int:
    from tests import fixtures
    from knn_tpu.obs.workload import WorkloadCapture
    from knn_tpu.serve.artifact import warmup
    from knn_tpu.serve.batcher import MicroBatcher

    model = fixtures.replay_fixture_model()
    d = model.train_.num_features
    warmup(model, batch_sizes=(1, POLICY["max_batch"]), kinds=("predict",))
    rng = np.random.default_rng(5678)
    # Bursty open-loop schedule: exponential inter-arrivals with a 3x
    # rate burst through the middle third — enough structure that the
    # what-if simulator has real coalescing to model.
    gaps = []
    for i in range(READS):
        mean_ms = 5.0 if READS // 3 <= i < 2 * READS // 3 else 15.0
        gaps.append(float(rng.exponential(mean_ms)))
    kinds = ["kneighbors" if rng.random() < 0.2 else "predict"
             for _ in range(READS)]
    row_counts = [int(rng.integers(1, 5)) for _ in range(READS)]
    queries = [rng.normal(0.0, 2.0, (r, d)).astype(np.float32)
               for r in row_counts]

    with tempfile.TemporaryDirectory() as tmp:
        cap = WorkloadCapture(tmp, num_features=d, k=model.k,
                              policy=dict(POLICY))
        batcher = MicroBatcher(
            model, max_batch=POLICY["max_batch"],
            max_wait_ms=POLICY["max_wait_ms"],
            index_version=fixtures.REPLAY_FIXTURE_VERSION,
            workload=cap,
        )
        try:
            cap.start(reason="fixture")
            futures = []
            for gap_ms, kind, q in zip(gaps, kinds, queries):
                time.sleep(gap_ms / 1e3)
                futures.append(batcher.submit(q, kind))
            for f in futures:
                f.result(timeout=60)
            cap.drain(30)
            summary = cap.stop()
        finally:
            batcher.close()
            cap.close()
        out = fixtures.REPLAY_WORKLOAD_DIR
        if out.exists():
            shutil.rmtree(out)
        out.parent.mkdir(parents=True, exist_ok=True)
        shutil.copytree(summary["path"], out)
    print(f"wrote {out}: {summary['requests']} requests over "
          f"{summary['duration_ms']:.0f} ms (policy {POLICY})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
