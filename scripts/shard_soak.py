"""Shard-soak gate (`make shard-soak`): mesh-sharded serving held to its
contracts (docs/SERVING.md §Sharded serving).

**Phase 1 — bit-identity under closed-loop load.** A ``--shards 2``
serve and an unsharded twin boot from byte-identical artifacts;
concurrent readers fire the SAME ``/kneighbors`` and ``/predict``
bodies at both and every answer must be bit-identical — sharding is a
device-memory topology, never an answer change. Afterwards the sharded
``/healthz`` and ``/debug/capacity`` must expose the frozen plan plus
the per-shard walls of the last dispatch with the max/min/skew
straggler family, ``/metrics`` must carry the ``knn_shard_*``
instruments, and the twin must report ``shard: null`` with ZERO
``knn_shard_*`` series (the disabled-overhead contract, live).

**Phase 2 — mutation lockstep.** The same inserts (and a base delete)
land on both servers in the same order, acks awaited, with a paired
read after every step: bit-identical answers at every ``mutation_seq``
— the delta tail shards with the plan and the fused sentinel fixups
never leak a dead-slot marker across a shard boundary.

**Phase 3 — shard-group kill drill behind the router.** A
``head+member`` shard group (the head itself serving ``--shards 2``)
and a singleton replica register behind ``knn_tpu route``; the group's
NON-head member is SIGKILLed under read load. Invariants: ZERO failed
reads (the router fails over to the singleton), every routed answer
bit-identical to a direct read of the singleton oracle, and the router
demotes the WHOLE group — ``healthy: false`` on the head with the
corpse listed in ``shard_group.unhealthy``, usable dropping to 1 —
even though the head itself still answers polls. Rebooting the member
restores usable=2.

Every invariant violation exits 1 with a diagnosis; PASS prints the
verdict JSON (also written to ``--json-out`` for CI).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)
from mutable_soak import (  # noqa: E402 — shared soak machinery
    BOOT_TIMEOUT_S,
    READY_RE,
    http,
)

STRAGGLER_KEYS = ("max_ms", "min_ms", "skew", "max_shard", "shards")
METRIC_NAMES = ("knn_shard_dispatch_ms", "knn_shard_candidates_total",
                "knn_shard_bytes_total", "knn_shard_dispatch_ms_max",
                "knn_shard_dispatch_ms_min", "knn_shard_dispatch_skew")


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: ~6 s load windows")
    p.add_argument("--window-s", type=float, default=None)
    p.add_argument("--readers", type=int, default=3)
    p.add_argument("--rows", type=int, default=4)
    p.add_argument("--shards", type=int, default=2)
    p.add_argument("--mutation-steps", type=int, default=12)
    p.add_argument("--seed", type=int, default=23)
    p.add_argument("--json-out", default=None, metavar="FILE")
    args = p.parse_args()
    if args.window_s is None:
        args.window_s = 6.0 if args.short else 15.0
    return args


def fail(msg: str) -> int:
    print(f"shard-soak: FAIL: {msg}", file=sys.stderr)
    return 1  # procgroup's atexit sweep reaps every spawned group


def free_ports(n: int) -> "list[int]":
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def spawn(cmd, env):
    proc = procgroup.popen_group(
        [sys.executable, "-m", "knn_tpu.cli", *cmd],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    import queue

    lines: "queue.Queue[str]" = queue.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout], daemon=True,
    ).start()
    return proc, lines


def wait_ready(proc, lines, what: str):
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(1.0, max(
                0.01, deadline - time.monotonic())))
        except Exception:  # noqa: BLE001 — queue.Empty
            if proc.poll() is not None:
                return None
            continue
        m = READY_RE.search(line)
        if m:
            print(f"shard-soak: {what}: {line.rstrip()}")
            return m.group(1)
    return None


def healthz(base) -> dict:
    _st, body = http(base, "/healthz")
    return json.loads(body)


def wait_until(pred, timeout_s: float, every_s: float = 0.2):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            v = pred()
        except Exception:  # noqa: BLE001 — target mid-reboot
            v = None
        if v:
            return v
        time.sleep(every_s)
    return None


def metrics_text(base: str) -> str:
    import urllib.request

    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        return r.read().decode()


class PairLoad:
    """Closed-loop readers firing the SAME body at two servers (or one
    server and an oracle twin) and requiring bit-identical JSON answers.
    The two responses were serialized by the same ``tolist()`` +
    ``json.dumps`` pipeline, so list equality of the parsed documents IS
    float bit-identity (repr round-trips doubles exactly)."""

    def __init__(self, a: str, b: str, test_x, args, endpoints=(
            "kneighbors", "predict")):
        import numpy as np

        self.np = np
        self.a = a
        self.b = b
        self.test_x = test_x
        self.args = args
        self.endpoints = endpoints
        self.stop = threading.Event()
        self.lock = threading.Lock()
        self.reads_ok = 0
        self.failures: list = []
        self.mismatches: list = []
        self.threads: list = []

    @staticmethod
    def compare_docs(ep: str, da: dict, db: dict):
        if ep == "predict":
            return ("predictions",) if (
                da["predictions"] != db["predictions"]) else ()
        bad = []
        if da["distances"] != db["distances"]:
            bad.append("distances")
        if da["indices"] != db["indices"]:
            bad.append("indices")
        return tuple(bad)

    def _reader(self, rid: int):
        rng = self.np.random.default_rng(self.args.seed * 3000 + rid)
        q = self.test_x.shape[0]
        r = self.args.rows
        while not self.stop.is_set():
            lo = int(rng.integers(0, max(1, q - r)))
            body = {"instances": self.test_x[lo:lo + r].tolist()}
            ep = self.endpoints[int(rng.integers(0, len(self.endpoints)))]
            docs = []
            ok = True
            for base in (self.a, self.b):
                try:
                    st, raw = http(base, "/" + ep, body)
                except Exception as e:  # noqa: BLE001 — server died
                    with self.lock:
                        self.failures.append(f"{base}/{ep} transport: {e}")
                    ok = False
                    break
                if st != 200:
                    with self.lock:
                        self.failures.append(
                            f"{base}/{ep} status {st}: {raw[:200]}")
                    ok = False
                    break
                docs.append(json.loads(raw))
            if not ok:
                continue
            bad = self.compare_docs(ep, docs[0], docs[1])
            with self.lock:
                if bad:
                    self.mismatches.append(
                        f"/{ep} rows [{lo}:{lo + r}] diverged on "
                        f"{'+'.join(bad)}")
                else:
                    self.reads_ok += 1

    def start(self):
        self.threads = [
            threading.Thread(target=self._reader, args=(r,), daemon=True)
            for r in range(self.args.readers)]
        for t in self.threads:
            t.start()

    def finish(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=90)
            if t.is_alive():
                self.failures.append("a load thread hung")


def check_shard_block(sb, num_shards: int, train_rows: int):
    """The /healthz + /debug/capacity shard block contract after at
    least one sharded dispatch. Returns an error string or None."""
    if sb is None:
        return "shard block is null on the sharded server"
    if sb.get("num_shards") != num_shards:
        return f"num_shards {sb.get('num_shards')} (want {num_shards})"
    if sum(sb.get("rows_per_shard", [])) != train_rows:
        return (f"rows_per_shard {sb.get('rows_per_shard')} does not "
                f"cover the {train_rows}-row train matrix")
    if sb.get("dispatches", 0) < 1:
        return "no sharded dispatch was ever recorded"
    last = sb.get("serve-sharded") or sb.get("serve-sharded-ivf")
    if not last:
        return "no per-shard walls for the last dispatch"
    if len(last.get("walls_ms", {})) != num_shards:
        return (f"last dispatch recorded walls for "
                f"{len(last.get('walls_ms', {}))} shard(s), want "
                f"{num_shards}")
    stragglers = last.get("stragglers")
    if not stragglers:
        return "no straggler summary on the last dispatch"
    missing = [k for k in STRAGGLER_KEYS if k not in stragglers]
    if missing:
        return f"straggler summary missing {missing}"
    if stragglers["skew"] < 1.0:
        return f"straggler skew {stragglers['skew']} < 1.0"
    return None


def main() -> int:
    args = parse_args()
    import numpy as np
    from bench import _load_medium  # noqa: E402 — repo-root import

    train, test = _load_medium()
    d = Path(__file__).parent.parent / "build" / "fixtures"
    ref = Path("/root/reference/datasets")
    train_arff = str((ref if ref.exists() else d) / "medium-train.arff")

    env = dict(os.environ, JAX_PLATFORMS="cpu", KNN_TPU_RETRY_BASE_MS="0")
    report: dict = {"shard_soak": {
        "train_rows": train.num_instances, "shards": args.shards,
        "readers": args.readers, "window_s": args.window_s,
    }}

    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        seed_idx = tmp / "seed"
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             str(seed_idx), "--k", "5"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: "
                        f"{build.stderr}")
        dirs = {}
        for name in ("sharded", "twin", "g1", "g2", "r0"):
            dirs[name] = tmp / name
            shutil.copytree(seed_idx, dirs[name])

        p_s, p_t = free_ports(2)
        serve_common = ["--max-batch", "32", "--max-wait-ms", "1",
                        "--mutable", "on", "--compact-interval-s", "0",
                        "--compact-threshold", "1000000"]
        proc_s, lines_s = spawn(
            ["serve", str(dirs["sharded"]), "--port", str(p_s),
             *serve_common, "--shards", str(args.shards)], env)
        proc_t, lines_t = spawn(
            ["serve", str(dirs["twin"]), "--port", str(p_t),
             *serve_common], env)
        sharded = wait_ready(proc_s, lines_s, "sharded")
        twin = wait_ready(proc_t, lines_t, "twin")
        if None in (sharded, twin):
            return fail(f"boot failed (sharded={sharded}, twin={twin})")
        h = healthz(sharded)
        if (h.get("shard") or {}).get("num_shards") != args.shards:
            return fail(f"sharded /healthz shard block wrong before any "
                        f"load: {h.get('shard')}")
        if healthz(twin).get("shard") is not None:
            return fail("the UNSHARDED twin reports a shard block — the "
                        "unset state must stay null")
        v0 = h["index_version"]
        if healthz(twin)["index_version"] != v0:
            return fail("the twin booted a different index_version — "
                        "the artifact copies diverged")

        # ---- phase 1: bit-identity under closed-loop load ----------------
        load = PairLoad(sharded, twin, test.features, args)
        load.start()
        time.sleep(args.window_s)
        load.finish()
        if load.failures:
            return fail(f"phase-1 request failures: {load.failures[:3]}")
        if load.mismatches:
            return fail(f"phase-1 sharded answers DIVERGED from the "
                        f"unsharded twin: {load.mismatches[:3]}")
        if load.reads_ok < 50:
            return fail(f"too little load to trust phase 1 "
                        f"({load.reads_ok} paired reads)")

        # The straggler surface after the window: /healthz and
        # /debug/capacity agree, /metrics carries the instruments.
        err = check_shard_block(healthz(sharded).get("shard"),
                                args.shards, train.num_instances)
        if err:
            return fail(f"phase-1 /healthz shard block: {err}")
        st, body = http(sharded, "/debug/capacity")
        if st != 200:
            return fail(f"/debug/capacity on the sharded server: {st}")
        err = check_shard_block(json.loads(body).get("shard"),
                                args.shards, train.num_instances)
        if err:
            return fail(f"phase-1 /debug/capacity shard block: {err}")
        text = metrics_text(sharded)
        missing = [m for m in METRIC_NAMES if m + "{" not in text]
        if missing:
            return fail(f"phase-1 /metrics is missing {missing}")
        if "knn_shard_" in metrics_text(twin):
            return fail("phase-1: the UNSHARDED twin leaked knn_shard_* "
                        "series — the disabled-overhead contract broke "
                        "live")
        report["phase1"] = {"paired_reads": load.reads_ok}
        print(f"shard-soak: phase 1 ok — {load.reads_ok} paired reads "
              f"bit-identical sharded-vs-unsharded; straggler gauges "
              f"live on /healthz, /debug/capacity and /metrics; twin "
              f"stayed shard-free")

        # ---- phase 2: mutation lockstep ----------------------------------
        rng = np.random.default_rng(args.seed)
        dcols = test.features.shape[1]
        probe = {"instances": test.features[:args.rows].tolist()}
        deleted = False
        for step in range(args.mutation_steps):
            m = int(rng.integers(1, 3))
            rows = rng.uniform(0, 4, (m, dcols)).astype(np.float32)
            labels = rng.integers(0, train.num_classes, m).tolist()
            payload = {"rows": rows.tolist(), "labels": labels}
            seqs = {}
            for name, base in (("sharded", sharded), ("twin", twin)):
                st, raw = http(base, "/insert", payload)
                if st != 200:
                    return fail(f"phase-2 step {step}: insert on {name} "
                                f"-> {st}: {raw[:200]}")
                seqs[name] = json.loads(raw)["seq"]
            if seqs["sharded"] != seqs["twin"]:
                return fail(f"phase-2 step {step}: lockstep seqs "
                            f"diverged: {seqs}")
            if step == args.mutation_steps // 2:
                for name, base in (("sharded", sharded), ("twin", twin)):
                    st, raw = http(base, "/delete", {"ids": [7]})
                    if st != 200:
                        return fail(f"phase-2 base delete on {name} -> "
                                    f"{st}: {raw[:200]}")
                deleted = True
            docs = {}
            for name, base in (("sharded", sharded), ("twin", twin)):
                st, raw = http(base, "/kneighbors", probe)
                if st != 200:
                    return fail(f"phase-2 step {step}: read on {name} "
                                f"-> {st}: {raw[:200]}")
                docs[name] = json.loads(raw)
            if (docs["sharded"]["mutation_seq"]
                    != docs["twin"]["mutation_seq"]):
                return fail(f"phase-2 step {step}: reads observed "
                            f"different mutation_seqs")
            bad = PairLoad.compare_docs("kneighbors", docs["sharded"],
                                        docs["twin"])
            if bad:
                return fail(f"phase-2 step {step} (seq "
                            f"{docs['sharded']['mutation_seq']}): "
                            f"sharded answer diverged on "
                            f"{'+'.join(bad)}")
        if not deleted:
            return fail("phase-2 never exercised the base-delete leg")
        # A final paired sweep over a spread of query windows, both
        # endpoints, against the mutated state.
        load = PairLoad(sharded, twin, test.features, args)
        load.start()
        time.sleep(args.window_s / 3)
        load.finish()
        if load.failures or load.mismatches:
            return fail(f"phase-2 post-mutation sweep: "
                        f"{(load.failures + load.mismatches)[:3]}")
        report["phase2"] = {
            "mutation_steps": args.mutation_steps,
            "final_seq": healthz(sharded)["mutable"]["seq"],
            "post_mutation_paired_reads": load.reads_ok,
        }
        print(f"shard-soak: phase 2 ok — {args.mutation_steps} lockstep "
              f"inserts + a base delete to seq "
              f"{report['phase2']['final_seq']}: every paired read "
              f"bit-identical ({load.reads_ok} more in the sweep)")
        procgroup.kill_group(proc_s)
        procgroup.kill_group(proc_t)

        # ---- phase 3: shard-group kill drill behind the router -----------
        q1, q2, q3, qr = free_ports(4)
        url = {"g1": f"http://127.0.0.1:{q1}",
               "g2": f"http://127.0.0.1:{q2}",
               "r0": f"http://127.0.0.1:{q3}"}
        immut = ["--max-batch", "16", "--max-wait-ms", "1"]

        def boot(name, extra=()):
            proc, lines = spawn(
                ["serve", str(dirs[name]), "--port",
                 url[name].rsplit(":", 1)[1], *immut, *extra], env)
            return proc, wait_ready(proc, lines, name)

        procs = {}
        procs["g1"], b1 = boot("g1", ("--shards", str(args.shards)))
        procs["g2"], b2 = boot("g2")
        procs["r0"], b3 = boot("r0")
        if None in (b1, b2, b3):
            return fail(f"phase-3 boot failed (g1={b1}, g2={b2}, "
                        f"r0={b3})")
        router_proc, router_lines = spawn(
            ["route", f"{url['g1']}+{url['g2']}", url["r0"],
             "--port", str(qr), "--health-interval-s", "0.25"], env)
        router = wait_ready(router_proc, router_lines, "router")
        if router is None:
            return fail(f"phase-3 router boot failed "
                        f"(rc={router_proc.poll()})")
        if not wait_until(lambda: healthz(router)["usable"] == 2,
                          timeout_s=20):
            return fail("phase-3: router never saw the group AND the "
                        "singleton usable")
        reps = healthz(router)["replicas"]
        if set(reps) != {url["g1"], url["r0"]}:
            return fail(f"phase-3: the router's replica view lists "
                        f"{sorted(reps)} — want heads only ({url['g1']} "
                        f"and {url['r0']})")
        group = reps[url["g1"]].get("shard_group")
        if (group is None
                or set(group["members"]) != {url["g1"], url["g2"]}):
            return fail(f"phase-3: the head's shard_group block is "
                        f"wrong: {group}")

        # Routed answers must be bit-identical to a direct read of the
        # singleton oracle — whichever "replica" answers, group or not.
        load = PairLoad(router, url["r0"], test.features, args,
                        endpoints=("kneighbors",))
        load.start()
        time.sleep(args.window_s / 3)
        procgroup.kill_group(procs["g2"])  # the NON-head member
        kill_t = time.monotonic()

        def group_demoted():
            h = healthz(router)
            s = h["replicas"][url["g1"]]
            return (h["usable"] == 1 and not s["healthy"]
                    and s["shard_group"]["unhealthy"] == [url["g2"]])

        if not wait_until(group_demoted, timeout_s=20):
            load.finish()
            h = healthz(router)
            return fail(f"phase-3: the router never demoted the WHOLE "
                        f"group after the member SIGKILL "
                        f"({time.monotonic() - kill_t:.1f}s; head state "
                        f"{h['replicas'][url['g1']]})")
        time.sleep(args.window_s / 3)
        procs["g2"], b2 = boot("g2")
        if b2 is None:
            load.finish()
            return fail(f"phase-3 member reboot failed "
                        f"(rc={procs['g2'].poll()})")
        if not wait_until(lambda: healthz(router)["usable"] == 2,
                          timeout_s=20):
            load.finish()
            return fail("phase-3: the group never rejoined after the "
                        "member reboot")
        time.sleep(args.window_s / 4)
        load.finish()
        if load.failures:
            return fail(f"phase-3 failed reads during the group kill "
                        f"drill: {load.failures[:3]}")
        if load.mismatches:
            return fail(f"phase-3 routed answers diverged from the "
                        f"singleton oracle: {load.mismatches[:3]}")
        if load.reads_ok < 50:
            return fail(f"too little load to trust phase 3 "
                        f"({load.reads_ok} paired reads)")
        report["phase3"] = {
            "paired_reads": load.reads_ok,
            "group_members": group["members"],
        }
        print(f"shard-soak: phase 3 ok — member SIGKILL demoted the "
              f"whole group (usable 2 -> 1) with ZERO failed reads "
              f"through the router; reboot restored usable=2; "
              f"{load.reads_ok} routed reads bit-identical to the "
              f"singleton oracle")

    out = json.dumps(report, indent=2)
    print(out)
    if args.json_out:
        Path(args.json_out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.json_out).write_text(out + "\n")
    print("shard-soak: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
