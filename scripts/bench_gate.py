"""Perf-regression gate (`make bench-gate`): measure a fresh bench gate
record and compare it against the committed baseline with the noise-aware
best-of-mins + MAD-tolerance rule (knn_tpu/obs/regress.py).

Flow:

1. Fresh record: ``bench.bench_gate_config()`` (or ``--fresh FILE`` to
   gate a pre-measured/synthetic record — what the tests and the
   "synthetically slowed" acceptance leg use).
2. Baseline: ``BENCH_GATE_BASELINE.json`` at the repo root — a map of
   environment-fingerprint keys (``{platform}-{device_kind}-cpu{N}``) to
   gate records, because trial lists measured on a v5e say nothing about
   a 2-vCPU CI runner. No entry for this environment → the gate reports
   ``no-baseline`` and exits 0 (with the fresh record written as a
   candidate), because failing every new box would train people to delete
   the gate; ``--write-baseline`` records this environment's entry.
3. Verdict JSON (``pass``, per-metric checks, params) goes to ``--out``
   (default ``build/bench_gate_verdict.json``) — the artifact CI uploads.

Exit 0 = pass / no-baseline / baseline-written; 1 = a gated metric
regressed past its tolerance; 2 = usage (unreadable files).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_BASELINE = REPO / "BENCH_GATE_BASELINE.json"
DEFAULT_OUT = REPO / "build" / "bench_gate_verdict.json"


def env_key(record: dict) -> str:
    env = record.get("env") or {}
    return (f"{env.get('platform', '?')}-{env.get('device_kind', '?')}"
            f"-cpu{env.get('cpus', '?')}").replace(" ", "_")


def write_json(path: Path, doc: dict) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=1) + "\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_gate.py")
    p.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                   help="committed baseline file (per-environment entries)")
    p.add_argument("--fresh", default=None, metavar="FILE",
                   help="gate a pre-measured record instead of measuring "
                   "(tests / synthetic-regression legs)")
    p.add_argument("--out", default=str(DEFAULT_OUT),
                   help="verdict JSON destination")
    p.add_argument("--write-baseline", action="store_true",
                   help="record the fresh measurement as this "
                   "environment's baseline entry and exit 0")
    p.add_argument("--rel-tol", type=float, default=None,
                   help="relative tolerance (default: obs/regress.py's)")
    p.add_argument("--mad-k", type=float, default=None,
                   help="baseline-MAD multiples of tolerance")
    args = p.parse_args(argv)

    from knn_tpu.obs import regress

    rel_tol = (regress.DEFAULT_REL_TOL if args.rel_tol is None
               else args.rel_tol)
    mad_k = regress.DEFAULT_MAD_K if args.mad_k is None else args.mad_k

    if args.fresh:
        try:
            fresh = json.loads(Path(args.fresh).read_text())
        except (OSError, ValueError) as e:
            print(f"bench-gate: error: --fresh {args.fresh}: {e}",
                  file=sys.stderr)
            return 2
    else:
        import bench

        print("bench-gate: measuring the fresh gate record "
              "(bench.bench_gate_config)...", file=sys.stderr)
        fresh = bench.bench_gate_config()

    key = env_key(fresh)
    baseline_path = Path(args.baseline)
    entries = {}
    if baseline_path.exists():
        try:
            entries = json.loads(baseline_path.read_text()).get("entries", {})
        except (OSError, ValueError) as e:
            print(f"bench-gate: error: unreadable baseline "
                  f"{baseline_path}: {e}", file=sys.stderr)
            return 2

    if args.write_baseline:
        entries[key] = fresh
        write_json(baseline_path, {
            "comment": "bench-gate baselines, one entry per environment "
                       "fingerprint (scripts/bench_gate.py "
                       "--write-baseline refreshes the current one)",
            "entries": entries,
        })
        write_json(Path(args.out), {
            "status": "baseline-written", "pass": True, "env": key,
        })
        print(f"bench-gate: baseline entry written for {key} -> "
              f"{baseline_path}")
        return 0

    baseline = entries.get(key)
    if baseline is None:
        candidate = REPO / "build" / "bench_gate_candidate.json"
        write_json(candidate, fresh)
        write_json(Path(args.out), {
            "status": "no-baseline", "pass": True, "env": key,
            "known_envs": sorted(entries),
            "note": f"no committed baseline for this environment; fresh "
                    f"record saved to {candidate} (commit it with "
                    f"--write-baseline to arm the gate here)",
        })
        print(f"bench-gate: no baseline for env {key} (known: "
              f"{sorted(entries)}); PASS (unarmed), candidate saved")
        return 0

    verdict = regress.compare_records(baseline, fresh, rel_tol=rel_tol,
                                      mad_k=mad_k)
    verdict["status"] = "compared"
    verdict["env"] = key
    write_json(Path(args.out), verdict)
    print(regress.summarize(verdict))
    if verdict.get("new_metrics"):
        # Metrics the fresh record has that the committed baseline lacks
        # (e.g. PR 8's serve_c8_occupancy_mean/duty_cycle/waste_ratio):
        # reported for visibility, never gated, until --write-baseline
        # records an entry that carries them.
        for name in verdict["new_metrics"]:
            m = fresh.get("metrics", {}).get(name, {})
            print(f" reported {name}: {m.get('trials')} {m.get('unit', '')} "
                  f"(new metric — not gated)")
    if not verdict["pass"]:
        print(f"bench-gate: FAIL — regression past tolerance "
              f"(verdict: {args.out})", file=sys.stderr)
        return 1
    print(f"bench-gate: PASS ({len(verdict['checks'])} metrics within "
          f"tolerance; verdict: {args.out})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
