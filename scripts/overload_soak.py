"""Overload-soak gate (`make overload-soak`): the control plane under fire —
the degradation-order acceptance run (docs/RESILIENCE.md §Degradation order,
docs/SERVING.md §Surviving an overload).

Two phases prove the two halves of the overload story:

**Phase 1 — one replica past its knee.** Boot `knn_tpu serve` with a
priority map (``interactive=0,bulk=2``), the brownout ladder armed, and a
deliberately tight queue bound, then hammer it with mixed-class closed-loop
clients until the queue-full 429s burn the availability budget. The gate
asserts the whole serve-side ladder engages IN ORDER and reverses:

- ``bulk`` requests shed with the typed policy 429 (body names the
  admission cutoff) while ``interactive`` is NEVER policy-shed — its only
  429s are the queue-full backstop;
- EVERY 429 carries an actionable ``Retry-After`` (>= 1 s);
- the brownout ladder applies at least one reversible step during the
  burst — and after the burst, under a light trickle, the cutoff restores
  fully and every applied brownout step is reverted (apply count ==
  revert count; level back to 0): the post-incident operating point is
  exactly the configured one;
- the SLO layer counted the policy sheds in ``policy_sheds`` — the
  deliberate-shed ledger that availability burn excludes.

**Phase 2 — the fleet grows before anyone sheds.** Boot two replicas plus
a router with ``--scale-cmd`` pointing at a logging stub and a third
registered-but-down replica slot. Under read load (with the hysteresis
bands narrowed via env so the drill fits a CI window) the router must
drive the operator's command ``up <slot-C-url>`` — the first rung of the
degradation order — and audit ``scale-up-begin``/``-complete`` in the
fleet event log; when the load stops, it must drive ``down`` against a
non-primary live replica, never below ``--scale-min``.

Exit 0 when every invariant holds; 1 with a diagnosis. stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 120


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: ~8 s overload burst")
    p.add_argument("--window-s", type=float, default=None)
    p.add_argument("--bulk-clients", type=int, default=6)
    p.add_argument("--interactive-clients", type=int, default=2)
    p.add_argument("--rows", type=int, default=16,
                   help="rows per request (vs the tight queue bound)")
    p.add_argument("--json-out", default=None, metavar="FILE")
    args = p.parse_args()
    if args.window_s is None:
        args.window_s = 8.0 if args.short else 20.0
    return args


def fail(msg: str) -> int:
    print(f"overload-soak: FAIL: {msg}", file=sys.stderr)
    return 1  # procgroup's atexit sweep reaps every spawned group


def free_ports(n: int) -> "list[int]":
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def http(base: str, path: str, payload=None, timeout=30, headers=None):
    """Returns ``(status, body, response_headers)``."""
    hdrs = {"Content-Type": "application/json"} if payload is not None else {}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers=hdrs,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def spawn(cmd, env):
    proc = procgroup.popen_group(
        [sys.executable, "-m", "knn_tpu.cli", *cmd],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    import queue

    lines: "queue.Queue[str]" = queue.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout], daemon=True,
    ).start()
    return proc, lines


def wait_ready(proc, lines, what: str):
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(1.0, max(
                0.01, deadline - time.monotonic())))
        except Exception:  # noqa: BLE001 — queue.Empty
            if proc.poll() is not None:
                return None
            continue
        m = READY_RE.search(line)
        if m:
            print(f"overload-soak: {what}: {line.rstrip()}")
            return m.group(1)
    return None


def wait_until(pred, timeout_s: float, every_s: float = 0.2):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            v = pred()
        except Exception:  # noqa: BLE001 — target mid-transition
            v = None
        if v:
            return v
        time.sleep(every_s)
    return None


def control_doc(base) -> dict:
    st, body, _h = http(base, "/debug/control", timeout=10)
    if st != 200:
        raise RuntimeError(f"/debug/control: status {st}: {body[:200]}")
    return json.loads(body)


class ClassStats:
    """Per-class outcome ledger one client cohort fills under the lock."""

    def __init__(self):
        self.ok = 0
        self.policy_shed = 0
        self.other_429 = 0
        self.missing_retry_after = 0
        self.errors: "list[str]" = []


def run_class_clients(base, rows, n_clients, cls, stop, stats, lock):
    def loop(cid):
        i = cid
        while not stop.is_set():
            lo = (7 * i) % max(1, len(rows) - len(rows) // 4)
            i += 1
            batch = rows[lo:lo + stats_rows].tolist()
            try:
                st, body, hdrs = http(base, "/predict",
                                      {"instances": batch}, timeout=30,
                                      headers={"x-knn-class": cls})
            except Exception as e:  # noqa: BLE001 — recorded
                with lock:
                    stats.errors.append(f"{cls} client {cid}: {e}")
                continue
            with lock:
                if st == 200:
                    stats.ok += 1
                elif st in (429, 503):
                    try:
                        retry = float(hdrs.get("Retry-After"))
                    except (TypeError, ValueError):
                        retry = None
                    if retry is None or retry < 1:
                        stats.missing_retry_after += 1
                    if "shed by admission policy" in body:
                        stats.policy_shed += 1
                    else:
                        stats.other_429 += 1
                elif st == 500:
                    stats.errors.append(f"{cls} client {cid}: 500: "
                                        f"{body[:200]}")

    threads = [threading.Thread(target=loop, args=(c,), daemon=True)
               for c in range(n_clients)]
    for t in threads:
        t.start()
    return threads


stats_rows = 16  # set from args in main() — rows per client request


def phase1(args, index, test_rows, report) -> "int | None":
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KNN_TPU_RETRY_BASE_MS="0",
        # Fast control cadence so the hysteresis walks inside a CI
        # window: evaluate every 50 ms, one tier/step per 300 ms.
        KNN_TPU_CONTROL_EVAL_MS="50",
        KNN_TPU_CONTROL_COOLDOWN_MS="300",
    )
    proc, lines = spawn(
        ["serve", index, "--port", "0",
         "--max-batch", "8", "--max-wait-ms", "1",
         # Tight queue bound: the closed-loop cohort overflows it, the
         # queue-full 429s burn availability, the burn engages the
         # control plane. 5 s SLO window = fast engage AND fast release.
         "--max-queue-rows", "48",
         "--slo-windows", "5,60",
         "--shadow-rate", "0.5", "--drift-rate", "0.2",
         "--priority", "interactive=0,bulk=2",
         "--brownout", "on"],
        env)
    base = wait_ready(proc, lines, "serve")
    if base is None:
        return fail(f"phase-1 serve: no ready banner (rc={proc.poll()})")

    doc = control_doc(base)
    if not (doc["enabled"]["admission"] and doc["enabled"]["brownout"]):
        return fail(f"control plane not armed at boot: {doc['enabled']}")

    stop = threading.Event()
    lock = threading.Lock()
    bulk, inter = ClassStats(), ClassStats()
    threads = run_class_clients(base, test_rows, args.bulk_clients,
                                "bulk", stop, bulk, lock)
    threads += run_class_clients(base, test_rows, args.interactive_clients,
                                 "interactive", stop, inter, lock)
    shed_tiers_max = 0
    brownout_max = 0
    t_end = time.monotonic() + args.window_s
    while time.monotonic() < t_end:
        try:
            doc = control_doc(base)
            shed_tiers_max = max(shed_tiers_max,
                                 doc["admission"]["shed_tiers"])
            brownout_max = max(brownout_max, doc["brownout"]["level"])
        except Exception:  # noqa: BLE001 — keep polling under load
            pass
        time.sleep(0.1)
    stop.set()
    for t in threads:
        t.join(timeout=35)
        if t.is_alive():
            return fail("a phase-1 client thread hung")

    if bulk.errors or inter.errors:
        for v in (bulk.errors + inter.errors)[:10]:
            print(f"overload-soak: VIOLATION: {v}", file=sys.stderr)
        return fail(f"{len(bulk.errors) + len(inter.errors)} serving "
                    f"violation(s) in phase 1")
    if bulk.policy_shed == 0:
        return fail(f"no bulk request was policy-shed across the burst "
                    f"(bulk: {bulk.ok} ok, {bulk.other_429} backstop "
                    f"429s; shed_tiers peak {shed_tiers_max}) — the "
                    f"admission cutoff never engaged")
    if inter.policy_shed > 0:
        return fail(f"{inter.policy_shed} INTERACTIVE request(s) were "
                    f"policy-shed — the protected tier must never shed "
                    f"by policy")
    missing = bulk.missing_retry_after + inter.missing_retry_after
    if missing:
        return fail(f"{missing} overload response(s) lacked an "
                    f"actionable Retry-After (>= 1 s)")
    if shed_tiers_max < 1:
        return fail("admission shed_tiers never rose during the burst")
    if brownout_max < 1:
        return fail("the brownout ladder never applied a step during "
                    "the burst")
    print(f"overload-soak: phase 1 burst ok — bulk {bulk.ok} ok / "
          f"{bulk.policy_shed} policy-shed / {bulk.other_429} backstop; "
          f"interactive {inter.ok} ok / {inter.other_429} backstop / "
          f"0 policy-shed; shed_tiers peak {shed_tiers_max}, brownout "
          f"peak {brownout_max}")

    # -- recovery: trickle load, everything must walk back -----------------
    def trickle_and_check():
        st, _b, _h = http(base, "/predict",
                          {"instances": test_rows[:2].tolist()},
                          timeout=10, headers={"x-knn-class": "interactive"})
        doc = control_doc(base)
        if (doc["admission"]["shed_tiers"] == 0
                and doc["brownout"]["level"] == 0):
            return doc
        return None

    doc = wait_until(trickle_and_check, timeout_s=40.0, every_s=0.1)
    if doc is None:
        last = control_doc(base)
        return fail(f"control plane did not fully recover within 40 s: "
                    f"shed_tiers={last['admission']['shed_tiers']}, "
                    f"brownout level={last['brownout']['level']}")
    adm, bro = doc["admission"], doc["brownout"]
    if adm["moves"]["restore"] < 1:
        return fail(f"cutoff reopened without a restore move: "
                    f"{adm['moves']}")
    if bro["moves"]["apply"] != bro["moves"]["revert"]:
        return fail(f"brownout applied {bro['moves']['apply']} step(s) "
                    f"but reverted {bro['moves']['revert']} — the "
                    f"operating point did not return to configured")
    if not any(e["action"] == "revert" for e in bro["audit"]):
        return fail("no revert entry in the brownout audit ring")
    if doc["degradation_order"] != ["scale", "shed_low_priority",
                                    "brownout_quality", "availability"]:
        return fail(f"degradation-order contract drifted: "
                    f"{doc['degradation_order']}")
    st, body, _h = http(base, "/healthz", timeout=10)
    sheds_1m = json.loads(body)["slo"]["policy_sheds"]["1m"]
    if sheds_1m < bulk.policy_shed:
        return fail(f"SLO policy_sheds (1m: {sheds_1m}) undercounts the "
                    f"{bulk.policy_shed} observed policy 429s")
    print(f"overload-soak: phase 1 recovery ok — cutoff restored "
          f"(moves {adm['moves']}), brownout reverted "
          f"(moves {bro['moves']}), slo policy_sheds 1m={sheds_1m}")

    proc.send_signal(signal.SIGINT)
    try:
        rc = proc.wait(timeout=20)
    except subprocess.TimeoutExpired:
        return fail("phase-1 server did not exit after SIGINT")
    if rc != 0:
        return fail(f"phase-1 server exited rc={rc} after SIGINT")

    report["phase1"] = {
        "window_s": args.window_s,
        "bulk": {"ok": bulk.ok, "policy_shed": bulk.policy_shed,
                 "backstop_429": bulk.other_429},
        "interactive": {"ok": inter.ok, "policy_shed": 0,
                        "backstop_429": inter.other_429},
        "shed_tiers_peak": shed_tiers_max,
        "brownout_level_peak": brownout_max,
        "admission_moves": adm["moves"],
        "brownout_moves": bro["moves"],
        "slo_policy_sheds_1m": sheds_1m,
    }
    return None


def phase2(args, index, test_rows, report, tmp) -> "int | None":
    env = dict(os.environ, JAX_PLATFORMS="cpu", KNN_TPU_RETRY_BASE_MS="0")
    scale_log = os.path.join(tmp, "scale.log")
    scale_sh = os.path.join(tmp, "scale.sh")
    Path(scale_sh).write_text(
        f"#!/bin/sh\necho \"$1 $2\" >> {scale_log}\n")
    os.chmod(scale_sh, 0o755)

    port_c = free_ports(1)[0]
    url_c = f"http://127.0.0.1:{port_c}"
    procs = []
    urls = []
    for name in ("a", "b"):
        proc, lines = spawn(
            ["serve", index, "--port", "0",
             "--max-batch", "8", "--max-wait-ms", "1"], env)
        base = wait_ready(proc, lines, f"replica-{name}")
        if base is None:
            return fail(f"phase-2 replica {name}: no ready banner "
                        f"(rc={proc.poll()})")
        procs.append(proc)
        urls.append(base)

    router_env = dict(
        env,
        # Narrow hysteresis bands so both directions of the drill fire
        # inside a CI window: any sustained load is "past the knee",
        # near-idle is "fits on fewer replicas".
        KNN_TPU_SCALE_UP_FRACTION="0.02",
        KNN_TPU_SCALE_DOWN_FRACTION="0.01",
    )
    router, rlines = spawn(
        ["route", urls[0], urls[1], url_c, "--port", "0",
         "--health-interval-s", "0.2",
         "--scale-cmd", scale_sh, "--scale-min", "1", "--scale-max", "3",
         "--scale-cooldown-s", "1",
         "--event-log", os.path.join(tmp, "fleet-events.jsonl")],
        router_env)
    rbase = wait_ready(router, rlines, "router")
    if rbase is None:
        return fail(f"phase-2 router: no ready banner (rc={router.poll()})")

    def two_usable():
        _st, body, _h = http(rbase, "/healthz", timeout=5)
        return json.loads(body)["usable"] == 2 or None

    if wait_until(two_usable, 20.0) is None:
        return fail("router never saw the 2 live replicas usable")

    # -- load until the autoscaler boots the empty slot --------------------
    stop = threading.Event()
    errors: "list[str]" = []

    def loop(cid):
        i = cid
        while not stop.is_set():
            lo = (3 * i) % max(1, len(test_rows) - 4)
            i += 1
            try:
                st, body, _h = http(rbase, "/predict",
                                    {"instances": test_rows[lo:lo + 2]
                                     .tolist()}, timeout=30)
                if st == 500:
                    errors.append(f"client {cid}: 500: {body[:200]}")
            except Exception as e:  # noqa: BLE001 — recorded
                errors.append(f"client {cid}: {e}")

    clients = [threading.Thread(target=loop, args=(c,), daemon=True)
               for c in range(4)]
    for t in clients:
        t.start()

    def scaled_up():
        if not os.path.exists(scale_log):
            return None
        return ("up " + url_c) in Path(scale_log).read_text() or None

    up_ok = wait_until(scaled_up, timeout_s=45.0)
    if up_ok is None:
        _st, body, _h = http(rbase, "/healthz", timeout=5)
        stop.set()
        return fail(f"autoscaler never drove 'up {url_c}' under load; "
                    f"autoscale={json.loads(body).get('autoscale')}")
    _st, body, _h = http(rbase, "/healthz", timeout=5)
    auto = json.loads(body)["autoscale"]
    print(f"overload-soak: phase 2 scale-up ok — scale command drove "
          f"the empty slot (offered {auto['offered_qps']} qps vs "
          f"sustainable {auto['sustainable_qps']}, "
          f"decisions {auto['decisions']})")

    # -- idle until it drains one live, non-primary replica ----------------
    stop.set()
    for t in clients:
        t.join(timeout=35)
        if t.is_alive():
            return fail("a phase-2 client thread hung")
    if errors:
        for v in errors[:10]:
            print(f"overload-soak: VIOLATION: {v}", file=sys.stderr)
        return fail(f"{len(errors)} routed-read violation(s) in phase 2")

    def scaled_down():
        text = Path(scale_log).read_text()
        downs = [ln for ln in text.splitlines() if ln.startswith("down ")]
        return downs or None

    # The offered-load ring is a 30 s trailing window: the down decision
    # fires once the burst has rolled out of it.
    downs = wait_until(scaled_down, timeout_s=60.0, every_s=0.5)
    if downs is None:
        _st, body, _h = http(rbase, "/healthz", timeout=5)
        return fail(f"autoscaler never drove a drain after the load "
                    f"stopped; autoscale="
                    f"{json.loads(body).get('autoscale')}")
    down_targets = {ln.split(" ", 1)[1] for ln in downs}
    if not down_targets <= set(urls):
        return fail(f"drain targeted a non-live slot: {down_targets} "
                    f"(live: {urls})")

    # -- the audit trail ---------------------------------------------------
    _st, body, _h = http(rbase, "/debug/events?n=200", timeout=10)
    events = [e["event"] for e in json.loads(body)["events"]]
    for want in ("scale-up-begin", "scale-up-complete",
                 "scale-down-begin", "scale-down-complete"):
        if want not in events:
            return fail(f"fleet event log missing {want!r} "
                        f"(saw: {sorted(set(events))})")
    _st, body, _h = http(rbase, "/healthz", timeout=5)
    auto = json.loads(body)["autoscale"]
    if auto["scales"] < 2:
        return fail(f"router counted {auto['scales']} scale op(s); "
                    f"expected >= 2 (one up, one down)")

    for proc in (router, *procs):
        proc.send_signal(signal.SIGINT)
    for what, proc in (("router", router), ("replica-a", procs[0]),
                       ("replica-b", procs[1])):
        try:
            rc = proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            return fail(f"phase-2 {what} did not exit after SIGINT")
        if rc != 0:
            return fail(f"phase-2 {what} exited rc={rc} after SIGINT")

    report["phase2"] = {
        "scale_up_target": url_c,
        "scale_down_targets": sorted(down_targets),
        "decisions": auto["decisions"],
        "scales": auto["scales"],
        "offered_qps_at_up": auto["offered_qps"],
    }
    print(f"overload-soak: phase 2 scale-down ok — drained "
          f"{sorted(down_targets)}, audit complete "
          f"({auto['scales']} scale ops)")
    return None


def main() -> int:
    args = parse_args()
    global stats_rows
    stats_rows = args.rows
    from tests import fixtures  # noqa: E402 — repo-root import

    d = fixtures.datasets_dir()
    train_arff = str(d / "small-train.arff")
    test_arff = str(d / "small-test.arff")

    from knn_tpu.data.arff import load_arff

    test_rows = load_arff(test_arff).features

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "3"],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        print(f"overload-soak: {build.stdout.strip()}")

        report: dict = {"overload_soak": {
            "window_s": args.window_s,
            "bulk_clients": args.bulk_clients,
            "interactive_clients": args.interactive_clients,
            "rows_per_request": args.rows,
        }}
        rc = phase1(args, index, test_rows, report)
        if rc is not None:
            return rc
        rc = phase2(args, index, test_rows, report, tmp)
        if rc is not None:
            return rc

        out = json.dumps(report, indent=2)
        print(out)
        if args.json_out:
            Path(args.json_out).parent.mkdir(parents=True, exist_ok=True)
            Path(args.json_out).write_text(out + "\n")
        print("overload-soak: PASS")
        return 0


if __name__ == "__main__":
    sys.exit(main())
