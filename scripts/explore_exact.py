"""Micro-exploration of the headline exact path: where do the ms go, and can a
two-stage (chunked) exact top-k or a leaner Pallas merge beat the current best?

Usage: python scripts/explore_exact.py
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import load_large
from knn_tpu.obs.bench_timing import pipelined_slope as _pipelined_slope

K = 5


def slope(mkstep, bufs, r_lo=20, r_hi=80):
    return _pipelined_slope(mkstep, bufs, r_lo, r_hi)[0]


def main():
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from knn_tpu.ops.distance import pairwise_sq_dists
    from knn_tpu.ops.vote import vote
    from knn_tpu.utils.padding import pad_axis_to_multiple

    train, test, _ = load_large()
    n, d_true = train.features.shape
    q = test.num_instances
    nc = train.num_classes
    tx = jnp.asarray(train.features)
    ty = jnp.asarray(train.labels)
    bufs = [jnp.asarray(test.features + np.float32(i) * 1e-7) for i in range(8)]
    jax.block_until_ready(bufs)
    golden = None

    def report(name, step, preds=None):
        nonlocal golden
        ms = slope(step, bufs) * 1e3
        par = ""
        if preds is not None:
            if golden is None:
                golden = preds
            par = "==" if np.array_equal(preds, golden) else "DIVERGED"
        print(f"{name:44s} {ms:8.3f} ms/step  {q/(ms/1e3):10.0f} q/s  {par}")

    # --- component timings ---
    @jax.jit
    def dist_only(qb):
        return pairwise_sq_dists(qb, tx).sum(axis=1)  # cheap reduce to avoid IO

    report("distance only (+sum reduce)", dist_only)

    @jax.jit
    def dist_topk(qb):
        d = pairwise_sq_dists(qb, tx)
        nd, i = lax.top_k(-d, K)
        return i

    report("distance + lax.top_k", dist_topk)

    @jax.jit
    def dist_approx(qb):
        d = pairwise_sq_dists(qb, tx)
        _, i = lax.approx_max_k(-d, K)
        return i

    report("distance + approx_max_k", dist_approx)

    # --- two-stage chunked exact top-k ---
    def make_two_stage(chunk):
        txp, _ = pad_axis_to_multiple(train.features, chunk, axis=0)
        txj = jnp.asarray(txp)
        n_pad = txj.shape[0]
        c = n_pad // chunk

        @jax.jit
        def step(qb):
            d = pairwise_sq_dists(qb, txj)  # [Q, n_pad]
            col = jnp.arange(n_pad)
            d = jnp.where(col[None, :] < n, d, jnp.inf)
            dc = d.reshape(qb.shape[0], c, chunk)
            nd, li = lax.top_k(-dc, K)  # [Q, c, K]
            gi = (li + (jnp.arange(c) * chunk)[None, :, None]).astype(jnp.int32)
            df = (-nd).reshape(qb.shape[0], c * K)
            gf = gi.reshape(qb.shape[0], c * K)
            ds, is_ = lax.sort((df, gf), dimension=-1, num_keys=2)
            return vote(ty[jnp.minimum(is_[:, :K], n - 1)], nc)

        return step

    for chunk in (1024, 2048, 4096, 8192):
        step = make_two_stage(chunk)
        report(f"two-stage exact chunk={chunk}", step, np.asarray(step(bufs[0])))

    # --- lane-striped pallas exact kernel ---
    from knn_tpu.ops.pallas_knn import knn_pallas_stripe_candidates

    for b_q, b_n in ((896, 2048), (896, 4096), (448, 2048), (1792, 2048),
                     (1792, 4096), (1792, 32768)):
        txp, _ = pad_axis_to_multiple(train.features, b_n, axis=0)
        txT = jnp.asarray(np.ascontiguousarray(
            np.pad(txp, ((0, 0), (0, 16 - d_true))).T))  # [16, N_pad]
        bufs_p = []
        for i in range(8):
            qp, _ = pad_axis_to_multiple(
                test.features + np.float32(i) * 1e-7, b_q, axis=0)
            qp = np.pad(qp, ((0, 0), (0, 16 - d_true)))
            bufs_p.append(jnp.asarray(qp))
        jax.block_until_ready(bufs_p)

        def step_stripe(qb, txT=txT, b_q=b_q, b_n=b_n):
            _, i = knn_pallas_stripe_candidates(
                txT, qb, n, K, block_q=b_q, block_n=b_n, d_true=d_true)
            return vote(ty[jnp.minimum(i, n - 1)], nc)

        try:
            p = np.asarray(step_stripe(bufs_p[0]))[:q]
        except Exception as e:
            print(f"stripe bq={b_q} bn={b_n}: FAILED {type(e).__name__}: {str(e)[:160]}")
            continue
        ms = slope(step_stripe, bufs_p) * 1e3
        if golden is None:
            golden = p
        par = "==" if np.array_equal(p, golden) else "DIVERGED"
        print(f"{f'pallas stripe exact bq={b_q} bn={b_n}':44s} {ms:8.3f} ms/step  "
              f"{q/(ms/1e3):10.0f} q/s  {par}")

    # --- current best paths for reference ---
    from knn_tpu.backends.tpu import knn_forward, knn_forward_tiled

    def step_full(qb):
        return knn_forward(tx, ty, qb, k=K, num_classes=nc)

    report("full-matrix exact (current)", step_full, np.asarray(step_full(bufs[0])))

    txp, _ = pad_axis_to_multiple(train.features, 32768, axis=0)
    typ, _ = pad_axis_to_multiple(train.labels, 32768, axis=0)
    txj, tyj = jnp.asarray(txp), jnp.asarray(typ)
    nv = jnp.asarray(n, jnp.int32)
    bufs_t = []
    for i in range(8):
        qp, _ = pad_axis_to_multiple(test.features + np.float32(i) * 1e-7, 1792, axis=0)
        bufs_t.append(jnp.asarray(qp))
    jax.block_until_ready(bufs_t)

    def step_tiled(qb):
        return knn_forward_tiled(
            txj, tyj, qb, nv, k=K, num_classes=nc, precision="exact",
            query_tile=1792, train_tile=32768)

    ms = slope(step_tiled, bufs_t) * 1e3
    p = np.asarray(step_tiled(bufs_t[0]))[:q]
    par = "==" if np.array_equal(p, golden) else "DIVERGED"
    print(f"{'tiled exact q=1792 t=32768 (best)':44s} {ms:8.3f} ms/step  "
          f"{q/(ms/1e3):10.0f} q/s  {par}")


if __name__ == "__main__":
    main()
