"""r4 probe: round-based vs merge-network stripe selection, interleaved.

Session-to-session device load on the tunneled v5e flickers far beyond the
documented 1.5x (r4 observed a slope trial reading 247 Tflop/s — above the
chip's bf16 peak — purely from a fast window during the r_hi batch), so the
only trustworthy comparison is the two kernels INTERLEAVED in one session.
Drives knn_pallas_stripe_candidates with select="rounds" vs select="net" on
the bench shapes; everything else (blocks, precision, buffers) identical.

Usage: python scripts/probe_select_r4.py [mnist|xl|headline ...]
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import load_large, log
from knn_tpu.obs.bench_timing import interleaved_slope_trials as _interleaved_slope_trials  # noqa: E402


def make_cases(config):
    import jax
    import jax.numpy as jnp

    from knn_tpu.ops.pallas_knn import (
        knn_pallas_stripe_candidates, stripe_prepare_queries,
        stripe_prepare_train,
    )

    if config == "mnist":
        n, q, d, k = 65536, 2048, 784, 5
        bq, bn = 1024, 1024
        precision, dtype = "bf16", jnp.bfloat16
        rng = np.random.default_rng(0)
        train = rng.random((n, d), np.float32)
        test = rng.random((q, d), np.float32)
        r_lo, r_hi = 10, 40
    elif config in ("xl", "headline"):
        tr, te, _ = load_large()
        reps = 33 if config == "xl" else 1
        train = np.tile(tr.features, (reps, 1))
        if reps > 1:
            train += 1e-3 * np.random.default_rng(0).standard_normal(
                train.shape, dtype=np.float32)
        test = te.features
        n, d = train.shape
        q = test.shape[0]
        k = 10 if config == "xl" else 5
        bq, bn = (64, 12288) if config == "xl" else (864, 2048)
        precision, dtype = "exact", jnp.float32
        r_lo, r_hi = (5, 20) if config == "xl" else (50, 200)
    else:
        raise SystemExit(f"unknown config {config}")

    txT, d_pad = stripe_prepare_train(train, bn)
    txj = jnp.asarray(txT, dtype)
    bufs = [
        jnp.asarray(stripe_prepare_queries(
            test + np.float32(i) * 1e-7, bq, d_pad))
        for i in range(r_hi)
    ]
    jax.block_until_ready(bufs)

    def mkstep(select):
        def step(qb):
            return knn_pallas_stripe_candidates(
                txj, qb, n, k, block_q=bq, block_n=bn, d_true=d,
                precision=precision, assume_finite=True, select=select,
            )
        return step

    steps = {s: mkstep(s) for s in ("rounds", "net")}
    # Compile both and check bit-identical outputs (both exact selections).
    outs = {}
    for s, st in steps.items():
        dd, ii = st(bufs[0])
        outs[s] = (np.asarray(dd), np.asarray(ii))
    same_i = np.array_equal(outs["rounds"][1], outs["net"][1])
    same_d = np.array_equal(outs["rounds"][0], outs["net"][0])
    log(f"{config}: rounds vs net outputs identical: idx={same_i} d={same_d}")
    assert same_i and same_d
    return {s: (st, bufs) for s, st in steps.items()}, q, n, d, r_lo, r_hi


def main(configs):
    for config in configs:
        cases, q, n, d, r_lo, r_hi = make_cases(config)
        slopes = _interleaved_slope_trials(cases, r_lo, r_hi, trials=5)
        for s in ("rounds", "net"):
            tr = sorted(slopes[s])
            med = tr[len(tr) // 2]
            log(f"{config} [{s:6}]: best {min(tr)*1e3:7.3f} ms  "
                f"median {med*1e3:7.3f} ms  "
                f"({q/min(tr):,.0f} q/s best, {q*n/min(tr)/1e9:.1f} Gdist/s)")
        ratio = min(slopes["rounds"]) / min(slopes["net"])
        log(f"{config}: net is {ratio:.2f}x rounds (best-vs-best, interleaved)")


if __name__ == "__main__":
    main(sys.argv[1:] or ["mnist", "xl", "headline"])
