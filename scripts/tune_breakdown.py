"""Breakdown of the headline step: distance matrix vs top-k selection, plus
alternative exact top-k formulations on the full [Q, N] matrix.

Usage: python scripts/tune_breakdown.py
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from knn_tpu.obs.bench_timing import pipelined_slope as _pipelined_slope

K = 5


def slope(mkstep, bufs, r_lo=20, r_hi=80):
    import jax

    return _pipelined_slope(
        mkstep, bufs, r_lo, r_hi, block_fn=jax.block_until_ready
    )[0]


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bench import load_large
    from knn_tpu.ops.distance import _DIST_FNS
    from knn_tpu.ops.vote import vote
    from knn_tpu.utils.evaluate import confusion_matrix, accuracy

    train, test, _ = load_large()
    q = test.num_instances
    nc = train.num_classes
    tx = jnp.asarray(train.features)
    ty = jnp.asarray(train.labels)
    bufs = [jnp.asarray(test.features + np.float32(i) * 1e-7) for i in range(8)]
    jax.block_until_ready(bufs)
    dist = _DIST_FNS["exact"]

    @jax.jit
    def d_only(qb):
        return dist(qb, tx)

    d_bufs = [d_only(b) for b in bufs]
    jax.block_until_ready(d_bufs)

    @jax.jit
    def topk_only(d):
        return lax.top_k(-d, K)

    @jax.jit
    def rounds_only(d):
        # 5 rounds of (min, argmin-by-lowest-index, retire) — pure VPU.
        idx = lax.broadcasted_iota(jnp.int32, d.shape, 1)
        outs = []
        for _ in range(K):
            m = jnp.min(d, axis=1, keepdims=True)
            is_min = d == m
            sel = jnp.min(jnp.where(is_min, idx, np.int32(2**31 - 1)),
                          axis=1, keepdims=True)
            outs.append(sel)
            d = jnp.where(is_min & (idx == sel), jnp.inf, d)
        return jnp.concatenate(outs, axis=1)

    @jax.jit
    def twostage_only(d):
        # [Q, N] -> [Q, B, n/B]: per-block top-K then merge the B*K finalists.
        B = 16
        n = d.shape[1]
        pad = (-n) % B
        dp = jnp.pad(d, ((0, 0), (0, pad)), constant_values=np.inf)
        blk = dp.reshape(d.shape[0], B, -1)
        nd, ni = lax.top_k(-blk, K)  # [Q, B, K]
        base = (jnp.arange(B) * blk.shape[2])[None, :, None]
        cd = (-nd).reshape(d.shape[0], B * K)
        ci = (ni + base).reshape(d.shape[0], B * K)
        # lexicographic final top-k via keyed sort
        order = jnp.argsort(cd * np.float32(1.0), axis=1, stable=True)
        cd_s = jnp.take_along_axis(cd, order, 1)[:, :K]
        ci_s = jnp.take_along_axis(ci, order, 1)[:, :K]
        return cd_s, ci_s

    @jax.jit
    def fused_rounds(qb):
        d = dist(qb, tx)
        idx = lax.broadcasted_iota(jnp.int32, d.shape, 1)
        outs = []
        for _ in range(K):
            m = jnp.min(d, axis=1, keepdims=True)
            is_min = d == m
            sel = jnp.min(jnp.where(is_min, idx, np.int32(2**31 - 1)),
                          axis=1, keepdims=True)
            outs.append(sel)
            d = jnp.where(is_min & (idx == sel), jnp.inf, d)
        i = jnp.concatenate(outs, axis=1)
        return vote(ty[i], nc)

    fused_s = None
    for name, fn, bs in [
        ("distance only", d_only, bufs),
        ("lax.top_k only", topk_only, d_bufs),
        ("5-round min-extract only", rounds_only, d_bufs),
        ("two-stage blocked top_k only", twostage_only, d_bufs),
        ("FUSED dist+5-round+vote", fused_rounds, bufs),
    ]:
        jax.block_until_ready(fn(bs[0]))
        s = slope(fn, bs, 10, 40)
        if fn is fused_rounds:
            fused_s = s
        print(f"{name:34s} {s*1e3:8.3f} ms/step", flush=True)

    # Parity check for the fused path (q/s from the measurement above).
    preds = np.asarray(fused_rounds(bufs[0]))
    acc = accuracy(confusion_matrix(preds, test.labels, nc))
    print(f"fused rounds accuracy {acc:.4f} ({q/fused_s:,.0f} q/s)")


if __name__ == "__main__":
    main()
