"""r5 probe: where does the large-Q kneighbors wall time go? (VERDICT r4 #4)

Decomposes the 110k-query retrieval into host prepare / upload / compute /
fetch, and compares chunking strategies:
  A. current path (64k chunks, per-chunk device_get in drain order)
  B. batched resolve (one jax.device_get over every pending chunk)
  C. single monolithic chunk (no ragged padding, one fetch)
Run ON the TPU. One-off measurement probe, not part of the test suite.
"""

import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp

from knn_tpu.data.arff import load_arff
from knn_tpu.ops.pallas_knn import (
    knn_pallas_stripe_candidates, stripe_block_sizes, stripe_candidates_arrays,
    stripe_prepare_queries, stripe_prepare_train,
)

REF = Path("/root/reference/datasets")


def t(label, fn, reps=3):
    best = float("inf")
    out = None
    for _ in range(reps):
        t0 = time.monotonic()
        out = fn()
        best = min(best, time.monotonic() - t0)
    print(f"{label:48s} {best*1e3:8.1f} ms", flush=True)
    return out, best


def main():
    train = load_arff(str(REF / "large-train.arff"))
    test = load_arff(str(REF / "large-test.arff"))
    big = np.tile(test.features, (64, 1))
    big += 1e-4 * np.random.default_rng(1).standard_normal(
        big.shape, dtype=np.float32)
    q = big.shape[0]
    k = 5
    n, d_true = train.features.shape
    block_q, block_n = stripe_block_sizes(None, None, q, k, d_pad=16)
    print(f"Q={q}, blocks=({block_q},{block_n})")

    txT_h, d_pad = stripe_prepare_train(train.features, block_n)
    txj = jnp.asarray(txT_h)
    jax.block_until_ready(txj)

    rows = 65536 // block_q * block_q
    chunks = [big[s : s + rows] for s in range(0, q, rows)]
    print(f"chunks: {[c.shape[0] for c in chunks]} (rows={rows})")

    # 1. host prepare (pad to block_q/d_pad + ragged pad)
    def prep():
        outs = []
        for c in chunks:
            qx = stripe_prepare_queries(c, block_q, d_pad)
            if qx.shape[0] < rows:
                qx = np.pad(qx, ((0, rows - qx.shape[0]), (0, 0)))
            outs.append(qx)
        return outs

    prepped, _ = t("host prepare (pad both chunks)", prep)

    # 2. upload (enqueue + block)
    def upload():
        bufs = [jnp.asarray(p) for p in prepped]
        jax.block_until_ready(bufs)
        return bufs

    bufs, _ = t("upload both chunks (blocked)", upload)

    # 3. compute: warm then pipelined slope over the 2 chunks
    def step(b):
        return knn_pallas_stripe_candidates(
            txj, b, n, k, block_q=block_q, block_n=block_n, d_true=d_true,
            precision="exact", assume_finite=True,
        )

    t("compile+first chunk", lambda: np.asarray(step(bufs[0])[0]), reps=1)

    def compute_all():
        outs = [step(b) for b in bufs]
        np.asarray(outs[-1][0])
        return outs

    t("compute 2 chunks (1 drain)", compute_all)

    # 4. fetch cost once landed: dispatch, async-copy, wait, then device_get
    def fetch_landed():
        outs = [step(b) for b in bufs]
        for o in outs:
            o[0].copy_to_host_async()
            o[1].copy_to_host_async()
        np.asarray(outs[-1][0])  # drain compute + last copy
        time.sleep(0.05)
        t0 = time.monotonic()
        for o in outs:
            jax.device_get(o)
        return time.monotonic() - t0

    for i in range(3):
        print(f"  per-chunk device_get after landed: {fetch_landed()*1e3:.1f} ms")

    def fetch_batched():
        outs = [step(b) for b in bufs]
        for o in outs:
            o[0].copy_to_host_async()
            o[1].copy_to_host_async()
        np.asarray(outs[-1][0])
        time.sleep(0.05)
        t0 = time.monotonic()
        jax.device_get(outs)
        return time.monotonic() - t0

    for i in range(3):
        print(f"  batched device_get after landed:   {fetch_batched()*1e3:.1f} ms")

    # 5. end-to-end variants
    cache = {}
    t("A. current stripe_candidates_arrays", lambda: stripe_candidates_arrays(
        train.features, big, k, cache=cache))
    t("C. single monolithic chunk", lambda: stripe_candidates_arrays(
        train.features, big, k, cache=cache, chunk_rows=1 << 20))
    t("D. 32k chunks", lambda: stripe_candidates_arrays(
        train.features, big, k, cache=cache, chunk_rows=32768))


if __name__ == "__main__":
    main()
