"""Round-3 probe: where does the mnist784 (wide-feature) step time go?

Components measured on the live chip, all at n=65536, d=784 (pad 896), q=2048,
k=5, one distinct query buffer per dispatch (dedupe-proof):

  A. pure bf16 matmul pallas kernel, same grid/blocks as the merge kernel
     -> MXU + pipeline floor per step
  B. merge kernel bf16 (current shipping form)
  C. stripe kernel precision=bf16 at the same blocks (elementwise selection)
  D. merge kernel bf16 with a 1024-row query block (train re-streams halved)

Diagnostics only — not part of bench.py.
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")
from knn_tpu.obs.bench_timing import pipelined_slope as _pipelined_slope  # noqa: E402
from knn_tpu.ops.pallas_knn import (  # noqa: E402
    knn_pallas_candidates,
    knn_pallas_stripe_candidates,
    stripe_prepare_queries,
    stripe_prepare_train,
)
from knn_tpu.utils.padding import pad_axis_to_multiple

N, Q, D, K = 65536, 2048, 784, 5


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _matmul_kernel(q_ref, t_ref, out_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros(out_ref.shape, out_ref.dtype)

    cross = jax.lax.dot_general(
        q_ref[:], t_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # cheap per-tile fold so the matmul can't be DCE'd and the output block
    # stays [BQ, 8] (not the full [BQ, N] distance matrix)
    out_ref[:] = out_ref[:] + jax.lax.reshape(
        jnp.sum(cross.reshape(cross.shape[0], 8, -1), axis=2),
        out_ref.shape,
    )


@functools.partial(jax.jit, static_argnames=("block_q", "block_n"))
def pure_matmul(tx, qx, block_q, block_n):
    n_pad, d_feat = tx.shape
    q_pad = qx.shape[0]
    grid = (q_pad // block_q, n_pad // block_n)
    return pl.pallas_call(
        _matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d_feat), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, d_feat), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((block_q, 8), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((q_pad, 8), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(qx, tx)


def make_bufs(bq, count, dtype=np.float32, d_to=896):
    rng = np.random.default_rng(1)
    test_x = rng.random((Q, D), np.float32)
    out = []
    for i in range(count):
        qp, _ = pad_axis_to_multiple(test_x + np.float32(i) * 1e-6, bq, axis=0)
        qp = np.pad(qp, ((0, 0), (0, d_to - D)))
        out.append(jnp.asarray(qp, dtype))
    jax.block_until_ready(out)
    return out


def run(name, mkstep, bufs, r_lo=10, r_hi=40):
    t0 = time.monotonic()
    np.asarray(jax.tree.leaves(mkstep(bufs[0]))[0])
    log(f"{name}: compile {time.monotonic()-t0:.1f}s")
    per_step, _ = _pipelined_slope(mkstep, bufs, r_lo, r_hi)
    tf = 2 * Q * N * D / per_step / 1e12
    log(f"{name}: {per_step*1e3:.3f} ms/step  ({Q/per_step:,.0f} q/s, {tf:.0f} TF eff)")
    return per_step


def main():
    rng = np.random.default_rng(0)
    train_x = rng.random((N, D), np.float32)
    tx, _ = pad_axis_to_multiple(train_x, 1024, axis=0)
    tx, _ = pad_axis_to_multiple(tx, 128, axis=1)
    txb = jnp.asarray(tx, jnp.bfloat16)
    txf = jnp.asarray(tx)

    bufs512 = make_bufs(512, 40)
    bufs512b = make_bufs(512, 40, jnp.bfloat16)
    bufs1024 = make_bufs(1024, 40)

    # A: pure matmul floor (bf16 operands)
    run("A  pure matmul bf16 bq=512 bn=1024",
        lambda qb: pure_matmul(txb, qb, 512, 1024), bufs512b)

    # B: shipping merge kernel bf16
    run("B  merge bf16 bq=512 bn=1024",
        lambda qb: knn_pallas_candidates(
            txb, qb, N, K, block_q=512, block_n=1024, d_true=D,
            precision="bf16"), bufs512)

    # B2: shipping merge kernel f32 (bq=256 shipping default)
    bufs256 = make_bufs(256, 40)
    run("B2 merge f32  bq=256 bn=1024",
        lambda qb: knn_pallas_candidates(
            txf, qb, N, K, block_q=256, block_n=1024, d_true=D,
            precision="fast"), bufs256)

    # C: stripe kernel with bf16 matmul distance (selection is elementwise)
    rngq = np.random.default_rng(1)
    test_x = rngq.random((Q, D), np.float32)

    def stripe_case(name, bq, bn, store_bf16):
        txT_h, d_pad = stripe_prepare_train(train_x, bn)
        txTj = jnp.asarray(txT_h, jnp.bfloat16 if store_bf16 else None)
        sbufs = []
        for i in range(40):
            sbufs.append(jnp.asarray(
                stripe_prepare_queries(test_x + np.float32(i) * 1e-6, bq, d_pad)))
        jax.block_until_ready(sbufs)
        try:
            run(name,
                lambda qb: knn_pallas_stripe_candidates(
                    txTj, qb, N, K, block_q=bq, block_n=bn, d_true=D,
                    precision="bf16", assume_finite=True), sbufs)
        except Exception as e:
            log(f"{name} failed: {type(e).__name__}: {str(e)[:160]}")

    stripe_case("C  stripe bf16 f32-store bq=512 bn=1024", 512, 1024, False)
    stripe_case("C2 stripe bf16 bf16-store bq=512 bn=1024", 512, 1024, True)
    stripe_case("C3 stripe bf16 bf16-store bq=512 bn=2048", 512, 2048, True)
    stripe_case("C4 stripe bf16 bf16-store bq=1024 bn=1024", 1024, 1024, True)
    stripe_case("C5 stripe bf16 bf16-store bq=2048 bn=1024", 2048, 1024, True)

    # D: merge bf16, 1024-row query block (half the train re-streams)
    try:
        run("D  merge bf16 bq=1024 bn=1024",
            lambda qb: knn_pallas_candidates(
                txb, qb, N, K, block_q=1024, block_n=1024, d_true=D,
                precision="bf16"), bufs1024)
    except Exception as e:
        log(f"D failed: {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
