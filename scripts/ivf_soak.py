"""IVF-soak gate (`make ivf-soak`): approximate serving held to its floor.

The ivf rung ships behind two enforced promises (docs/INDEXES.md), and
this gate measures both on the LARGE fixture — the regime partitioned
retrieval exists for:

**Phase 1 — speed x recall.** Build a format-3 artifact
(``save-index --ivf-cells``), boot `knn_tpu serve` twice under identical
closed-loop load with shadow scoring at rate 1.0: once exact-only, once
with ``--ivf-probes``. Assert the ivf serve sustains at least
``--min-speedup`` (default 3.0) times the exact serve's row throughput
AND the shadow-scored recall SLI on the ivf rung holds at or above the
recall floor — the speed is real only if the quality SLI says the
answers stayed good, and the recall is trusted only because the scorer
recomputes every served distance itself.

**Phase 2 — burn detected, probe policy recovers.** Boot with ``--ivf-
probes 1`` (recall far below the floor on this partition) and fast
policy knobs. Assert the causal chain the quality loop promises: the
quality burn rate RISES above 1 (the shadow scorer caught the recall
violation), the probe policy WIDENS nprobe (visible in /healthz), and
the short-window burn then RECOVERS to <= 1 — the self-healing answer to
"an approximate rung silently serving bad neighbors".

Exit 0 when every invariant holds; 1 with a diagnosis. stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 180


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: ~6 s load windows")
    p.add_argument("--window-s", type=float, default=None)
    p.add_argument("--clients", type=int, default=2)
    p.add_argument("--rows", type=int, default=16,
                   help="query rows per request (a serving-shape batch)")
    p.add_argument("--cells", type=int, default=128)
    p.add_argument("--probes", type=int, default=8,
                   help="phase-1 --ivf-probes (the healthy operating "
                   "point)")
    p.add_argument("--recall-floor", type=float, default=0.95)
    p.add_argument("--min-speedup", type=float, default=3.0,
                   help="required ivf/exact row-throughput multiple "
                   "(the acceptance bar)")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--json-out", default=None, metavar="FILE")
    args = p.parse_args()
    if args.window_s is None:
        args.window_s = 6.0 if args.short else 15.0
    return args


def fail(msg: str, *procs) -> int:
    print(f"ivf-soak: FAIL: {msg}", file=sys.stderr)
    for proc in procs:
        if proc is not None and proc.poll() is None:
            proc.kill()
    return 1


def http(base: str, path: str, payload=None, timeout=60):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"} if payload else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def boot(index: str, env: dict, extra_flags):
    # --batch-buckets off on BOTH sides: this soak is a controlled
    # comparison of the INDEX FAMILY (probed approximate vs exact
    # retrieval) at one fixed dispatch-shape policy — the PR-10
    # conditions its >= min-speedup bar was measured under. The bucket
    # ladder (PR 12) cuts the exact rung's query-pad compute so much on
    # this CI-sized fixture that it would mask the train-side sub-linear
    # effect being asserted; bucketed-vs-bucketed at production index
    # sizes is bench.py --config ivf's surface, not this gate's.
    proc = procgroup.popen_group(
        [sys.executable, "-m", "knn_tpu.cli", "serve", index,
         "--port", "0", "--max-batch", "32", "--max-wait-ms", "1",
         "--batch-buckets", "off",
         *extra_flags],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    import queue

    lines: "queue.Queue[str]" = queue.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout], daemon=True,
    ).start()
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(1.0, max(
                0.01, deadline - time.monotonic())))
        except Exception:  # noqa: BLE001 — queue.Empty
            if proc.poll() is not None:
                return proc, None
            continue
        m = READY_RE.search(line)
        if m:
            print(f"ivf-soak: server: {line.rstrip()}")
            return proc, m.group(1)
    return proc, None


def shutdown(proc, base=None) -> "int | None":
    proc.send_signal(signal.SIGINT)
    try:
        return proc.wait(timeout=20)
    except subprocess.TimeoutExpired:
        proc.kill()
        return None


def run_load(base, rows_mat, n_clients, req_rows, window_s):
    """Closed-loop predict load for ``window_s`` seconds; returns
    (ok_requests, ok_rows, violations, wall_s)."""
    stop = threading.Event()
    lock = threading.Lock()
    stats = {"ok": 0, "rows": 0}
    violations: list = []
    q = rows_mat.shape[0]

    def loop(cid):
        i = cid * 31
        while not stop.is_set():
            lo = (7 * i) % max(1, q - req_rows)
            i += 1
            payload = {"instances": rows_mat[lo:lo + req_rows].tolist()}
            try:
                st, body = http(base, "/predict", payload)
            except Exception as e:  # noqa: BLE001 — recorded
                with lock:
                    violations.append(f"client {cid} transport error: {e}")
                continue
            if st == 200:
                with lock:
                    stats["ok"] += 1
                    stats["rows"] += req_rows
            elif st == 500:
                with lock:
                    violations.append(f"client {cid}: 500: {body[:200]}")

    threads = [threading.Thread(target=loop, args=(c,), daemon=True)
               for c in range(n_clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(window_s)
    stop.set()
    for t in threads:
        t.join(timeout=90)
        if t.is_alive():
            violations.append("a client thread hung")
    wall = time.monotonic() - t0
    return stats["ok"], stats["rows"], violations, wall


def quality_doc(base):
    st, body = http(base, "/debug/quality", timeout=60)
    if st != 200:
        raise RuntimeError(f"/debug/quality: status {st}: {body[:200]}")
    return json.loads(body)


def wait_queue_drained(base, timeout_s=120):
    deadline = time.monotonic() + timeout_s
    doc = None
    while time.monotonic() < deadline:
        doc = quality_doc(base)
        sh = doc["shadow"]
        if sh["queue_depth"] == 0 and sh["scored"] + sh["shed"] > 0:
            return doc
        time.sleep(0.3)
    return doc


def main() -> int:
    args = parse_args()
    from bench import load_large  # noqa: E402 — repo-root import

    train, test, _ = load_large()
    d = Path(__file__).parent.parent / "build" / "fixtures"
    ref = Path("/root/reference/datasets")
    train_arff = str((ref if ref.exists() else d) / "large-train.arff")

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KNN_TPU_RETRY_BASE_MS="0")
    shadow_flags = [
        "--shadow-rate", "1", "--quality-queue", "64",
        "--quality-seed", str(args.seed), "--slo-windows", "5,60",
    ]

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "5", "--ivf-cells", str(args.cells),
             "--ivf-seed", "0"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        print(f"ivf-soak: {build.stdout.strip()}")

        # -- phase 1a: exact-only reference throughput ---------------------
        proc, base = boot(index, env, shadow_flags)
        if base is None:
            return fail(f"exact serve: no ready banner (rc={proc.poll()})",
                        proc)
        ok_e, rows_e, viol, wall_e = run_load(
            base, test.features, args.clients, args.rows, args.window_s)
        if viol:
            return fail(f"exact serve violations: {viol[:3]}", proc)
        if ok_e < 5:
            return fail(f"exact serve answered only {ok_e} requests — too "
                        f"few to trust the ratio", proc)
        exact_qps = rows_e / wall_e
        rc = shutdown(proc)
        if rc != 0:
            return fail(f"exact serve exited rc={rc}")
        print(f"ivf-soak: exact-only: {ok_e} requests, "
              f"{exact_qps:.0f} rows/s")

        # -- phase 1b: ivf serving — speed AND shadow-scored recall --------
        proc, base = boot(index, env, shadow_flags + [
            "--ivf-probes", str(args.probes),
            "--ivf-recall-floor", str(args.recall_floor)])
        if base is None:
            return fail(f"ivf serve: no ready banner (rc={proc.poll()})",
                        proc)
        ok_i, rows_i, viol, wall_i = run_load(
            base, test.features, args.clients, args.rows, args.window_s)
        if viol:
            return fail(f"ivf serve violations: {viol[:3]}", proc)
        ivf_qps = rows_i / wall_i
        doc = wait_queue_drained(base)
        sh = doc["shadow"]
        ivf_rung = sh["rungs"].get("ivf")
        if ivf_rung is None or sh["scored"] < 20:
            return fail(f"too few ivf shadow scores to trust the verdict "
                        f"(rungs={sorted(sh['rungs'])}, "
                        f"scored={sh['scored']})", proc)
        recall = ivf_rung["recall"]
        if recall is None or recall < args.recall_floor:
            return fail(f"ivf rung recall SLI {recall} under the "
                        f"{args.recall_floor} floor at the healthy "
                        f"operating point (nprobe {args.probes})", proc)
        speedup = ivf_qps / exact_qps
        st, body = http(base, "/healthz")
        ivf_block = json.loads(body).get("ivf") or {}
        rc = shutdown(proc)
        if rc != 0:
            return fail(f"ivf serve exited rc={rc}")
        if speedup < args.min_speedup:
            return fail(f"ivf rung {ivf_qps:.0f} rows/s is only "
                        f"{speedup:.2f}x the exact rung's "
                        f"{exact_qps:.0f} — under the {args.min_speedup}x "
                        f"bar")
        print(f"ivf-soak: phase 1 ok — ivf {ivf_qps:.0f} rows/s = "
              f"{speedup:.2f}x exact {exact_qps:.0f}, recall SLI "
              f"{recall} >= {args.recall_floor} ({sh['scored']} scored, "
              f"{sh['shed']} shed, nprobe {ivf_block.get('nprobe')})")

        # -- phase 2: starve probes; burn must rise, policy must recover ---
        # The device scorer is forced here (phase 1 keeps the production
        # auto routing because its >=3x timing assertion is about the
        # index family, not the scorer): this phase's assertions are
        # burn/widen/recover — timing-free — so it is where the fused
        # gather+score kernel soaks under live serving, the probe policy
        # widening through its compiled-shape ladder as nprobe moves.
        env2 = dict(env,
                    KNN_TPU_IVF_SCORER="device",
                    KNN_TPU_PROBE_COOLDOWN_MS="800",
                    KNN_TPU_PROBE_EVAL_MS="100")
        proc, base = boot(index, env2, shadow_flags + [
            "--ivf-probes", "1",
            "--ivf-recall-floor", str(args.recall_floor)])
        if base is None:
            return fail(f"phase-2 serve: no ready banner "
                        f"(rc={proc.poll()})", proc)
        stop = threading.Event()
        lock = threading.Lock()
        viol2: list = []

        def bg_loop(cid):
            i = cid * 13
            q = test.features.shape[0]
            while not stop.is_set():
                lo = (7 * i) % max(1, q - args.rows)
                i += 1
                try:
                    st, body = http(base, "/predict", {
                        "instances":
                            test.features[lo:lo + args.rows].tolist()})
                    if st == 500:
                        with lock:
                            viol2.append(f"500: {body[:120]}")
                except Exception as e:  # noqa: BLE001
                    with lock:
                        viol2.append(f"transport: {e}")

        clients = [threading.Thread(target=bg_loop, args=(c,), daemon=True)
                   for c in range(args.clients)]
        for t in clients:
            t.start()
        burn_peak = 0.0
        burned = widened = recovered = False
        nprobe_seen = 1
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            doc = quality_doc(base)
            burns = doc["slo_quality"]["burn_rates"]
            short = burns.get("5s", max(burns.values(), default=0.0))
            burn_peak = max(burn_peak, max(burns.values(), default=0.0))
            if burn_peak > 1.0:
                burned = True
            _, hb = http(base, "/healthz")
            ivf_block = json.loads(hb).get("ivf") or {}
            nprobe_seen = max(nprobe_seen, ivf_block.get("nprobe", 1))
            if burned and nprobe_seen > 1:
                widened = True
            if widened and short <= 1.0:
                recovered = True
                break
            time.sleep(0.25)
        stop.set()
        for t in clients:
            t.join(timeout=90)
        if viol2:
            return fail(f"phase-2 serving violations: {viol2[:3]}", proc)
        if not burned:
            return fail(f"quality burn never rose above 1 with nprobe "
                        f"starved to 1 (peak {burn_peak:.2f}) — the "
                        f"recall violation went undetected", proc)
        if not widened:
            return fail(f"probe policy never widened nprobe past 1 "
                        f"(burn peak {burn_peak:.2f}) — the quality loop "
                        f"is open", proc)
        if not recovered:
            return fail(f"short-window quality burn did not recover "
                        f"<= 1.0 after widening to nprobe "
                        f"{nprobe_seen}", proc)
        moves = (ivf_block.get("moves") or {})
        print(f"ivf-soak: phase 2 ok — burn peaked {burn_peak:.1f}, "
              f"policy widened 1 -> {nprobe_seen} "
              f"({moves.get('widen', '?')} widen moves), short-window "
              f"burn recovered <= 1")
        rc = shutdown(proc)
        if rc != 0:
            return fail(f"phase-2 serve exited rc={rc}")

        report = {
            "ivf_soak": {
                "train_rows": train.num_instances,
                "cells": args.cells,
                "probes": args.probes,
                "recall_floor": args.recall_floor,
                "rows_per_request": args.rows,
                "clients": args.clients,
                "window_s": args.window_s,
            },
            "phase1": {
                "exact_rows_per_s": round(exact_qps, 1),
                "ivf_rows_per_s": round(ivf_qps, 1),
                "speedup": round(speedup, 2),
                "min_speedup": args.min_speedup,
                "recall_sli": recall,
                "scored": sh["scored"],
                "shed": sh["shed"],
            },
            "phase2": {
                "burn_peak": round(burn_peak, 2),
                "widened_to_nprobe": nprobe_seen,
                "recovered": True,
            },
        }
        out = json.dumps(report, indent=2)
        print(out)
        if args.json_out:
            Path(args.json_out).parent.mkdir(parents=True, exist_ok=True)
            Path(args.json_out).write_text(out + "\n")
        print("ivf-soak: PASS")
        return 0


if __name__ == "__main__":
    sys.exit(main())
