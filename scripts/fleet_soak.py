"""Fleet-soak gate (`make fleet-soak`): replica sets held to their
contracts (docs/SERVING.md §Running a replica set).

Topology under test: 3 `knn_tpu serve --mutable on` replicas (1 primary
with ``--replicate-to``, 2 followers with ``--follower-of``) behind a
`knn_tpu route` router with auto-failover armed — every client request
in this gate goes through the ROUTER, exactly as production traffic
would.

**Phase 1 — follower SIGKILL under load.** Concurrent readers + writers
through the router; a follower's process GROUP is SIGKILLed mid-window.
Invariants: ZERO failed reads (the router retries transport failures on
a different replica), every read bit-identical to the oracle replay of
the primary's durable WAL at that read's ``mutation_seq``, and the
router's /healthz marks the dead replica unusable.

**Phase 2 — primary SIGKILL + failover.** The primary is SIGKILLed under
the same load. Invariants: reads never fail; writes return typed 503
(never a traceback, never a hang) until ``--auto-failover`` promotes the
most-caught-up follower, then resume; ZERO acknowledged writes lost —
every client-acked (seq, rows) pair must appear bit-identical in the NEW
primary's WAL (semi-synchronous ack is what makes this exact), and reads
replay bit-identically against that WAL. Reads that observed the dead
primary's unreplicated tail (seq past the takeover point, served before
the promote) are excluded and counted — that pre-ack visibility is the
documented read-uncommitted window, not a correctness loss.

**Phase 3 — ex-primary rejoin.** The killed primary reboots
``--follower-of`` the new primary: its unacknowledged WAL tail past the
takeover seq is truncated, it catches up over wal-append (digest-checked
overlap, no divergence), lag drains, and a read served directly by the
rejoined replica replays bit-identical.

**Forensics — the observability plane audits the incident** (after
phase 3, same router). Sampled 200 reads must resolve via router
``GET /debug/requests?id=`` to ONE stitched cross-tier timeline whose
router-side phases sum to ~the router-observed wall, linked to the
answering replica's timeline for the same id; the audit log's
failover-window event must agree with the client-measured 503 span;
``knn_fleet_replication_lag_seq`` must be back to 0 fleet-wide after
the rejoin; the stitched Perfetto export lands in ``build/`` as the CI
artifact.

**Phase 5 — blank-follower bootstrap under live traffic.** A replica's
directory is wiped to NOTHING and the process rebooted
``--follower-of`` the primary: the CLI pulls the primary's committed
generation over the chunked, digest-verified ``/admin/snapshot``
transfer, commits it atomically, then drains the WAL gap through the
normal shipping path to lag 0 — "add a replica is one command", with
zero failed reads throughout.

**Phase 6 — rolling-restart upgrade.** Every replica is replaced one at
a time under closed-loop load (followers behind the router's retry
shield, the primary via auto-failover). Invariants: ZERO failed reads,
writes resume after the typed 503 window, and ZERO acknowledged writes
lost — every client-acked (seq, rows) pair bit-identical in the oracle
replay of the surviving WAL.

**Phase 7 — partition/rejoin divergence drill.** An isolated follower
accepts a forged WAL record the primary never shipped, then the fleet
writes through: same seq, different content. The digest-overlap
backstop must fire as a typed ``WALDivergence`` (shipper parks
``diverged``), the router's auto-bootstrap leg must re-seed the
follower with no operator action and no primary restart
(``reseed-begin``/``reseed-complete`` in the audit log), and the healed
follower must answer bit-identically to the true lineage — never a
divergent 200 outside the bounded, counted divergence window.

**Phase 4 — coordinated reload under a crash-stop** (runs last, on its
own fleet; the number is historical). A fresh immutable 3-replica fleet
(hot reload is the immutable-serving operation — the mutable tier owns
its own artifact lifecycle). One replica is crash-stopped, then the
router is asked to reload: the attempt must fail typed with
``rolled_back: true`` and every LIVE replica still on the old version
(all-or-nothing). The dead replica is rebooted and the retry must land
every replica on the new version.

Every terminal outcome in every phase must be typed JSON — a traceback
body anywhere fails the gate. Exit 0 when every invariant holds; 1 with
a diagnosis.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)
from mutable_soak import (  # noqa: E402 — shared soak machinery
    BOOT_TIMEOUT_S,
    READY_RE,
    Mirror,
    http,
)


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: ~6 s load windows")
    p.add_argument("--window-s", type=float, default=None)
    p.add_argument("--writers", type=int, default=2)
    p.add_argument("--readers", type=int, default=3)
    p.add_argument("--rows", type=int, default=4)
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--json-out", default=None, metavar="FILE")
    args = p.parse_args()
    if args.window_s is None:
        args.window_s = 6.0 if args.short else 15.0
    return args


def fail(msg: str) -> int:
    print(f"fleet-soak: FAIL: {msg}", file=sys.stderr)
    return 1  # procgroup's atexit sweep reaps every spawned group


def free_ports(n: int) -> "list[int]":
    """Reserve n distinct ephemeral ports (bind, read, close). A
    collision later fails the boot loudly rather than corrupting the
    gate."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def spawn(cmd, env):
    proc = procgroup.popen_group(
        [sys.executable, "-m", "knn_tpu.cli", *cmd],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO,
    )
    import queue

    lines: "queue.Queue[str]" = queue.Queue()
    threading.Thread(
        target=lambda: [lines.put(ln) for ln in proc.stdout], daemon=True,
    ).start()
    return proc, lines


def wait_ready(proc, lines, what: str):
    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while time.monotonic() < deadline:
        try:
            line = lines.get(timeout=min(1.0, max(
                0.01, deadline - time.monotonic())))
        except Exception:  # noqa: BLE001 — queue.Empty
            if proc.poll() is not None:
                return None
            continue
        m = READY_RE.search(line)  # serve and route share the banner form
        if m:
            print(f"fleet-soak: {what}: {line.rstrip()}")
            return m.group(1)
    return None


def healthz(base) -> dict:
    _st, body = http(base, "/healthz")
    return json.loads(body)


def wait_until(pred, timeout_s: float, every_s: float = 0.2):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            v = pred()
        except Exception:  # noqa: BLE001 — target mid-reboot
            v = None
        if v:
            return v
        time.sleep(every_s)
    return None


def build_wal_mirror(base_features, k, metric, replica_url) -> Mirror:
    """The oracle: replay the replica's own durable WAL (insert-only in
    this gate) via ``GET /admin/wal-since`` — gapless by the engine's
    seq contract, so every served ``mutation_seq`` is verifiable."""
    import numpy as np

    mirror = Mirror(base_features, k, metric)
    cursor = 0
    while True:
        st, body = http(replica_url,
                        f"/admin/wal-since?seq={cursor}&limit=512")
        if st != 200:
            raise RuntimeError(f"wal-since on {replica_url}: {st}: "
                               f"{body[:200]}")
        records = json.loads(body)["records"]
        if not records:
            return mirror
        for rec in records:
            if rec["op"] != "insert":
                raise RuntimeError(f"unexpected op {rec['op']!r} in the "
                                   f"insert-only fleet soak WAL")
            mirror.ack(rec["seq"], "insert",
                       np.asarray(rec["rows"], np.float32))
            cursor = rec["seq"]


class FleetLoad:
    """Readers + writers through the ROUTER. Readers treat ANY non-200
    as a failure (the router's whole job is that reads never fail while
    a replica survives); writers tolerate the typed 503 failover window
    (counted) and require every such body to be JSON with an ``error``
    field — never a traceback."""

    def __init__(self, router: str, test_x, num_classes, args):
        import numpy as np

        self.np = np
        self.router = router
        self.test_x = test_x
        self.num_classes = num_classes
        self.args = args
        self.stop = threading.Event()
        self.lock = threading.Lock()
        self.reads: list = []        # (inst, seq, version, d, i, t_mono)
        self.reads_ok = 0
        self.read_failures: list = []
        self.acked: list = []        # (seq, rows) the client got a 200 for
        self.writes_ok = 0
        self.writes_503 = 0
        # Client-observed failover window: first typed 503 -> first 200
        # after it. The router's own failover-window SLI must agree with
        # this independent measurement (the forensics phase checks).
        self.first_503_t = None
        self.first_ok_after_503_t = None
        self.write_failures: list = []
        self.versions_seen: set = set()
        self.threads: list = []

    def _typed_or_fail(self, body: str, where: str):
        try:
            doc = json.loads(body)
            if not isinstance(doc, dict) or "error" not in doc:
                raise ValueError("no error field")
            return doc
        except ValueError:
            with self.lock:
                self.write_failures.append(
                    f"{where}: non-JSON terminal body: {body[:160]}")
            return None

    def _writer(self, wid: int):
        rng = self.np.random.default_rng(self.args.seed * 1000 + wid)
        d = self.test_x.shape[1]
        while not self.stop.is_set():
            m = int(rng.integers(1, 3))
            rows = rng.uniform(0, 4, (m, d)).astype(self.np.float32)
            labels = rng.integers(0, self.num_classes, m).tolist()
            try:
                st, body = http(self.router, "/insert",
                                {"rows": rows.tolist(), "labels": labels})
            except Exception as e:  # noqa: BLE001 — the ROUTER died
                with self.lock:
                    self.write_failures.append(f"router transport: {e}")
                time.sleep(0.05)
                continue
            if st == 200:
                doc = json.loads(body)
                with self.lock:
                    self.writes_ok += 1
                    self.acked.append((doc["seq"], rows))
                    if (self.first_503_t is not None
                            and self.first_ok_after_503_t is None):
                        self.first_ok_after_503_t = time.monotonic()
            elif st == 503:
                # The typed failover window / replication-ack timeout.
                # An applied-but-unconfirmed 503 is NOT an ack: the
                # client was told so, and the lost-write accounting
                # below only covers 200s.
                if self._typed_or_fail(body, "write 503") is not None:
                    with self.lock:
                        self.writes_503 += 1
                        if self.first_503_t is None:
                            self.first_503_t = time.monotonic()
                time.sleep(0.05)
            elif st in (429, 502):
                self._typed_or_fail(body, f"write {st}")
                time.sleep(0.05)
            else:
                with self.lock:
                    self.write_failures.append(
                        f"write status {st}: {body[:160]}")
            time.sleep(0.004)

    def _reader(self, rid: int):
        rng = self.np.random.default_rng(self.args.seed * 2000 + rid)
        q = self.test_x.shape[0]
        r = self.args.rows
        while not self.stop.is_set():
            lo = int(rng.integers(0, max(1, q - r)))
            inst = self.test_x[lo:lo + r]
            try:
                st, body = http(self.router, "/kneighbors",
                                {"instances": inst.tolist()})
            except Exception as e:  # noqa: BLE001 — the ROUTER died
                with self.lock:
                    self.read_failures.append(f"router transport: {e}")
                continue
            if st != 200:
                with self.lock:
                    self.read_failures.append(
                        f"read status {st}: {body[:200]}")
                continue
            doc = json.loads(body)
            with self.lock:
                self.reads_ok += 1
                self.versions_seen.add(doc["index_version"])
                if "mutation_seq" in doc:
                    self.reads.append(
                        (self.np.asarray(inst), doc["mutation_seq"],
                         doc["index_version"], doc["distances"],
                         doc["indices"], time.monotonic()))

    def start(self):
        self.threads = (
            [threading.Thread(target=self._writer, args=(w,), daemon=True)
             for w in range(self.args.writers)]
            + [threading.Thread(target=self._reader, args=(r,),
                                daemon=True)
               for r in range(self.args.readers)])
        for t in self.threads:
            t.start()

    def finish(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=90)
            if t.is_alive():
                self.read_failures.append("a load thread hung")


def verify_against_wal(load: FleetLoad, mirror: Mirror, v0: str, where,
                       exclude=None) -> "tuple[list, int]":
    """Lost-write accounting + bit-identity replay. Returns
    (violations, excluded_read_count)."""
    import numpy as np

    bad = []
    for seq, rows in load.acked:
        got = mirror.history.get(seq)
        if got is None:
            bad.append(f"{where}: ACKED write seq {seq} is missing from "
                       f"the surviving WAL — an acknowledged write was "
                       f"LOST")
            continue
        if got[0] != "insert" or not np.array_equal(
                np.asarray(got[1], np.float32), rows):
            bad.append(f"{where}: WAL seq {seq} carries different rows "
                       f"than the client acked")
    excluded = 0
    verifiable = []
    for inst, seq, version, dists, idx, t in load.reads:
        if exclude is not None and exclude(seq, t):
            excluded += 1
            continue
        verifiable.append((inst, seq, version, dists, idx))
    bad += mirror.verify_reads(verifiable, {v0: ()}, where)
    return bad, excluded


def main() -> int:
    args = parse_args()
    from bench import _load_medium  # noqa: E402 — repo-root import
    from knn_tpu.serve.artifact import load_index

    train, test = _load_medium()
    d = Path(__file__).parent.parent / "build" / "fixtures"
    ref = Path("/root/reference/datasets")
    train_arff = str((ref if ref.exists() else d) / "medium-train.arff")

    env = dict(os.environ, JAX_PLATFORMS="cpu", KNN_TPU_RETRY_BASE_MS="0",
               # Drill pacing: a parked shipper re-probes every 1s and the
               # router may re-drive an auto-bootstrap after 2s (production
               # defaults are 30s each) so the park -> re-seed -> resume
               # cycle in phases 5-7 completes in seconds.
               KNN_TPU_SHIP_RETRY_S="1.0",
               KNN_TPU_BOOTSTRAP_COOLDOWN_S="2.0")
    report: dict = {"fleet_soak": {
        "train_rows": train.num_instances, "writers": args.writers,
        "readers": args.readers, "window_s": args.window_s,
    }}

    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        seed_idx = tmp / "seed"
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             str(seed_idx), "--k", "5"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: "
                        f"{build.stderr}")
        model = load_index(seed_idx)
        # Byte-identical copies => every replica reports the SAME
        # index_version, which is what lets one oracle replay cover
        # reads answered by any of them.
        dirs = {}
        for name in ("r1", "r2", "r3"):
            dirs[name] = tmp / name
            shutil.copytree(seed_idx, dirs[name])

        p1, p2, p3, pr = free_ports(4)
        url = {n: f"http://127.0.0.1:{p}"
               for n, p in (("r1", p1), ("r2", p2), ("r3", p3))}
        serve_common = ["--max-batch", "32", "--max-wait-ms", "1",
                        "--mutable", "on", "--compact-interval-s", "0",
                        "--compact-threshold", "1000000"]

        def port_of(u: str) -> str:
            return u.rsplit(":", 1)[1]

        def boot_follower(name: str, primary: str):
            proc, lines = spawn(
                ["serve", str(dirs[name]), "--port", port_of(url[name]),
                 *serve_common, "--follower-of", primary], env)
            return proc, wait_ready(proc, lines, name)

        procs = {}
        procs["r2"], b2 = boot_follower("r2", url["r1"])
        procs["r3"], b3 = boot_follower("r3", url["r1"])
        procs["r1"], lines1 = spawn(
            ["serve", str(dirs["r1"]), "--port", port_of(url["r1"]),
             *serve_common, "--replicate-to",
             f"{url['r2']},{url['r3']}", "--replicate-ack", "any",
             "--replicate-ack-timeout-s", "10"], env)
        b1 = wait_ready(procs["r1"], lines1, "r1")
        if None in (b1, b2, b3):
            return fail(f"replica boot failed (ready: r1={b1}, r2={b2}, "
                        f"r3={b3})")
        build_dir = REPO / "build"
        build_dir.mkdir(exist_ok=True)
        event_log_path = build_dir / "fleet-soak-events.jsonl"
        event_log_path.unlink(missing_ok=True)
        router_proc, router_lines = spawn(
            ["route", url["r1"], url["r2"], url["r3"],
             "--port", str(pr), "--health-interval-s", "0.25",
             "--auto-failover", "on", "--failover-after-s", "1.0",
             "--hedge-ms", "auto",
             "--event-log", str(event_log_path)], env)
        router = wait_ready(router_proc, router_lines, "router")
        if router is None:
            return fail(f"router boot failed (rc={router_proc.poll()})")
        v0 = healthz(url["r1"])["index_version"]
        for name in ("r2", "r3"):
            if healthz(url[name])["index_version"] != v0:
                return fail(f"{name} booted a different index_version "
                            f"than the primary — the copies diverged")

        # ---- phase 1: follower SIGKILL under load ------------------------
        load = FleetLoad(router, test.features, train.num_classes, args)
        load.start()
        time.sleep(args.window_s / 3)
        procgroup.kill_group(procs["r3"])
        kill_t = time.monotonic()
        time.sleep(2 * args.window_s / 3)
        load.finish()
        if load.read_failures:
            return fail(f"phase-1 failed reads after a follower "
                        f"SIGKILL: {load.read_failures[:3]}")
        if load.write_failures:
            return fail(f"phase-1 write violations: "
                        f"{load.write_failures[:3]}")
        if load.reads_ok < 50 or load.writes_ok < 10:
            return fail(f"too little load to trust phase 1 "
                        f"({load.reads_ok} reads, {load.writes_ok} "
                        f"writes)")
        stray = load.versions_seen - {v0}
        if stray:
            return fail(f"phase-1 reads carried unknown version(s) "
                        f"{stray} (want {v0} fleet-wide)")
        mirror = build_wal_mirror(model.train_.features, model.k,
                                  model.metric, url["r1"])
        bad, _ = verify_against_wal(load, mirror, v0, "phase-1")
        if bad:
            return fail("; ".join(bad[:3]))
        h = healthz(router)
        if h["replicas"][url["r3"]]["healthy"]:
            return fail(f"router still reports the SIGKILLed follower "
                        f"healthy {time.monotonic() - kill_t:.1f}s "
                        f"after the kill")
        report["phase1"] = {
            "reads_verified": len(load.reads), "reads_ok": load.reads_ok,
            "writes_ok": load.writes_ok,
            "acked_writes": len(load.acked),
        }
        print(f"fleet-soak: phase 1 ok — follower SIGKILL under load: "
              f"{load.reads_ok} reads, ZERO failed; {len(load.reads)} "
              f"replayed bit-identical; router demoted the corpse")

        # Reboot the killed follower before phase 2 (a follower rejoin
        # in its own right): the semi-synchronous ack needs a live
        # follower to confirm against, and a healthy fleet is the
        # stated starting point of the primary-loss leg.
        procs["r3"], b3 = boot_follower("r3", url["r1"])
        if b3 is None:
            return fail(f"follower reboot before phase 2 failed "
                        f"(rc={procs['r3'].poll()})")
        if not wait_until(
                lambda: (healthz(url["r3"])["mutable"]["seq"]
                         >= healthz(url["r1"])["mutable"]["seq"]),
                timeout_s=30):
            return fail("rebooted follower never caught up before "
                        "phase 2")
        if not wait_until(lambda: healthz(router)["usable"] == 3,
                          timeout_s=20):
            return fail("router never saw 3 usable replicas before "
                        "phase 2")

        # ---- phase 2: primary SIGKILL -> typed 503 -> promote ------------
        load = FleetLoad(router, test.features, train.num_classes, args)
        load.start()
        time.sleep(args.window_s / 3)
        procgroup.kill_group(procs["r1"])

        def new_primary():
            p = healthz(router).get("primary")
            return p if p and p != url["r1"] else None

        promoted = wait_until(new_primary, timeout_s=30)
        t_promote = time.monotonic()
        if promoted not in (url["r2"], url["r3"]):
            load.finish()
            return fail(f"auto-failover did not promote a surviving "
                        f"follower (primary={promoted!r}, want one of "
                        f"{url['r2']}/{url['r3']})")
        with load.lock:
            writes_at_promote = load.writes_ok
        time.sleep(args.window_s / 3)
        load.finish()
        # The client-observed failover window (first typed 503 -> first
        # 200 after it), kept for the forensics phase to reconcile
        # against the router's own failover-window audit event.
        client_window_s = None
        if (load.first_503_t is not None
                and load.first_ok_after_503_t is not None):
            client_window_s = (load.first_ok_after_503_t
                               - load.first_503_t)
        if load.read_failures:
            return fail(f"phase-2 failed reads during primary failover: "
                        f"{load.read_failures[:3]}")
        if load.write_failures:
            return fail(f"phase-2 write violations: "
                        f"{load.write_failures[:3]}")
        if load.writes_503 < 1:
            return fail("phase-2 never saw the typed 503 failover "
                        "window — the kill landed outside the write "
                        "path?")
        if load.writes_ok <= writes_at_promote:
            return fail(f"phase-2: writes never resumed after the "
                        f"promote ({load.writes_ok} total, "
                        f"{writes_at_promote} pre-promote)")
        cap = healthz(promoted)["fleet"]["promoted_at_seq"]
        if cap is None:
            return fail("promoted replica reports no promoted_at_seq")
        mirror = build_wal_mirror(model.train_.features, model.k,
                                  model.metric, promoted)
        # Reads that observed the dead primary's unreplicated tail:
        # served BEFORE the promote with a seq past the takeover point.
        bad, excluded = verify_against_wal(
            load, mirror, v0, "phase-2",
            exclude=lambda seq, t: seq > cap and t < t_promote)
        if bad:
            return fail("; ".join(bad[:3]))
        report["phase2"] = {
            "reads_verified": len(load.reads) - excluded,
            "reads_excluded_unreplicated_tail": excluded,
            "reads_ok": load.reads_ok,
            "writes_503_window": load.writes_503,
            "writes_after_promote": load.writes_ok - writes_at_promote,
            "acked_writes": len(load.acked),
            "takeover_seq": cap,
            "promoted": promoted,
        }
        print(f"fleet-soak: phase 2 ok — primary SIGKILL: "
              f"{load.writes_503} typed-503 writes in the window, "
              f"promote to {promoted} at seq {cap}, writes resumed "
              f"({load.writes_ok - writes_at_promote} post-promote), "
              f"zero acked writes lost, "
              f"{len(load.reads) - excluded} reads replay bit-identical "
              f"({excluded} pre-ack tail reads excluded)")

        # ---- phase 3: ex-primary rejoin ----------------------------------
        procs["r1"], b1 = boot_follower("r1", promoted)
        if b1 is None:
            return fail(f"phase-3 rejoin boot failed "
                        f"(rc={procs['r1'].poll()})")
        caught_up = wait_until(
            lambda: (healthz(url["r1"])["mutable"]["seq"]
                     >= healthz(promoted)["mutable"]["seq"]),
            timeout_s=30)
        if not caught_up:
            s1 = healthz(url["r1"])["mutable"]["seq"]
            s2 = healthz(promoted)["mutable"]["seq"]
            return fail(f"phase-3 rejoin never caught up (r1 seq {s1}, "
                        f"primary seq {s2})")
        ship = (healthz(promoted)["fleet"]["followers"]
                or {}).get(url["r1"], {})
        if ship.get("state") in ("diverged", "behind_fold", "rejected"):
            return fail(f"phase-3 rejoin shipping failed: {ship}")
        st, body = http(url["r1"], "/kneighbors",
                        {"instances": test.features[:args.rows].tolist()})
        if st != 200:
            return fail(f"phase-3 read on the rejoined replica: {st}")
        doc = json.loads(body)
        mirror = build_wal_mirror(model.train_.features, model.k,
                                  model.metric, promoted)
        bad = mirror.verify_reads(
            [(test.features[:args.rows], doc["mutation_seq"],
              doc["index_version"], doc["distances"], doc["indices"])],
            {v0: ()}, "phase-3")
        if bad:
            return fail("; ".join(bad))
        report["phase3"] = {
            "rejoined_seq": healthz(url["r1"])["mutable"]["seq"],
            "ship_state": ship.get("state"),
        }
        print(f"fleet-soak: phase 3 ok — ex-primary rejoined as "
              f"follower, caught up to seq "
              f"{report['phase3']['rejoined_seq']} with no divergence, "
              f"reads bit-identical")

        # ---- forensics: the observability plane audits the incident ------
        # The router lived through the whole primary-loss incident. Its
        # observability plane must now tell the story back, and the story
        # must agree with what the load harness measured independently:
        #   (a) a sampled 200 read resolves via GET /debug/requests?id=
        #       to ONE stitched cross-tier timeline whose router-side
        #       phases sum to ~the router-observed wall, linked to the
        #       answering replica's own timeline for the SAME id;
        #   (b) the audit log's failover-window SLI agrees with the
        #       client-measured 503 span;
        #   (c) replication lag (knn_fleet_replication_lag_seq) is back
        #       to 0 fleet-wide after the rejoin catch-up;
        #   (d) the stitched Perfetto export lands in build/ for CI.
        import urllib.request as _rq

        def traced_read(rid: str):
            req = _rq.Request(
                router + "/kneighbors",
                data=json.dumps({"instances":
                                 test.features[:args.rows].tolist()}
                                ).encode(),
                headers={"Content-Type": "application/json",
                         "x-request-id": rid})
            with _rq.urlopen(req, timeout=60) as r:
                return r.status, r.read().decode()

        stitched_docs = []
        for i in range(3):
            rid = f"soak-forensic-{i:02d}"
            st, body = traced_read(rid)
            if st != 200:
                return fail(f"forensics: traced read {rid} got {st}: "
                            f"{body[:200]}")
            st, body = http(router, f"/debug/requests?id={rid}")
            if st != 200:
                return fail(f"forensics: /debug/requests?id={rid} -> "
                            f"{st}: {body[:300]}")
            doc = json.loads(body)
            tl = doc["router"]
            if tl["request_id"] != rid or tl["outcome"] != "ok":
                return fail(f"forensics: router timeline for {rid} is "
                            f"wrong: {json.dumps(tl)[:300]}")
            wall = tl["request_ms"]
            phase_sum = sum(p["ms"] or 0.0 for p in tl["phases"])
            if abs(wall - phase_sum) > max(0.25 * wall, 20.0):
                return fail(f"forensics: {rid}: router phases sum to "
                            f"{phase_sum:.3f} ms but the router observed "
                            f"a {wall:.3f} ms wall — the timeline has a "
                            f"hole")
            answered = [u for u, r_tl in doc["replicas"].items()
                        if r_tl is not None
                        and r_tl.get("request_id") == rid]
            if not answered:
                return fail(f"forensics: {rid}: no replica timeline "
                            f"stitched in — the cross-tier link is "
                            f"broken ({json.dumps(doc)[:300]})")
            stitched_docs.append((rid, doc))

        # (d) the Perfetto render of the first sampled read: one process
        # track per tier, saved as the CI artifact.
        rid0 = stitched_docs[0][0]
        st, body = http(router,
                        f"/debug/requests?id={rid0}&format=perfetto")
        if st != 200:
            return fail(f"forensics: perfetto export -> {st}")
        trace_doc = json.loads(body)
        pids = {e["pid"] for e in trace_doc.get("traceEvents", [])}
        if len(pids) < 2:
            return fail(f"forensics: the stitched Perfetto trace has "
                        f"{len(pids)} process track(s) — want the router "
                        f"AND at least one replica tier")
        trace_path = build_dir / "fleet-soak-trace.json"
        trace_path.write_text(json.dumps(trace_doc) + "\n")

        # (b) the audit log vs the client's stopwatch.
        st, body = http(router, "/debug/events")
        if st != 200:
            return fail(f"forensics: /debug/events -> {st}: {body[:200]}")
        events_doc = json.loads(body)
        windows = [e for e in events_doc["events"]
                   if e["event"] == "failover-window"]
        if not windows:
            return fail("forensics: no failover-window audit event — "
                        "phase 2's incident left no trace in the log")
        audit_window_s = windows[0]["window_ms"] / 1e3
        if client_window_s is None:
            return fail("forensics: the load harness never bracketed the "
                        "503 window (no 503 or no recovery 200 observed)")
        if abs(audit_window_s - client_window_s) > max(
                2.0, 0.5 * client_window_s):
            return fail(f"forensics: the audit log claims a "
                        f"{audit_window_s:.2f}s failover window but the "
                        f"client measured {client_window_s:.2f}s — the "
                        f"SLI is lying")
        promotes = [e for e in events_doc["events"]
                    if e["event"] in ("promote", "auto-failover")]
        if not promotes:
            return fail("forensics: the promote left no audit event")
        if not event_log_path.exists() or not event_log_path.stat().st_size:
            return fail(f"forensics: --event-log {event_log_path} was "
                        f"never written")

        # (c) replication lag is back to 0 fleet-wide. /healthz refreshes
        # the router's lag gauges from the live role/seq documents; the
        # federated /metrics then carries every tier's copy.
        def lag_drained():
            healthz(router)
            with _rq.urlopen(router + "/metrics", timeout=30) as r:
                text = r.read().decode()
            import re
            vals = [float(m) for m in re.findall(
                r'knn_fleet_replication_lag_seq\{[^}]*\}\s+([0-9.e+-]+)',
                text)]
            return (vals and all(v == 0.0 for v in vals), len(vals))

        drained = wait_until(lambda: lag_drained()[0], timeout_s=30)
        if not drained:
            ok, n = lag_drained()
            return fail(f"forensics: knn_fleet_replication_lag_seq never "
                        f"drained to 0 fleet-wide after the rejoin "
                        f"({n} samples)")
        report["forensics"] = {
            "stitched_reads": len(stitched_docs),
            "audit_failover_window_s": round(audit_window_s, 3),
            "client_failover_window_s": round(client_window_s, 3),
            "trace_artifact": str(trace_path),
            "event_log": str(event_log_path),
        }
        print(f"fleet-soak: forensics ok — {len(stitched_docs)} reads "
              f"resolve to stitched cross-tier timelines (phase sums "
              f"match walls); audit failover window "
              f"{audit_window_s:.2f}s vs client {client_window_s:.2f}s; "
              f"replication lag drained to 0; Perfetto artifact at "
              f"{trace_path}")

        # ---- phase 5: blank-follower bootstrap under live traffic --------
        # "Adding a replica under live traffic is ONE command"
        # (docs/SERVING.md): wipe the ex-primary's directory to NOTHING
        # and reboot it --follower-of the promoted primary. The CLI must
        # pull the primary's committed generation over the chunked,
        # digest-verified /admin/snapshot transfer, commit it atomically
        # (CURRENT.json), then drain the WAL gap through the normal
        # shipping path until lag is 0 — all while client traffic keeps
        # flowing through the router with ZERO failed reads.
        load = FleetLoad(router, test.features, train.num_classes, args)
        load.start()
        time.sleep(args.window_s / 4)
        procgroup.kill_group(procs["r1"])
        shutil.rmtree(dirs["r1"])
        dirs["r1"].mkdir()
        procs["r1"], b1 = boot_follower("r1", promoted)
        if b1 is None:
            load.finish()
            return fail(f"phase-5 blank-follower boot failed "
                        f"(rc={procs['r1'].poll()})")
        if not (dirs["r1"] / "CURRENT.json").exists():
            load.finish()
            return fail("phase-5: the blank follower booted without a "
                        "snapshot install (no CURRENT.json committed)")

        def p5_ship():
            return (healthz(promoted)["fleet"]["followers"]
                    or {}).get(url["r1"], {})

        def p5_caught_up():
            return (p5_ship().get("state") == "ok"
                    and healthz(url["r1"])["mutable"]["seq"]
                    >= healthz(promoted)["mutable"]["seq"])

        if not wait_until(p5_caught_up, timeout_s=45):
            load.finish()
            return fail(f"phase-5: the blank follower never drained lag "
                        f"to 0 (ship {p5_ship()})")
        time.sleep(args.window_s / 4)
        load.finish()
        if load.read_failures:
            return fail(f"phase-5 failed reads during the blank-follower "
                        f"bootstrap: {load.read_failures[:3]}")
        if load.write_failures:
            return fail(f"phase-5 write violations: "
                        f"{load.write_failures[:3]}")
        if load.reads_ok < 50 or load.writes_ok < 10:
            return fail(f"too little load to trust phase 5 "
                        f"({load.reads_ok} reads, {load.writes_ok} "
                        f"writes)")
        mirror = build_wal_mirror(model.train_.features, model.k,
                                  model.metric, promoted)
        bad, _ = verify_against_wal(load, mirror, v0, "phase-5")
        if bad:
            return fail("; ".join(bad[:3]))
        st, body = http(url["r1"], "/kneighbors",
                        {"instances": test.features[:args.rows].tolist()})
        if st != 200:
            return fail(f"phase-5 read on the re-seeded replica: {st}")
        doc = json.loads(body)
        bad = mirror.verify_reads(
            [(test.features[:args.rows], doc["mutation_seq"],
              doc["index_version"], doc["distances"], doc["indices"])],
            {v0: ()}, "phase-5 direct read")
        if bad:
            return fail("; ".join(bad))
        report["phase5"] = {
            "reads_verified": len(load.reads), "reads_ok": load.reads_ok,
            "acked_writes": len(load.acked),
            "bootstrapped_seq": healthz(url["r1"])["mutable"]["seq"],
        }
        print(f"fleet-soak: phase 5 ok — blank-dir follower bootstrapped "
              f"from the primary's snapshot under live load, drained lag "
              f"to 0 at seq {report['phase5']['bootstrapped_seq']}; "
              f"{load.reads_ok} reads, ZERO failed; {len(load.reads)} "
              f"replayed bit-identical")

        # ---- phase 6: rolling-restart upgrade under load -----------------
        # Replace EVERY replica one at a time under closed-loop load —
        # the zero-downtime upgrade drill. Followers restart behind the
        # router's retry shield (zero failed reads); the primary's turn
        # rides auto-failover (typed 503 window, then writes resume);
        # afterwards the oracle replay of the surviving WAL must hold
        # every client-acked (seq, rows) pair bit-identical — a rolling
        # upgrade may never lose an acknowledged write.
        name_of = {u: n for n, u in url.items()}
        current_primary = promoted
        load = FleetLoad(router, test.features, train.num_classes, args)
        load.start()
        time.sleep(args.window_s / 4)
        restart_order = [n for n in ("r1", "r2", "r3")
                         if url[n] != current_primary]
        for name in restart_order:
            procgroup.kill_group(procs[name])
            procs[name], b = boot_follower(name, current_primary)
            if b is None:
                load.finish()
                return fail(f"phase-6 {name} restart failed "
                            f"(rc={procs[name].poll()})")
            if not wait_until(
                    lambda n=name, p=current_primary: (
                        healthz(url[n])["mutable"]["seq"]
                        >= healthz(p)["mutable"]["seq"]),
                    timeout_s=45):
                load.finish()
                return fail(f"phase-6: restarted follower {name} never "
                            f"caught up")
            if not wait_until(lambda: healthz(router)["usable"] == 3,
                              timeout_s=20):
                load.finish()
                return fail(f"phase-6: router never saw 3 usable "
                            f"replicas after restarting {name} — the "
                            f"restart was not rolling")
        # The primary's own turn: kill it, let auto-failover promote,
        # reboot the ex-primary as a follower of the new primary.
        old_primary = current_primary
        procgroup.kill_group(procs[name_of[old_primary]])

        def p6_new_primary():
            p = healthz(router).get("primary")
            return p if p and p != old_primary else None

        current_primary = wait_until(p6_new_primary, timeout_s=30)
        t_promote6 = time.monotonic()
        if current_primary is None:
            load.finish()
            return fail("phase-6: auto-failover never promoted a "
                        "survivor after the primary's restart turn")
        with load.lock:
            writes_at_promote6 = load.writes_ok
        procs[name_of[old_primary]], b = boot_follower(
            name_of[old_primary], current_primary)
        if b is None:
            load.finish()
            return fail(f"phase-6 ex-primary reboot failed "
                        f"(rc={procs[name_of[old_primary]].poll()})")
        if not wait_until(
                lambda: (healthz(old_primary)["mutable"]["seq"]
                         >= healthz(current_primary)["mutable"]["seq"]),
                timeout_s=45):
            load.finish()
            return fail("phase-6: the restarted ex-primary never caught "
                        "up")
        if not wait_until(lambda: healthz(router)["usable"] == 3,
                          timeout_s=20):
            load.finish()
            return fail("phase-6: router never recovered 3 usable "
                        "replicas after the rolling restart")
        time.sleep(args.window_s / 4)
        load.finish()
        if load.read_failures:
            return fail(f"phase-6 failed reads during the rolling "
                        f"restart: {load.read_failures[:3]}")
        if load.write_failures:
            return fail(f"phase-6 write violations: "
                        f"{load.write_failures[:3]}")
        if load.writes_503 < 1:
            return fail("phase-6 never saw the typed 503 window — the "
                        "primary's restart turn landed outside the "
                        "write path?")
        if load.writes_ok <= writes_at_promote6:
            return fail(f"phase-6: writes never resumed after the "
                        f"promote ({load.writes_ok} total, "
                        f"{writes_at_promote6} pre-promote)")
        cap6 = healthz(current_primary)["fleet"]["promoted_at_seq"]
        if cap6 is None:
            return fail("phase-6 promoted replica reports no "
                        "promoted_at_seq")
        mirror = build_wal_mirror(model.train_.features, model.k,
                                  model.metric, current_primary)
        bad, excluded6 = verify_against_wal(
            load, mirror, v0, "phase-6",
            exclude=lambda seq, t: seq > cap6 and t < t_promote6)
        if bad:
            return fail("; ".join(bad[:3]))
        report["phase6"] = {
            "replicas_replaced": 3,
            "promoted": current_primary,
            "takeover_seq": cap6,
            "reads_verified": len(load.reads) - excluded6,
            "reads_excluded_unreplicated_tail": excluded6,
            "writes_503_window": load.writes_503,
            "acked_writes": len(load.acked),
        }
        print(f"fleet-soak: phase 6 ok — rolling restart replaced all 3 "
              f"replicas under load: ZERO failed reads, "
              f"{load.writes_503} typed-503 writes in the primary's "
              f"turn, zero acked writes lost, "
              f"{len(load.reads) - excluded6} reads replay bit-identical "
              f"({excluded6} pre-ack tail reads excluded)")

        # ---- phase 7: partition/rejoin divergence drill ------------------
        # An isolated follower accepts a WAL record the primary never
        # shipped (the partitioned-writer hazard), then the fleet writes
        # through: the primary assigns the SAME seq to DIFFERENT content.
        # The digest-overlap backstop must fire as a typed WALDivergence
        # (shipper parks "diverged" — never a silent skip), the router's
        # self-healing leg must re-seed the follower over /admin/snapshot
        # with NO operator action and NO primary restart, and the healed
        # follower must answer bit-identically to the true lineage.
        import numpy as np

        p7_primary = current_primary
        victim = [n for n in ("r1", "r2", "r3")
                  if url[n] != p7_primary][0]
        vurl = url[victim]
        if not wait_until(
                lambda: (healthz(vurl)["mutable"]["seq"]
                         == healthz(p7_primary)["mutable"]["seq"]),
                timeout_s=30):
            return fail("phase-7: the fleet never quiesced before the "
                        "divergence drill")
        s_div = healthz(p7_primary)["mutable"]["seq"]
        st, body = http(vurl,
                        f"/admin/wal-since?seq={max(0, s_div - 1)}"
                        f"&limit=8")
        if st != 200:
            return fail(f"phase-7 wal-since on the victim: {st}: "
                        f"{body[:200]}")
        recs = json.loads(body)["records"]
        if not recs:
            return fail("phase-7: no WAL record to clone for the forged "
                        "write")
        template = recs[-1]
        d_width = len(template["rows"][0])
        # The forged record: same validated shape, same lineage position
        # (seq s_div+1), content the primary will never ship. Rows sit at
        # coordinate ~1000 — far outside the dataset — so a direct probe
        # there separates "serving the forged row" from "healed".
        forged = dict(template)
        forged["seq"] = s_div + 1
        forged["rows"] = [[1000.0 + j] * d_width
                          for j in range(len(template["rows"]))]
        st, body = http(vurl, "/admin/wal-append",
                        {"records": [forged], "primary_seq": s_div + 1})
        if st != 200:
            return fail(f"phase-7: the forged record was refused ({st}: "
                        f"{body[:200]}) — the drill could not create "
                        f"divergence")
        probe = [[1000.0] * d_width]
        st, body = http(vurl, "/kneighbors", {"instances": probe})
        if st != 200:
            return fail(f"phase-7 pre-heal probe on the victim: {st}")
        div_answer = json.loads(body)
        st, body = http(p7_primary, "/kneighbors", {"instances": probe})
        if st != 200:
            return fail(f"phase-7 probe on the primary: {st}")
        pri_answer = json.loads(body)
        if div_answer["distances"] == pri_answer["distances"]:
            return fail("phase-7: the forged record did not change the "
                        "victim's answers — the drill proves nothing")
        load = FleetLoad(router, test.features, train.num_classes, args)
        load.start()

        def p7_ship():
            return (healthz(p7_primary)["fleet"]["followers"]
                    or {}).get(vurl, {})

        parked = wait_until(
            lambda: (p7_ship()
                     if p7_ship().get("state") == "diverged" else None),
            timeout_s=30)
        if parked is None:
            load.finish()
            return fail(f"phase-7: the same-seq/different-digest "
                        f"backstop never fired — shipper state never "
                        f"reached 'diverged' (ship {p7_ship()})")
        if "diverg" not in str(parked.get("last_error", "")).lower():
            load.finish()
            return fail(f"phase-7: the park was not a typed "
                        f"WALDivergence refusal: {parked}")

        def p7_healed():
            return (p7_ship().get("state") == "ok"
                    and healthz(vurl)["mutable"]["seq"] >= s_div)

        if not wait_until(p7_healed, timeout_s=60):
            load.finish()
            return fail(f"phase-7: the diverged follower never healed "
                        f"via auto-bootstrap (ship {p7_ship()})")
        t_heal = time.monotonic()
        time.sleep(args.window_s / 4)
        load.finish()
        if load.read_failures:
            return fail(f"phase-7 failed reads during the divergence "
                        f"drill: {load.read_failures[:3]}")
        if load.write_failures:
            return fail(f"phase-7 write violations: "
                        f"{load.write_failures[:3]}")
        # The audit log must tell the self-healing story: reseed-begin +
        # reseed-complete on the victim, driven by the auto trigger.
        st, body = http(router, "/debug/events")
        if st != 200:
            return fail(f"phase-7 /debug/events -> {st}")
        p7_events = json.loads(body)["events"]
        begins = [e for e in p7_events if e["event"] == "reseed-begin"
                  and e.get("follower") == vurl]
        completes = [e for e in p7_events
                     if e["event"] == "reseed-complete"
                     and e.get("follower") == vurl]
        if not begins or not completes:
            return fail(f"phase-7: the re-seed left no audit trail "
                        f"(begins={len(begins)}, "
                        f"completes={len(completes)})")
        if completes[0].get("trigger") != "auto":
            return fail(f"phase-7: the re-seed was not auto-triggered: "
                        f"{completes[0]}")
        # The healed follower: the forged row must be GONE and its
        # answer at the probe must replay bit-identical against the
        # oracle of the primary's durable WAL.
        st, body = http(vurl, "/kneighbors", {"instances": probe})
        if st != 200:
            return fail(f"phase-7 post-heal probe on the victim: {st}")
        healed_answer = json.loads(body)
        if healed_answer["distances"] == div_answer["distances"]:
            return fail("phase-7: the healed follower still serves the "
                        "forged row — the re-seed did not abandon the "
                        "divergent lineage")
        mirror = build_wal_mirror(model.train_.features, model.k,
                                  model.metric, p7_primary)
        bad = mirror.verify_reads(
            [(np.asarray(probe, np.float32),
              healed_answer["mutation_seq"],
              healed_answer["index_version"], healed_answer["distances"],
              healed_answer["indices"])],
            {v0: ()}, "phase-7 healed probe")
        if bad:
            return fail("; ".join(bad))
        # Never a divergent 200 through the router: every read outside
        # the bounded divergence window (claimed seq past the fork,
        # served before the heal) must replay bit-identical; window
        # reads are excluded AND counted, exactly like phase 2's
        # read-uncommitted accounting.
        bad, excluded7 = verify_against_wal(
            load, mirror, v0, "phase-7",
            exclude=lambda seq, t: seq > s_div and t < t_heal)
        if bad:
            return fail("; ".join(bad[:3]))
        report["phase7"] = {
            "forked_at_seq": s_div,
            "parked_error": str(parked.get("last_error"))[:160],
            "reseed_trigger": completes[0].get("trigger"),
            "reads_verified": len(load.reads) - excluded7,
            "reads_excluded_divergence_window": excluded7,
            "acked_writes": len(load.acked),
        }
        print(f"fleet-soak: phase 7 ok — forged same-seq record parked "
              f"the shipper as typed WALDivergence at seq {s_div + 1}; "
              f"auto-bootstrap re-seeded {victim} with no operator "
              f"action; healed answers replay bit-identical "
              f"({len(load.reads) - excluded7} reads verified, "
              f"{excluded7} divergence-window reads excluded)")

        # Tear the mutable fleet down before phase 4 (the immutable
        # coordinated-reload drill keeps its historical number; it runs
        # last because it boots its own fleet).
        for name in ("r1", "r2", "r3"):
            procgroup.kill_group(procs[name])
        procgroup.kill_group(router_proc)

        # ---- phase 4: coordinated reload under a crash-stop --------------
        q1, q2, q3, qr = free_ports(4)
        iurl = {n: f"http://127.0.0.1:{p}"
                for n, p in (("i1", q1), ("i2", q2), ("i3", q3))}
        idirs = {}
        for name in ("i1", "i2", "i3"):
            idirs[name] = tmp / name
            shutil.copytree(seed_idx, idirs[name])
        new_idx = tmp / "new"
        subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             str(new_idx), "--k", "5"],
            env=env, capture_output=True, text=True, cwd=REPO, check=True)

        iprocs = {}
        for name in ("i1", "i2", "i3"):
            proc, lines = spawn(
                ["serve", str(idirs[name]), "--port", port_of(iurl[name]),
                 "--max-batch", "16", "--max-wait-ms", "1"], env)
            if wait_ready(proc, lines, name) is None:
                return fail(f"phase-4 {name} boot failed")
            iprocs[name] = proc
        rproc, rlines = spawn(
            ["route", iurl["i1"], iurl["i2"], iurl["i3"],
             "--port", str(qr), "--health-interval-s", "0.25"], env)
        irouter = wait_ready(rproc, rlines, "router-4")
        if irouter is None:
            return fail("phase-4 router boot failed")
        iv0 = healthz(iurl["i1"])["index_version"]

        # Crash-stop i3, then immediately demand a coordinated reload:
        # the router's sequential confirm hits the corpse mid-sequence
        # and must roll the flipped replicas back — all-or-nothing.
        procgroup.kill_group(iprocs["i3"])
        st, body = http(irouter, "/admin/reload",
                        {"index": str(new_idx)}, timeout=600)
        doc = json.loads(body)
        if st != 502 or not doc.get("rolled_back"):
            return fail(f"phase-4 mid-crash reload: wanted 502 "
                        f"rolled_back, got {st}: {body[:300]}")
        for name in ("i1", "i2"):
            v = healthz(iurl[name])["index_version"]
            if v != iv0:
                return fail(f"phase-4 {name} is on {v} after the rolled-"
                            f"back reload (want {iv0}) — the fleet "
                            f"version DIVERGED")
        # Reboot the corpse, retry: now it must be all-or-nothing the
        # other way — every replica lands on the new version.
        proc, lines = spawn(
            ["serve", str(idirs["i3"]), "--port", port_of(iurl["i3"]),
             "--max-batch", "16", "--max-wait-ms", "1"], env)
        if wait_ready(proc, lines, "i3-reboot") is None:
            return fail("phase-4 i3 reboot failed")
        iprocs["i3"] = proc
        if not wait_until(lambda: healthz(irouter)["usable"] == 3,
                          timeout_s=20):
            return fail("phase-4: router never saw all 3 replicas "
                        "usable after the reboot")
        st, body = http(irouter, "/admin/reload",
                        {"index": str(new_idx)}, timeout=600)
        doc = json.loads(body)
        if st != 200:
            return fail(f"phase-4 retry reload: {st}: {body[:300]}")
        iv_new = doc["index_version"]
        for name in ("i1", "i2", "i3"):
            v = healthz(iurl[name])["index_version"]
            if v != iv_new:
                return fail(f"phase-4 {name} on {v} after the confirmed "
                            f"reload (want {iv_new})")
        if iv_new == iv0:
            return fail("phase-4 reload did not change the version — "
                        "the gate proved nothing")
        report["phase4"] = {
            "rolled_back_on_crash": True, "v0": iv0, "v_new": iv_new,
        }
        print(f"fleet-soak: phase 4 ok — crash-stopped replica aborted "
              f"the reload with every live replica still on {iv0}; "
              f"retry flipped all three to {iv_new}")

    out = json.dumps(report, indent=2)
    print(out)
    if args.json_out:
        Path(args.json_out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.json_out).write_text(out + "\n")
    print("fleet-soak: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
