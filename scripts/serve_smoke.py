"""Serve smoke gate (`make serve-smoke`): the full boot→probe→shutdown
lifecycle of the serving stack, as a subprocess — the one thing the pytest
suite's in-process server tests cannot cover (signal handling, the ready
banner, a real ephemeral-port bind, clean exit code).

Steps:
1. build a fixture index with `knn_tpu save-index` (small-train.arff);
2. boot `knn_tpu serve --port 0` and wait for the ready banner;
3. probe /healthz (ready, NOT draining, carries index_version + the SLO
   burn-rate block — the self-healing fields, docs/SERVING.md), /predict
   (predictions match an in-process model on the same rows, a supplied
   x-request-id echoes on header AND body), /kneighbors (shapes),
   /metrics (knn_serve_* counters present; the OpenMetrics exposition
   negotiated via Accept carries trace_id exemplars and ends `# EOF`),
   /debug/requests + /debug/slowest (the predict's request_id resolves
   to a finished timeline with closed phases; Perfetto export balanced),
   /debug/history (non-empty after two snapshot intervals) and
   /debug/alerts (no rules loaded: empty but well-formed);
4. rebuild the index and SIGHUP: the hot reload must swap index_version
   while the process keeps serving bit-identical predictions;
5. SIGINT and require a clean exit within the grace period;
6. post-mortem: `knn_tpu history` answers a range query from the dead
   server's --history-dir (the 3am path, docs/SERVING.md).

Exit 0 on success; any failure prints a diagnosis and exits 1.
stdlib-only (urllib, not curl: the gate must not depend on host tools).
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 120  # first-call compile on a cold cache can be slow
SHUTDOWN_GRACE_S = 15


def fail(msg: str, proc: "subprocess.Popen | None" = None) -> "int":
    print(f"serve-smoke: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    return 1


def request(base: str, path: str, payload=None, headers=None, timeout=30):
    hdrs = {"Content-Type": "application/json"} if payload else {}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers=hdrs,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def main() -> int:
    from tests import fixtures  # noqa: E402 — repo-root import

    d = fixtures.datasets_dir()
    train_arff = str(d / "small-train.arff")
    test_arff = str(d / "small-test.arff")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "3"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        print(f"serve-smoke: {build.stdout.strip()}")

        captures_dir = os.path.join(tmp, "captures")
        history_dir = os.path.join(tmp, "history")
        proc = procgroup.popen_group(
            [sys.executable, "-m", "knn_tpu.cli", "serve", index,
             "--port", "0", "--max-batch", "16", "--max-wait-ms", "1",
             # Quality observability on (PR 7): every request shadow-scored
             # + drift-sketched so the /debug/quality probe sees real data.
             "--shadow-rate", "1", "--drift-rate", "1",
             "--quality-queue", "4096",
             # Workload capture (PR 11): /admin/capture + /debug/capture
             # probed below; the finalized smoke workload is saved to
             # build/ as a CI artifact.
             "--capture-dir", captures_dir,
             # Metrics history (PR 20): a fast snapshot cadence so
             # /debug/history fills within the smoke, and the post-mortem
             # `knn_tpu history` query below has segments to read.
             "--history-dir", history_dir, "--history-interval-s", "0.5"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        # Read the banner on a thread: a server that wedges silently
        # before printing anything (stuck compile, deadlock) must FAIL the
        # gate after BOOT_TIMEOUT_S, not hang CI on a blocking readline.
        import queue
        import threading

        lines: "queue.Queue[str]" = queue.Queue()
        reader = threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],
            daemon=True,
        )
        reader.start()
        base = None
        deadline = time.monotonic() + BOOT_TIMEOUT_S
        while time.monotonic() < deadline:
            try:
                line = lines.get(timeout=min(1.0, max(
                    0.01, deadline - time.monotonic())))
            except queue.Empty:
                if proc.poll() is not None:
                    return fail(
                        f"server exited rc={proc.poll()} before ready", proc)
                continue
            print(f"serve-smoke: server: {line.rstrip()}")
            m = READY_RE.search(line)
            if m:
                base = m.group(1)
                break
        if base is None:
            return fail("no ready banner within the boot timeout", proc)

        try:
            st, body, _ = request(base, "/healthz")
            health = json.loads(body)
            if st != 200 or not health.get("ready"):
                return fail(f"/healthz not ready: {st} {body}", proc)
            if health.get("draining") is not False:
                return fail(f"/healthz draining field wrong at boot: "
                            f"{body}", proc)
            boot_version = health.get("index_version")
            if not boot_version:
                return fail(f"/healthz missing index_version: {body}", proc)
            slo = health.get("slo") or {}
            if "burn_rates" not in slo or "fast_rung" not in slo["burn_rates"]:
                return fail(f"/healthz missing the SLO burn-rate block: "
                            f"{body[:300]}", proc)
            print(f"serve-smoke: /healthz ok (train_rows="
                  f"{health['train_rows']}, index_version={boot_version}, "
                  f"draining=false, slo windows={slo.get('windows')})")

            from knn_tpu.data.arff import load_arff
            from knn_tpu.models.knn import KNNClassifier

            train, test = load_arff(train_arff), load_arff(test_arff)
            rows = test.features[:8]
            want = KNNClassifier(k=3).fit(train).predict(
                type(test)(rows, test.labels[:8])
            ).tolist()
            rid = "smoke-trace-0001"
            st, body, hdrs = request(base, "/predict",
                                     {"instances": rows.tolist()},
                                     headers={"x-request-id": rid})
            doc = json.loads(body)
            got = doc.get("predictions")
            if st != 200 or got != want:
                return fail(f"/predict {st}: got {got}, want {want}", proc)
            if doc.get("request_id") != rid or hdrs.get("x-request-id") != rid:
                return fail(f"/predict did not echo x-request-id {rid!r}: "
                            f"body={doc.get('request_id')!r}, "
                            f"header={hdrs.get('x-request-id')!r}", proc)
            print(f"serve-smoke: /predict ok ({len(got)} rows, "
                  f"bit-identical to the in-process model, "
                  f"x-request-id echoed)")

            st, body, _ = request(
                base, "/kneighbors", {"instances": rows[:2].tolist()})
            kn = json.loads(body)
            if st != 200 or len(kn["indices"]) != 2 or len(kn["indices"][0]) != 3:
                return fail(f"/kneighbors {st}: {body[:200]}", proc)
            print("serve-smoke: /kneighbors ok")

            st, metrics, _ = request(base, "/metrics")
            needed = ("knn_serve_requests_total", "knn_serve_batch_size",
                      "knn_serve_request_ms", "knn_slo_burn_rate")
            missing = [n for n in needed if n not in metrics]
            if st != 200 or missing:
                return fail(f"/metrics {st}: missing {missing}", proc)
            print("serve-smoke: /metrics ok (knn_serve_* + knn_slo_* "
                  "present)")

            # OpenMetrics negotiation: exemplars link latency buckets to
            # request ids; the exposition must end with "# EOF".
            st, om, hdrs = request(
                base, "/metrics",
                headers={"Accept": "application/openmetrics-text"})
            if st != 200 or not om.rstrip().endswith("# EOF"):
                return fail(f"OpenMetrics exposition missing # EOF "
                            f"terminator ({st})", proc)
            if "application/openmetrics-text" not in hdrs.get(
                    "Content-Type", ""):
                return fail(f"OpenMetrics content type wrong: "
                            f"{hdrs.get('Content-Type')}", proc)
            ex_lines = [ln for ln in om.splitlines()
                        if ln.startswith("knn_serve_request_ms_bucket")
                        and "# {" in ln and "trace_id=" in ln]
            if not ex_lines:
                return fail("knn_serve_request_ms OpenMetrics buckets carry "
                            "no trace_id exemplars", proc)
            print(f"serve-smoke: OpenMetrics ok ({len(ex_lines)} exemplar "
                  f"bucket(s), e.g. {ex_lines[0][:90]}...)")

            # Flight recorder: the predict's request_id must resolve to a
            # finished timeline with closed phases.
            st, body, _ = request(base, f"/debug/requests?id={rid}")
            if st != 200:
                return fail(f"/debug/requests?id={rid}: {st} {body[:200]}",
                            proc)
            tl = json.loads(body)["requests"][0]
            if tl.get("outcome") != "ok" or tl.get("status") != 200:
                return fail(f"timeline {rid}: outcome={tl.get('outcome')} "
                            f"status={tl.get('status')}", proc)
            if any(p.get("ms") is None for p in tl.get("phases", ())):
                return fail(f"timeline {rid} has unclosed phases: "
                            f"{tl.get('phases')}", proc)
            st, body, _ = request(base, "/debug/slowest")
            if st != 200 or not json.loads(body).get("requests"):
                return fail(f"/debug/slowest empty or failed ({st})", proc)
            st, body, _ = request(base, "/debug/requests?format=perfetto")
            ev = json.loads(body).get("traceEvents", [])
            b_n = sum(1 for e in ev if e.get("ph") == "B")
            e_n = sum(1 for e in ev if e.get("ph") == "E")
            if st != 200 or not ev or b_n != e_n:
                return fail(f"perfetto export bad: {st}, {b_n} B vs {e_n} E",
                            proc)
            print(f"serve-smoke: /debug ok (timeline for {rid} resolved, "
                  f"phases {[p['phase'] for p in tl['phases']]}, perfetto "
                  f"{len(ev)} events)")

            # Quality observability (PR 7): /debug/quality joins
            # shadow-scored recall, drift vs the artifact's training
            # sketch (a fresh save-index artifact is format 2 -> baseline
            # present), and the quality SLO burn; /healthz carries the
            # quality block; /metrics exposes knn_quality_*/knn_drift_*.
            deadline_q = time.monotonic() + 30
            qdoc = None
            while time.monotonic() < deadline_q:
                st, body, _ = request(base, "/debug/quality")
                if st != 200:
                    return fail(f"/debug/quality {st}: {body[:200]}", proc)
                qdoc = json.loads(body)
                sh = qdoc.get("shadow") or {}
                if sh.get("scored", 0) >= 1 and sh.get("queue_depth") == 0:
                    break
                time.sleep(0.2)
            sh = (qdoc or {}).get("shadow") or {}
            if sh.get("scored", 0) < 1:
                return fail(f"/debug/quality never showed a scored sample: "
                            f"{json.dumps(qdoc)[:300]}", proc)
            fast = (sh.get("rungs") or {}).get("fast") or {}
            if fast.get("recall") != 1.0 or fast.get("divergence"):
                return fail(f"shadow scorer reports divergence on a clean "
                            f"serve: {fast}", proc)
            drift = qdoc.get("drift") or {}
            if drift.get("baseline") != "present":
                return fail(f"drift baseline missing from a fresh format-2 "
                            f"artifact: {drift}", proc)
            if "burn_rates" not in (qdoc.get("slo_quality") or {}):
                return fail(f"/debug/quality missing the quality SLO "
                            f"block: {json.dumps(qdoc)[:300]}", proc)
            h_quality = json.loads(request(base, "/healthz")[1]) \
                .get("quality") or {}
            if not (h_quality.get("shadow") or {}).get("scored"):
                return fail(f"/healthz missing the quality block: "
                            f"{h_quality}", proc)
            st, metrics, _ = request(base, "/metrics")
            q_missing = [n for n in ("knn_quality_recall",
                                     "knn_quality_scored_total",
                                     "knn_drift_baseline_present")
                         if n not in metrics]
            if q_missing:
                return fail(f"/metrics missing quality rows: {q_missing}",
                            proc)
            print(f"serve-smoke: /debug/quality ok ({sh['scored']} scored, "
                  f"recall 1.0, 0 divergence, drift baseline present, "
                  f"quality burn "
                  f"{qdoc['slo_quality']['burn_rates']})")

            # Device observability (PR 6): knn_device_memory_bytes gauges
            # in the scrape, and /debug/profile returning ONE
            # Perfetto-loadable trace that carries both serve host spans
            # (TraceAnnotation pass-through) and device-side events —
            # captured UNDER LOAD from a background client thread. The
            # trace is saved to build/ so CI can upload it as an artifact.
            st, metrics, _ = request(base, "/metrics")
            if st != 200 or "knn_device_memory_bytes" not in metrics:
                return fail(f"/metrics missing knn_device_memory_bytes "
                            f"({st})", proc)
            dev = json.loads(request(base, "/healthz")[1]).get("device") or {}
            if "memory" not in dev or "executable_cache" not in dev:
                return fail(f"/healthz missing the device block: {dev}",
                            proc)
            stop_load = threading.Event()

            def load_loop():
                while not stop_load.is_set():
                    try:
                        request(base, "/predict",
                                {"instances": rows[:2].tolist()})
                    except Exception:  # noqa: BLE001 — load gen best-effort
                        pass
                    # Gentle load: the point is spans inside the window,
                    # not saturating the CI box while the profiler's
                    # xplane->trace conversion competes for the same cores.
                    time.sleep(0.01)

            loader = threading.Thread(target=load_loop, daemon=True)
            loader.start()
            try:
                st, body, _ = request(base, "/debug/profile?ms=150",
                                      timeout=180)
            finally:
                stop_load.set()
                loader.join(timeout=10)
            if st != 200:
                return fail(f"/debug/profile {st}: {body[:200]}", proc)
            trace = json.loads(body)
            ev_names = {e.get("name", "") for e in
                        trace.get("traceEvents", ()) if isinstance(e, dict)}
            if not ev_names:
                return fail("/debug/profile returned an empty trace", proc)
            serve_spans = [n for n in ev_names if n.startswith("serve.")]
            device_evs = [n for n in ev_names
                          if not n.startswith(("serve.", "$"))
                          and n not in ("", "process_name", "thread_name",
                                        "process_sort_index",
                                        "thread_sort_index")]
            if trace["otherData"].get("source") == "jax.profiler" and (
                    not serve_spans or not device_evs):
                return fail(f"/debug/profile trace lacks serve spans "
                            f"({serve_spans[:3]}) or device events "
                            f"({device_evs[:3]})", proc)
            out = REPO / "build" / "serve-profile-trace.json"
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_text(body)
            st_bad, body_bad, _ = request(base, "/debug/profile?ms=notanum")
            if st_bad != 400:
                return fail(f"/debug/profile?ms=notanum: want 400, got "
                            f"{st_bad}", proc)
            print(f"serve-smoke: /debug/profile ok "
                  f"({len(trace['traceEvents'])} events, "
                  f"source={trace['otherData'].get('source')}, serve spans "
                  f"{serve_spans[:3]}, saved to {out.name})")

            # Workload capture (PR 11, docs/OBSERVABILITY.md §Workload
            # capture & replay): /debug/capture reports the idle layer,
            # /admin/capture start arms a window, captured requests land
            # in a loadable workload artifact on stop, and the artifact
            # is saved to build/ for the CI upload.
            st, body, _ = request(base, "/debug/capture")
            cdoc = json.loads(body)
            if st != 200 or cdoc.get("enabled") is not True \
                    or cdoc.get("capturing") is not False:
                return fail(f"/debug/capture idle state wrong: {st} "
                            f"{body[:200]}", proc)
            st, body, _ = request(base, "/admin/capture",
                                  {"action": "start", "reason": "smoke"})
            if st != 200 or not json.loads(body).get("capturing"):
                return fail(f"/admin/capture start: {st} {body[:200]}", proc)
            st, body, _ = request(base, "/admin/capture",
                                  {"action": "start"})
            if st != 409:
                return fail(f"double capture start: want 409, got {st}",
                            proc)
            cap_rid = "smoke-capture-0001"
            for i in range(3):
                hdrs = {"x-request-id": cap_rid} if i == 0 else None
                st, body, _ = request(base, "/predict",
                                      {"instances": rows[:2].tolist()},
                                      headers=hdrs)
                if st != 200:
                    return fail(f"/predict during capture: {st}", proc)
            st, body, _ = request(base, "/admin/capture",
                                  {"action": "stop"})
            cstop = json.loads(body)
            if st != 200 or cstop.get("requests", 0) < 3:
                return fail(f"/admin/capture stop: {st} {body[:300]}", proc)
            st, body, _ = request(base, "/debug/capture")
            cdoc = json.loads(body)
            if (cdoc.get("capturing") is not False
                    or (cdoc.get("last") or {}).get("requests", 0) < 3):
                return fail(f"/debug/capture after stop: {body[:300]}",
                            proc)
            from knn_tpu.obs.workload import load_workload

            wl = load_workload(cstop["path"])
            captured_ids = {e.get("request_id")
                            for e in wl.read_events}
            if cap_rid not in captured_ids:
                return fail(f"captured workload lost the request_id "
                            f"linkage: {sorted(captured_ids)[:5]}", proc)
            # The access-log/flight-recorder linkage rides the timeline:
            # the captured request's trace must carry workload_record.
            st, body, _ = request(base, f"/debug/requests?id={cap_rid}")
            tl = json.loads(body)["requests"][0] if st == 200 else {}
            if "workload_record" not in tl:
                return fail(f"flight-recorder timeline for {cap_rid} "
                            f"lacks workload_record: {body[:300]}", proc)
            import shutil

            smoke_out = REPO / "build" / "smoke-workload"
            if smoke_out.exists():
                shutil.rmtree(smoke_out)
            smoke_out.parent.mkdir(parents=True, exist_ok=True)
            shutil.copytree(cstop["path"], smoke_out)
            print(f"serve-smoke: capture ok ({cstop['requests']} requests "
                  f"captured, request_id + workload_record linkage "
                  f"verified, artifact saved to {smoke_out.name}/)")

            # Oversized x-request-id: 400, never a traceback.
            st, body, _ = request(base, "/predict",
                                  {"instances": rows[:1].tolist()},
                                  headers={"x-request-id": "y" * 4096})
            if st != 400 or "request_id" not in json.loads(body):
                return fail(f"oversized x-request-id: want 400 with a "
                            f"generated request_id, got {st} {body[:200]}",
                            proc)
            print("serve-smoke: malformed x-request-id rejected 400")

            # Metrics history (PR 20): /debug/history must answer a range
            # query with >= 2 points once two snapshot intervals have
            # elapsed, and /debug/alerts (no rules loaded) must be empty
            # but well-formed.
            hist_points = None
            deadline_h = time.monotonic() + 30
            while time.monotonic() < deadline_h:
                st, body, _ = request(
                    base, "/debug/history?metric=knn_serve_requests_total")
                if st != 200:
                    return fail(f"/debug/history {st}: {body[:200]}", proc)
                hdoc = json.loads(body)
                if hdoc.get("enabled") is not True:
                    return fail(f"/debug/history reports disabled with "
                                f"--history-dir set: {body[:200]}", proc)
                series = hdoc.get("series") or []
                if series and len(series[0].get("points", ())) >= 2:
                    hist_points = series[0]["points"]
                    break
                time.sleep(0.2)
            if hist_points is None:
                return fail("/debug/history never accumulated 2 points for "
                            "knn_serve_requests_total (two snapshot "
                            "intervals)", proc)
            if hist_points[-1][1] <= 0:
                return fail(f"history counter value not positive: "
                            f"{hist_points[-1]}", proc)
            st, body, _ = request(base, "/debug/alerts")
            adoc = json.loads(body)
            if st != 200 or adoc.get("rules") != [] \
                    or adoc.get("firing") != []:
                return fail(f"/debug/alerts (no rules) not empty/well-"
                            f"formed: {st} {body[:200]}", proc)
            print(f"serve-smoke: /debug/history ok ({len(hist_points)} "
                  f"points, latest {hist_points[-1]}), /debug/alerts ok "
                  f"(no rules loaded)")

            # Hot reload: rebuild the index (new created_unix -> new
            # version), SIGHUP, and require the swap while serving stays
            # bit-identical.
            rebuild = subprocess.run(
                [sys.executable, "-m", "knn_tpu.cli", "save-index",
                 train_arff, index, "--k", "3"],
                env=env, capture_output=True, text=True, cwd=REPO,
            )
            if rebuild.returncode != 0:
                return fail(f"index rebuild rc={rebuild.returncode}: "
                            f"{rebuild.stderr}", proc)
            proc.send_signal(signal.SIGHUP)
            new_version = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                st, body, _ = request(base, "/healthz")
                v = json.loads(body).get("index_version")
                if st == 200 and v and v != boot_version:
                    new_version = v
                    break
                time.sleep(0.1)
            if new_version is None:
                return fail("SIGHUP reload never swapped index_version",
                            proc)
            st, body, _ = request(base, "/predict",
                                  {"instances": rows.tolist()})
            got = json.loads(body)
            if st != 200 or got.get("predictions") != want:
                return fail(f"/predict after reload {st}: got "
                            f"{got.get('predictions')}, want {want}", proc)
            if got.get("index_version") != new_version:
                return fail(f"response index_version {got.get('index_version')} "
                            f"!= reloaded {new_version}", proc)
            print(f"serve-smoke: SIGHUP reload ok "
                  f"({boot_version} -> {new_version}, still bit-identical)")
        except Exception as e:  # noqa: BLE001 — smoke harness boundary
            return fail(f"{type(e).__name__}: {e}", proc)

        proc.send_signal(signal.SIGINT)
        try:
            rc = proc.wait(timeout=SHUTDOWN_GRACE_S)
        except subprocess.TimeoutExpired:
            return fail("server did not exit after SIGINT", proc)
        if rc != 0:
            return fail(f"server exited rc={rc} after SIGINT")

        # Post-mortem: the history CLI must answer a range query from the
        # dead server's --history-dir (no server process anywhere).
        hist = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "history", history_dir,
             "--metric", "knn_serve_requests_total", "--json"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if hist.returncode != 0:
            return fail(f"knn_tpu history rc={hist.returncode}: "
                        f"{hist.stderr[:300]}")
        hdoc = json.loads(hist.stdout)
        series = hdoc.get("series") or []
        if not series or not series[0].get("points"):
            return fail(f"post-mortem history query returned no points: "
                        f"{hist.stdout[:300]}")
        last = series[0]["points"][-1]
        if last[1] <= 0:
            return fail(f"post-mortem history counter not positive: {last}")
        print(f"serve-smoke: post-mortem `knn_tpu history` ok "
              f"({hdoc.get('samples')} samples, "
              f"knn_serve_requests_total={last[1]})")
        print("serve-smoke: clean shutdown, PASS")
        return 0


if __name__ == "__main__":
    sys.exit(main())
