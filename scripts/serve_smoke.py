"""Serve smoke gate (`make serve-smoke`): the full boot→probe→shutdown
lifecycle of the serving stack, as a subprocess — the one thing the pytest
suite's in-process server tests cannot cover (signal handling, the ready
banner, a real ephemeral-port bind, clean exit code).

Steps:
1. build a fixture index with `knn_tpu save-index` (small-train.arff);
2. boot `knn_tpu serve --port 0` and wait for the ready banner;
3. probe /healthz (ready, NOT draining, carries index_version — the
   self-healing fields, docs/SERVING.md), /predict (predictions match an
   in-process model on the same rows), /kneighbors (shapes), /metrics
   (knn_serve_* counters present);
4. rebuild the index and SIGHUP: the hot reload must swap index_version
   while the process keeps serving bit-identical predictions;
5. SIGINT and require a clean exit within the grace period.

Exit 0 on success; any failure prints a diagnosis and exits 1.
stdlib-only (urllib, not curl: the gate must not depend on host tools).
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 120  # first-call compile on a cold cache can be slow
SHUTDOWN_GRACE_S = 15


def fail(msg: str, proc: "subprocess.Popen | None" = None) -> "int":
    print(f"serve-smoke: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    return 1


def request(base: str, path: str, payload=None):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"} if payload else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def main() -> int:
    from tests import fixtures  # noqa: E402 — repo-root import

    d = fixtures.datasets_dir()
    train_arff = str(d / "small-train.arff")
    test_arff = str(d / "small-test.arff")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "3"],
            env=env, capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        print(f"serve-smoke: {build.stdout.strip()}")

        proc = subprocess.Popen(
            [sys.executable, "-m", "knn_tpu.cli", "serve", index,
             "--port", "0", "--max-batch", "16", "--max-wait-ms", "1"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        # Read the banner on a thread: a server that wedges silently
        # before printing anything (stuck compile, deadlock) must FAIL the
        # gate after BOOT_TIMEOUT_S, not hang CI on a blocking readline.
        import queue
        import threading

        lines: "queue.Queue[str]" = queue.Queue()
        reader = threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],
            daemon=True,
        )
        reader.start()
        base = None
        deadline = time.monotonic() + BOOT_TIMEOUT_S
        while time.monotonic() < deadline:
            try:
                line = lines.get(timeout=min(1.0, max(
                    0.01, deadline - time.monotonic())))
            except queue.Empty:
                if proc.poll() is not None:
                    return fail(
                        f"server exited rc={proc.poll()} before ready", proc)
                continue
            print(f"serve-smoke: server: {line.rstrip()}")
            m = READY_RE.search(line)
            if m:
                base = m.group(1)
                break
        if base is None:
            return fail("no ready banner within the boot timeout", proc)

        try:
            st, body = request(base, "/healthz")
            health = json.loads(body)
            if st != 200 or not health.get("ready"):
                return fail(f"/healthz not ready: {st} {body}", proc)
            if health.get("draining") is not False:
                return fail(f"/healthz draining field wrong at boot: "
                            f"{body}", proc)
            boot_version = health.get("index_version")
            if not boot_version:
                return fail(f"/healthz missing index_version: {body}", proc)
            print(f"serve-smoke: /healthz ok (train_rows="
                  f"{health['train_rows']}, index_version={boot_version}, "
                  f"draining=false)")

            from knn_tpu.data.arff import load_arff
            from knn_tpu.models.knn import KNNClassifier

            train, test = load_arff(train_arff), load_arff(test_arff)
            rows = test.features[:8]
            want = KNNClassifier(k=3).fit(train).predict(
                type(test)(rows, test.labels[:8])
            ).tolist()
            st, body = request(base, "/predict", {"instances": rows.tolist()})
            got = json.loads(body).get("predictions")
            if st != 200 or got != want:
                return fail(f"/predict {st}: got {got}, want {want}", proc)
            print(f"serve-smoke: /predict ok ({len(got)} rows, "
                  f"bit-identical to the in-process model)")

            st, body = request(
                base, "/kneighbors", {"instances": rows[:2].tolist()})
            kn = json.loads(body)
            if st != 200 or len(kn["indices"]) != 2 or len(kn["indices"][0]) != 3:
                return fail(f"/kneighbors {st}: {body[:200]}", proc)
            print("serve-smoke: /kneighbors ok")

            st, metrics = request(base, "/metrics")
            needed = ("knn_serve_requests_total", "knn_serve_batch_size",
                      "knn_serve_request_ms")
            missing = [n for n in needed if n not in metrics]
            if st != 200 or missing:
                return fail(f"/metrics {st}: missing {missing}", proc)
            print("serve-smoke: /metrics ok (knn_serve_* present)")

            # Hot reload: rebuild the index (new created_unix -> new
            # version), SIGHUP, and require the swap while serving stays
            # bit-identical.
            rebuild = subprocess.run(
                [sys.executable, "-m", "knn_tpu.cli", "save-index",
                 train_arff, index, "--k", "3"],
                env=env, capture_output=True, text=True, cwd=REPO,
            )
            if rebuild.returncode != 0:
                return fail(f"index rebuild rc={rebuild.returncode}: "
                            f"{rebuild.stderr}", proc)
            proc.send_signal(signal.SIGHUP)
            new_version = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                st, body = request(base, "/healthz")
                v = json.loads(body).get("index_version")
                if st == 200 and v and v != boot_version:
                    new_version = v
                    break
                time.sleep(0.1)
            if new_version is None:
                return fail("SIGHUP reload never swapped index_version",
                            proc)
            st, body = request(base, "/predict",
                               {"instances": rows.tolist()})
            got = json.loads(body)
            if st != 200 or got.get("predictions") != want:
                return fail(f"/predict after reload {st}: got "
                            f"{got.get('predictions')}, want {want}", proc)
            if got.get("index_version") != new_version:
                return fail(f"response index_version {got.get('index_version')} "
                            f"!= reloaded {new_version}", proc)
            print(f"serve-smoke: SIGHUP reload ok "
                  f"({boot_version} -> {new_version}, still bit-identical)")
        except Exception as e:  # noqa: BLE001 — smoke harness boundary
            return fail(f"{type(e).__name__}: {e}", proc)

        proc.send_signal(signal.SIGINT)
        try:
            rc = proc.wait(timeout=SHUTDOWN_GRACE_S)
        except subprocess.TimeoutExpired:
            return fail("server did not exit after SIGINT", proc)
        if rc != 0:
            return fail(f"server exited rc={rc} after SIGINT")
        print("serve-smoke: clean shutdown, PASS")
        return 0


if __name__ == "__main__":
    sys.exit(main())
