"""Sweep selection-phase variants of the lane-striped kernel (VERDICT r1 #8).

The headline step spends roughly half its time in the per-tile selection
rounds (k rounds x (g+k) planes x ~6 elementwise ops). This probe measures,
on the real device, (a) the distance-only floor — what a zero-cost selection
would give, (b) the current round structure, (c) a cheaper-retirement round
structure, across block-size configs, so the winning variant can be promoted
into ops/pallas_knn.py with evidence.

SUPERSEDED (r4): the round-based selection this probe tunes was replaced as
the default by the truncated odd-even merge network (ops/topk_net.py,
measured 1.39x on the headline shape interleaved — scripts/probe_select_r4.py);
the rounds remain reachable at k <= 2 and via select="rounds".

HISTORICAL RECORD (r2): the "lite" variant won (~16% off the step at
bq=864/bn=2048) and ships in ops/pallas_knn.py gated on finite inputs
(stripe_inputs_finite — NaN/overflow inputs need full index retirement; see
the counterexample in _knn_stripe_kernel). The shipped kernel has since also
moved to per-chunk distance accumulation for VMEM headroom; this probe keeps
the r2 decision-point kernel structure so its numbers stay reproducible.
Measurement caveat learned later (see bench.py): use one DISTINCT buffer per
dispatch — repeat-buffer slopes can collapse to enqueue cost.

Usage: python scripts/tune_stripe_selection.py
"""

from __future__ import annotations

import functools
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import load_large
from knn_tpu.obs.bench_timing import pipelined_slope as _pipelined_slope

K = 5
_INT_MAX = np.int32(np.iinfo(np.int32).max)


def make_variant_kernel(sel_mode: str):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(
        n_valid_ref, q_ref, tT_ref, out_d_ref, out_i_ref, cand_d_ref,
        cand_i_ref, *, k, block_n, d_true, n_tiles,
    ):
        j = pl.program_id(1)
        lanes = 128

        @pl.when(j == 0)
        def _init():
            cand_d_ref[:] = jnp.full(cand_d_ref.shape, jnp.inf, jnp.float32)
            cand_i_ref[:] = jnp.full(cand_i_ref.shape, _INT_MAX, jnp.int32)

        q = q_ref[:]
        nv = n_valid_ref[0]
        bq = q.shape[0]
        g = block_n // lanes

        d_full = jnp.zeros((bq, block_n), jnp.float32)
        for f in range(d_true):
            diff = q[:, f : f + 1] - tT_ref[f, :].reshape(1, block_n)
            d_full = d_full + diff * diff
        d_full = jnp.where(jnp.isnan(d_full), jnp.inf, d_full)

        i128 = jax.lax.broadcasted_iota(jnp.int32, (bq, lanes), 1)
        d_planes, i_planes = [], []
        for c in range(g):
            gcol = i128 + (j * block_n + c * lanes)
            valid = gcol < nv
            d_planes.append(
                jnp.where(valid, d_full[:, c * lanes : (c + 1) * lanes], jnp.inf)
            )
            i_planes.append(jnp.where(valid, gcol, _INT_MAX))

        if sel_mode == "nosel":
            # Floor: fold everything into level 0 with a plain min — no
            # correct selection, just the cheapest possible accumulator
            # keeping the same memory traffic.
            m = cand_d_ref[:, :lanes]
            for p in d_planes:
                m = jnp.minimum(m, p)
            cand_d_ref[:, :lanes] = m
        elif sel_mode == "current":
            d_planes += [cand_d_ref[:, l * lanes : (l + 1) * lanes] for l in range(k)]
            i_planes += [cand_i_ref[:, l * lanes : (l + 1) * lanes] for l in range(k)]
            for level in range(k):
                m_d = d_planes[0]
                for p in range(1, len(d_planes)):
                    m_d = jnp.minimum(m_d, d_planes[p])
                m_i = _INT_MAX * jnp.ones_like(i_planes[0])
                for p in range(len(d_planes)):
                    m_i = jnp.minimum(
                        m_i, jnp.where(d_planes[p] == m_d, i_planes[p], _INT_MAX)
                    )
                cand_d_ref[:, level * lanes : (level + 1) * lanes] = m_d
                cand_i_ref[:, level * lanes : (level + 1) * lanes] = m_i
                if level + 1 < k:
                    for p in range(len(d_planes)):
                        taken = i_planes[p] == m_i
                        d_planes[p] = jnp.where(taken, jnp.inf, d_planes[p])
                        i_planes[p] = jnp.where(taken, _INT_MAX, i_planes[p])
        elif sel_mode == "lite":
            # Drop the index-retirement write: once an element's distance is
            # +inf it can only be re-selected in a round whose min is +inf,
            # which (given >= k valid candidates overall) only produces
            # duplicate (inf, i) pairs that can never win the final XLA
            # merge. Saves one where per plane per round.
            d_planes += [cand_d_ref[:, l * lanes : (l + 1) * lanes] for l in range(k)]
            i_planes += [cand_i_ref[:, l * lanes : (l + 1) * lanes] for l in range(k)]
            for level in range(k):
                m_d = d_planes[0]
                for p in range(1, len(d_planes)):
                    m_d = jnp.minimum(m_d, d_planes[p])
                m_i = _INT_MAX * jnp.ones_like(i_planes[0])
                for p in range(len(d_planes)):
                    m_i = jnp.minimum(
                        m_i, jnp.where(d_planes[p] == m_d, i_planes[p], _INT_MAX)
                    )
                cand_d_ref[:, level * lanes : (level + 1) * lanes] = m_d
                cand_i_ref[:, level * lanes : (level + 1) * lanes] = m_i
                if level + 1 < k:
                    for p in range(len(d_planes)):
                        taken = i_planes[p] == m_i
                        d_planes[p] = jnp.where(taken, jnp.inf, d_planes[p])
        else:
            raise ValueError(sel_mode)

        @pl.when(j == n_tiles - 1)
        def _writeback():
            out_d_ref[:] = cand_d_ref[:]
            out_i_ref[:] = cand_i_ref[:]

    return kernel


@functools.partial(
    __import__("jax").jit,
    static_argnames=("k", "block_q", "block_n", "d_true", "sel_mode"),
)
def stripe_variant(train_xT, test_x, n_valid, k, block_q, block_n, d_true, sel_mode):
    """Variant kernel + the final 128k -> k merge fused in one jit (matching
    real usage — returning the raw [Q, 128k] candidate buffers as jit outputs
    makes XLA stack-allocate them in VMEM and OOM at headline block sizes)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from knn_tpu.ops.pallas_knn import _merge_topk_rounds

    d_pad, n_pad = train_xT.shape
    q_pad = test_x.shape[0]
    grid = (q_pad // block_q, n_pad // block_n)
    kernel = functools.partial(
        make_variant_kernel(sel_mode), k=k, block_n=block_n, d_true=d_true,
        n_tiles=grid[1],
    )
    cd, ci = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_q, test_x.shape[1]), lambda i, j, n_ref: (i, 0)),
                pl.BlockSpec((d_pad, block_n), lambda i, j, n_ref: (0, j)),
            ],
            out_specs=[
                pl.BlockSpec((block_q, k * 128), lambda i, j, n_ref: (i, 0)),
                pl.BlockSpec((block_q, k * 128), lambda i, j, n_ref: (i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, k * 128), jnp.float32),
                pltpu.VMEM((block_q, k * 128), jnp.int32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((q_pad, k * 128), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, k * 128), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=False,
    )(jnp.asarray(n_valid, jnp.int32).reshape(1), test_x, train_xT)
    if sel_mode == "nosel":
        return cd[:, :1], ci[:, :1]
    return _merge_topk_rounds(cd, ci, k)


def main():
    import jax
    import jax.numpy as jnp

    from knn_tpu.ops.pallas_knn import (
        _merge_topk_rounds, stripe_prepare_queries, stripe_prepare_train,
    )

    train, test, _ = load_large()
    n, d_true = train.features.shape
    q = test.num_instances
    print(f"device: {jax.devices()[0].device_kind}; "
          f"{q} queries x {n} train x {d_true} feats, k={K}", file=sys.stderr)

    # Reference candidates from the shipped kernel for parity checks.
    from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

    ref_d, ref_i = stripe_candidates_arrays(train.features, test.features, K)

    configs = [(896, 2048), (864, 2048), (448, 4096), (432, 4096), (224, 8192)]
    for block_q, block_n in configs:
        txT, d_pad = stripe_prepare_train(train.features, block_n)
        txj = jnp.asarray(txT)
        bufs = [
            jnp.asarray(stripe_prepare_queries(
                test.features + np.float32(i) * 1e-7, block_q, d_pad))
            for i in range(8)
        ]
        jax.block_until_ready(bufs)
        nv = jnp.asarray(n, jnp.int32)
        for mode in ("nosel", "current", "lite"):
            def step(qb, mode=mode, bq=block_q, bn=block_n):
                return stripe_variant(txj, qb, nv, K, bq, bn, d_true, mode)

            try:
                md, mi = step(bufs[0])
                jax.block_until_ready((md, mi))
            except Exception as e:
                print(f"bq={block_q} bn={block_n} {mode:8s} FAILED: "
                      f"{type(e).__name__}: {str(e)[:120]}")
                continue
            ok = "-"
            if mode != "nosel":
                ok = bool(
                    np.array_equal(np.asarray(mi)[:q], ref_i)
                    and np.allclose(np.asarray(md)[:q], ref_d)
                )
            per_step, _ = _pipelined_slope(
                step, bufs, 50, 200, block_fn=jax.block_until_ready
            )
            print(f"bq={block_q} bn={block_n} {mode:8s} "
                  f"{per_step*1e3:7.3f} ms/step  parity={ok}", flush=True)


if __name__ == "__main__":
    main()
