"""Disabled-path overhead gate (part of `make verify`).

Every observability layer grown since PR 1 — spans, metrics, and now
request tracing / exemplars / SLO burn rates — carries the same contract:
**zero cost when disabled** (one predicate per call site). This gate pins
that contract two ways:

1. **functional** — with nothing enabled (the default import state), a
   full predict plus a micro-batched serving call must record ZERO spans
   and ZERO metric instruments, and the batcher must not allocate request
   traces. This is deterministic: an accidentally-always-on layer fails
   here on any machine.
2. **timing** — medium-preset predict best-of mins must stay under a
   budget (``KNN_TPU_OVERHEAD_BUDGET_MS``, default 60 ms — a gross-
   regression tripwire sized for noisy CI boxes; the local reference
   box measures ≈17 ms at PR 4, and the measured value is printed so the
   trend is visible in every CI log even when the gate passes).

Exit 0 when both hold; 1 with a diagnosis otherwise. Run on CPU jax.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BEST_OF = 5


def fail(msg: str) -> int:
    print(f"disabled-overhead: FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ.get("KNN_TPU_OBS", "") not in ("", "0"):
        return fail("KNN_TPU_OBS is set; this gate measures the DISABLED "
                    "path — unset it")

    from knn_tpu import obs

    if obs.enabled():
        return fail("knn_tpu.obs is enabled at import with no KNN_TPU_OBS "
                    "set — the disabled-by-default contract is broken")

    from bench import _load_medium  # noqa: E402 — repo-root import
    from knn_tpu.models.knn import KNNClassifier
    from knn_tpu.serve.batcher import MicroBatcher

    train, test = _load_medium()
    model = KNNClassifier(k=5).fit(train)
    model.predict(test)  # warm: compile + first dispatch excluded

    # -- 1. functional: the disabled path records nothing ------------------
    obs.reset()
    model.predict(test)
    with MicroBatcher(model, max_batch=8, max_wait_ms=0.0) as b:
        b.predict(test.features[0], timeout=60)
    spans = obs.tracer().spans()
    instruments = obs.registry().instruments()
    if spans:
        return fail(f"{len(spans)} span(s) recorded while disabled "
                    f"(first: {spans[0].name!r})")
    if instruments:
        return fail(f"{len(instruments)} metric instrument(s) created "
                    f"while disabled (first: {instruments[0].name!r})")
    print("disabled-overhead: functional ok (0 spans, 0 instruments, "
          "no request traces)")

    # -- 1a. shadow scoring + drift sketching off-state --------------------
    # Rate 0 (the serve default) must construct NOTHING: no scorer, no
    # drift monitor, no worker threads, no queue, zero knn_quality_*/
    # knn_drift_* instruments — the batcher then pays exactly one
    # `is None` predicate per served request.
    import threading

    from knn_tpu.serve.server import ServeApp

    app = ServeApp(model, max_batch=8, max_wait_ms=0.0)
    try:
        if app.quality is not None or app.drift is not None:
            return fail("ServeApp built a shadow scorer / drift monitor "
                        "at rate 0 — the quality layer must not exist "
                        "while disabled")
        if app.batcher.quality is not None or app.batcher.drift is not None:
            return fail("the batcher holds a quality/drift tap at rate 0")
        # Cost & capacity (PR 8): the default (--cost-accounting off /
        # ServeApp's cost_accounting=False) must construct NOTHING — no
        # accountant, no capacity tracker, no class parsing state.
        if app.accounting is not None or app.capacity is not None:
            return fail("ServeApp built a cost accountant / capacity "
                        "tracker with cost_accounting off — the layer "
                        "must not exist while disabled")
        if (app.batcher.accounting is not None
                or app.batcher.capacity is not None):
            return fail("the batcher holds an accounting/capacity tap "
                        "while disabled")
        # IVF (PR 9): a format-1/2 / exact-only model (no ivf_ partition,
        # no --ivf-probes) must construct ZERO approximate-serving
        # machinery — no IVFServing, no probe policy, no ivf ladder rung;
        # the exact ladder is untouched.
        if app.ivf is not None or app.batcher.ivf is not None:
            return fail("ServeApp built IVF serving machinery for an "
                        "exact-only model — the ivf layer must not exist "
                        "while disabled")
        if any(name == "ivf" for name, _fn
               in app.batcher._rungs(app.batcher._model)):
            return fail("the serving ladder grew an ivf rung for an "
                        "exact-only model")
        if app.primary_rung != "fast":
            return fail(f"primary rung {app.primary_rung!r} on an "
                        f"exact-only serve; the fast_rung SLI would "
                        f"misattribute")
        # Mutable tier (PR 10): the default (--mutable off /
        # ServeApp's mutable=False) must construct NOTHING — no delta
        # engine, no tombstone state, no compactor thread, no epoch log,
        # no per-dispatch snapshot/merge (the batcher pays one `is None`
        # predicate per dispatch and never wraps a rung).
        if app.mutable is not None or app.compactor is not None:
            return fail("ServeApp built a mutable engine / compactor with "
                        "mutable off — the layer must not exist while "
                        "disabled")
        if app.batcher.mutable is not None:
            return fail("the batcher holds a mutable engine while disabled")
        # Device-resident retrieval hot path (PR 13): exact-only,
        # mutable-off serving must construct ZERO device-IVF machinery —
        # the segment-score kernel module and the device delta tail are
        # lazy imports that only the ivf/mutable device paths pull in,
        # so their mere presence in sys.modules here means something
        # constructed them on the disabled path.
        for mod in ("knn_tpu.ops.segment_score",
                    "knn_tpu.mutable.device_tail"):
            if mod in sys.modules:
                return fail(f"{mod} imported during exact-only, "
                            f"mutable-off serving — the device-IVF/"
                            f"delta-tail machinery must not construct "
                            f"while disabled")
        # Mesh-sharded serving (PR 18): --shards unset (ServeApp's
        # shards=None) must construct ZERO shard machinery — no sharded
        # twin wrapping the model, no per-shard executable caches, no
        # knn_shard_* instruments; the whole knn_tpu.shard package is a
        # lazy import only the opted-in path pulls in.
        if app.shards is not None:
            return fail("ServeApp resolved a shard count with --shards "
                        "unset")
        if hasattr(app.model, "shard_plan_"):
            return fail("ServeApp wrapped the model in a sharded twin "
                        "with --shards unset")
        for mod in ("knn_tpu.shard", "knn_tpu.shard.plan",
                    "knn_tpu.shard.model", "knn_tpu.shard.dispatch"):
            if mod in sys.modules:
                return fail(f"{mod} imported during unsharded serving — "
                            f"shard machinery must not construct while "
                            f"disabled")
        # Fleet replication (PR 15): plain single-process serving (no
        # --follower-of, no --replicate-to, no router) must construct
        # ZERO fleet machinery — no FleetReplica, no WAL shippers, no
        # router imports; the whole knn_tpu.fleet package is a lazy
        # import only the opted-in paths pull in.
        if app.fleet is not None:
            return fail("ServeApp built a fleet role with no "
                        "--follower-of/--replicate-to — the layer must "
                        "not exist while disabled")
        for mod in ("knn_tpu.fleet", "knn_tpu.fleet.replica",
                    "knn_tpu.fleet.router", "knn_tpu.fleet.health",
                    "knn_tpu.fleet.wire", "knn_tpu.fleet.bootstrap",
                    "knn_tpu.fleet.events"):
            if mod in sys.modules:
                return fail(f"{mod} imported during plain single-process "
                            f"serving — fleet machinery must not "
                            f"construct while disabled")
        # Workload capture (PR 11): the default (no --capture-dir /
        # ServeApp's capture_dir=None) must construct NOTHING — no
        # recorder, no sample queue, no consumer thread, no
        # knn_workload_* instruments, no per-request capture work (the
        # batcher pays one `is None` predicate per terminal outcome).
        if app.workload is not None or app.batcher.workload is not None:
            return fail("ServeApp built a workload capture layer with no "
                        "capture_dir — the layer must not exist while "
                        "disabled")
        # Overload control plane (PR 19): the defaults (no --priority,
        # --brownout off, no --autotune-interval-s) must construct
        # NOTHING — no admission map, no brownout controller thread, no
        # autotuner thread, no knn_control_* instruments; the whole
        # knn_tpu.control package is a lazy import only the opted-in
        # paths pull in.
        if (app.admission is not None or app.brownout is not None
                or app.autotune is not None):
            return fail("ServeApp built overload-control machinery with "
                        "no --priority/--brownout/--autotune-interval-s "
                        "— the control plane must not exist while "
                        "disabled")
        if app.batcher.admission is not None:
            return fail("the batcher holds an admission tap while "
                        "disabled")
        for mod in ("knn_tpu.control", "knn_tpu.control.admission",
                    "knn_tpu.control.brownout", "knn_tpu.control.autotune",
                    "knn_tpu.control.autoscale"):
            if mod in sys.modules:
                return fail(f"{mod} imported during flagless serving — "
                            f"control-plane machinery must not construct "
                            f"while disabled")
        if any("_merged_rung" in fn.__qualname__
               for _name, fn in app.batcher._rungs(app.batcher._model)):
            return fail("the serving ladder wrapped a rung with the "
                        "mutable merge while disabled")
        # Durable history + alerting (PR 20): the defaults (no
        # --history-dir, no --alert-rules) must construct NOTHING — no
        # recorder, no sampling thread, no alert engine, no
        # knn_history_*/knn_alerts_* instruments; obs.history/alerts are
        # lazy imports only the opted-in path pulls in.
        if app.history is not None or app.alerts is not None:
            return fail("ServeApp built history/alerting machinery with "
                        "no --history-dir/--alert-rules — the layer must "
                        "not exist while disabled")
        for mod in ("knn_tpu.obs.history", "knn_tpu.obs.alerts"):
            if mod in sys.modules:
                return fail(f"{mod} imported during flagless serving — "
                            f"history/alerting machinery must not "
                            f"construct while disabled")
        # Shape buckets + result cache (PR 12): the embedded defaults
        # (buckets=None, result_cache_rows=0) must construct NOTHING —
        # no bucket ladder state, no upload stager, no ResultCache, no
        # knn_cache_* instruments, and the process-global pad stays the
        # legacy single quantum.
        if app.batcher.buckets is not None or app.batcher._stager is not None:
            return fail("the batcher built a bucket ladder / upload "
                        "stager with no --batch-buckets configured")
        if app.batcher.cache is not None:
            return fail("the batcher built a result cache with "
                        "result_cache_rows 0 — the layer must not exist "
                        "while disabled")
        from knn_tpu.models import knn as knn_mod

        if knn_mod.query_buckets() is not None:
            return fail("a process-global query bucket ladder is "
                        "installed with no serve --batch-buckets — the "
                        "legacy pad quantum must be untouched")
        app.batcher.predict(test.features[0], timeout=60)
    finally:
        app.close()
    # A SINGLE-bucket ladder with the cache off must construct nothing
    # NEW either: the one bucket is one compiled shape exactly like the
    # legacy quantum — no ResultCache, zero knn_cache_* instruments.
    with knn_mod.query_bucket_ladder((8,)):
        app_1b = ServeApp(model, max_batch=8, max_wait_ms=0.0,
                          batch_buckets=(8,), result_cache_rows=0)
        try:
            if app_1b.batcher.cache is not None:
                return fail("a single-bucket ladder with "
                            "--result-cache-rows 0 built a result cache")
            app_1b.batcher.predict(test.features[0], timeout=60)
        finally:
            app_1b.close()
    if any(i.name.startswith("knn_cache_")
           for i in obs.registry().instruments()):
        return fail("knn_cache_* instrument(s) recorded with the result "
                    "cache disabled")
    bad_threads = [t.name for t in threading.enumerate()
                   if t.name.startswith(("knn-quality", "knn-drift",
                                         "knn-compactor", "knn-workload",
                                         "knn-fleet", "knn-control",
                                         "knn-history", "knn-alerts"))]
    if bad_threads:
        return fail(f"quality/drift/compactor/workload worker thread(s) "
                    f"alive while disabled: {bad_threads}")
    leaked = [i.name for i in obs.registry().instruments()
              if i.name.startswith(("knn_quality_", "knn_drift_",
                                    "knn_cost_", "knn_capacity_",
                                    "knn_ivf_", "knn_mutable_",
                                    "knn_workload_", "knn_cache_",
                                    "knn_fleet_", "knn_shard_",
                                    "knn_control_", "knn_history_",
                                    "knn_alerts_"))]
    if leaked:
        return fail(f"quality/drift/cost/capacity/ivf/mutable/workload "
                    f"instrument(s) recorded while disabled: {leaked}")
    print("disabled-overhead: quality/drift/cost/capacity/ivf/mutable/"
          "workload off-state ok (no scorer, no monitor, no accountant, "
          "no tracker, no probe policy, no delta engine, no compactor, "
          "no capture recorder, no worker threads, zero instruments, "
          "zero queue activity)")

    # -- 1b. the device-side layer (obs/devprof.py) off-state --------------
    # Even with the compile listener having been registered by a PRIOR
    # enable (jax.monitoring offers no unregister), a disabled process
    # must record nothing: force the listener in, compile a fresh shape,
    # sample device memory, probe the executable-cache tracker.
    from knn_tpu.obs import devprof

    devprof.install_compile_listeners()
    import jax
    import jax.numpy as jnp

    jax.jit(lambda x: x * 2 + 1)(jnp.ones((17, 3))).block_until_ready()
    devprof.record_device_memory()
    if devprof.record_executable_lookup("gate", ("probe",)) != "off":
        return fail("devprof.record_executable_lookup tracked a signature "
                    "while disabled")
    instruments = obs.registry().instruments()
    if instruments:
        return fail(f"devprof recorded {len(instruments)} instrument(s) "
                    f"while disabled (first: {instruments[0].name!r}) — "
                    f"the compile listener / memory gauges must gate on "
                    f"obs.enabled()")
    print("disabled-overhead: devprof off-state ok (compile listener, "
          "memory sample, cache tracker all recorded nothing)")

    # -- 1c. the fleet router without flags (PR 16) ------------------------
    # A router booted with no --event-log / --access-log must construct
    # ZERO fleet-observability machinery: no FleetEventLog (no ring, no
    # file handle), no AccessLog (the serve module must not even be
    # imported for it), no hedge-pool worker threads before a first
    # forward. This runs AFTER the plain-serve fleet sys.modules
    # assertions above — importing the router here is the opted-in path.
    from knn_tpu.fleet.router import RouterApp

    router = RouterApp(["http://127.0.0.1:9"],  # port 9: never listening
                       health_interval_s=3600.0, poll_timeout_s=0.2)
    try:
        if router.events is not None:
            return fail("RouterApp built a fleet event log with no "
                        "--event-log — the audit layer must not exist "
                        "while disabled")
        if router.access_log is not None:
            return fail("RouterApp built an access log with no "
                        "--access-log")
        if router.recorder is None:
            return fail("RouterApp dropped its default flight recorder "
                        "(the serve parity contract: tracing is on, "
                        "bounded, --flight-recorder-size 0 disables)")
        if router._pool._threads:
            return fail(f"{len(router._pool._threads)} hedge-pool "
                        f"thread(s) started before any forward — the "
                        f"pool must stay lazy")
        if router.set.events is not None:
            return fail("the health poller holds an event log while "
                        "disabled")
        # Self-healing bootstrap (PR 17): a flagless router (no
        # --auto-failover) must construct ZERO bootstrap machinery — no
        # reseed driver threads, nothing inflight, and the poll hook
        # must bail before touching the replica set.
        if router._bootstrap_inflight or router._bootstrap_last:
            return fail("RouterApp tracked bootstrap work with "
                        "auto-failover off")
        if router.reseeds != 0:
            return fail("RouterApp counted a reseed with auto-failover "
                        "off")
        router._maybe_bootstrap()  # must be a no-op without the flag
        boot_threads = [t.name for t in threading.enumerate()
                        if t.name.startswith("knn-fleet-bootstrap")]
        if boot_threads:
            return fail(f"bootstrap driver thread(s) alive on a "
                        f"flagless router: {boot_threads}")
        # Fleet autoscaler (PR 19): no --scale-cmd must construct ZERO
        # autoscale machinery — no policy, no offered-load ring, no
        # control import, and the poll hook must bail immediately.
        if router.autoscale is not None or router._offered is not None:
            return fail("RouterApp built autoscale machinery with no "
                        "--scale-cmd — the layer must not exist while "
                        "disabled")
        if "knn_tpu.control.autoscale" in sys.modules:
            return fail("knn_tpu.control.autoscale imported on a "
                        "flagless router")
        router._maybe_autoscale()  # must be a no-op without the flag
        scale_threads = [t.name for t in threading.enumerate()
                         if t.name.startswith("knn-control-autoscale")]
        if scale_threads:
            return fail(f"autoscale driver thread(s) alive on a "
                        f"flagless router: {scale_threads}")
        # Durable history + alerting (PR 20): a flagless router must
        # construct ZERO history/alerting machinery — no recorder, no
        # scraping thread, no alert engine.
        if router.history is not None or router.alerts is not None:
            return fail("RouterApp built history/alerting machinery "
                        "with no --history-dir/--alert-rules — the "
                        "layer must not exist while disabled")
        for mod in ("knn_tpu.obs.history", "knn_tpu.obs.alerts"):
            if mod in sys.modules:
                return fail(f"{mod} imported on a flagless router — "
                            f"history/alerting machinery must not "
                            f"construct while disabled")
        hist_threads = [t.name for t in threading.enumerate()
                        if t.name.startswith(("knn-history", "knn-alerts"))]
        if hist_threads:
            return fail(f"history/alert thread(s) alive on a flagless "
                        f"router: {hist_threads}")
    finally:
        router.close()
    leaked = [i.name for i in obs.registry().instruments()
              if i.name.startswith("knn_fleet_")]
    if leaked:
        return fail(f"router off-state recorded fleet instrument(s) "
                    f"with obs disabled: {leaked}")
    print("disabled-overhead: router off-state ok (no event log, no "
          "access log, lazy hedge pool, zero instruments)")

    # -- 2. timing: best-of mins under the budget --------------------------
    # Measured WITH a cost-accounting-enabled ServeApp alive (PR 8) AND a
    # workload-capture window armed (PR 11): both layers live entirely on
    # the serving dispatch path, so their existence must not move the
    # classify-path predict budget at all — and each must actually
    # construct + record when asked (the on-state sanity half).
    import tempfile

    budget_ms = float(os.environ.get("KNN_TPU_OVERHEAD_BUDGET_MS", "60"))
    capture_tmp = tempfile.mkdtemp(prefix="knn-overhead-capture-")
    app_on = ServeApp(model, max_batch=8, max_wait_ms=0.0,
                      cost_accounting=True, capture_dir=capture_tmp)
    try:
        if app_on.accounting is None or app_on.capacity is None:
            return fail("ServeApp(cost_accounting=True) did not build the "
                        "accounting/capacity layers")
        if app_on.workload is None or app_on.batcher.workload is None:
            return fail("ServeApp(capture_dir=...) did not build the "
                        "workload capture layer")
        app_on.workload.start(reason="overhead-gate")
        app_on.batcher.predict(test.features[0], timeout=60)
        if not app_on.workload.drain(10):
            return fail("workload capture queue did not drain")
        cap_stat = app_on.workload.export()
        if cap_stat["captured_events"] < 1:
            return fail("workload capture ON recorded nothing for a "
                        "served request")
        print(f"disabled-overhead: workload-capture on-state ok "
              f"({cap_stat['captured_events']} event(s) captured, "
              f"{cap_stat['shed']} shed)")
        totals = app_on.accounting.export()["totals"]
        if totals["dispatches"] < 1 or totals["dispatch_wall_ms"] <= 0:
            return fail("cost accounting ON attributed nothing for a "
                        "served request")
        print("disabled-overhead: cost-accounting on-state ok "
              f"({totals['dispatches']} dispatch(es) attributed, "
              f"{totals['attributed_ms']:.2f} ms conserved)")
        walls = []
        for _ in range(BEST_OF):
            t0 = time.monotonic()
            model.predict(test)
            walls.append((time.monotonic() - t0) * 1e3)
    finally:
        app_on.close()
        import shutil

        shutil.rmtree(capture_tmp, ignore_errors=True)
    best = min(walls)
    print(f"disabled-overhead: medium-preset predict best-of-{BEST_OF} min "
          f"{best:.2f} ms with cost accounting on (budget "
          f"{budget_ms:.0f} ms; all: {[round(w, 1) for w in walls]})")
    if best > budget_ms:
        return fail(f"best-of min {best:.2f} ms exceeds the "
                    f"{budget_ms:.0f} ms budget — the disabled path "
                    f"regressed (KNN_TPU_OVERHEAD_BUDGET_MS overrides)")
    print("disabled-overhead: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
