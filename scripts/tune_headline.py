"""Sweep candidate implementations of the headline config (large k=5) on the
real device and report marginal ms/step for each, so bench.py can pin the
fastest *exact* (prediction-parity) path.

Usage: python scripts/tune_headline.py
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from knn_tpu.obs.bench_timing import pipelined_slope as _pipelined_slope

K = 5


def slope(mkstep, bufs, r_lo=20, r_hi=80):
    return _pipelined_slope(mkstep, bufs, r_lo, r_hi)[0]


def main():
    import jax
    import jax.numpy as jnp

    from bench import load_large
    from knn_tpu.backends.tpu import knn_forward, knn_forward_tiled
    from knn_tpu.ops.pallas_knn import knn_pallas_candidates
    from knn_tpu.ops.vote import vote
    from knn_tpu.utils.evaluate import confusion_matrix, accuracy
    from knn_tpu.utils.padding import pad_axis_to_multiple

    train, test, is_ref = load_large()
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}", file=sys.stderr)
    n, d_true = train.features.shape
    q = test.num_instances
    nc = train.num_classes
    tx = jnp.asarray(train.features)
    ty = jnp.asarray(train.labels)
    golden = None

    def report(name, step, bufs, preds):
        nonlocal golden
        acc = accuracy(confusion_matrix(preds, test.labels, nc))
        if golden is None:
            golden = preds
        par = "==" if np.array_equal(preds, golden) else "DIVERGED"
        ms = slope(step, bufs) * 1e3
        print(f"{name:42s} {ms:8.3f} ms/step  {q/(ms/1e3):10.0f} q/s  "
              f"acc {acc:.4f}  {par}")

    # 1. Full-matrix (current headline).
    bufs_full = [jnp.asarray(test.features + np.float32(i) * 1e-7) for i in range(8)]
    jax.block_until_ready(bufs_full)

    def step_full(qb):
        return knn_forward(tx, ty, qb, k=K, num_classes=nc)

    report("full-matrix exact", step_full,
           bufs_full, np.asarray(step_full(bufs_full[0])))

    # 2. Tiled running-top-k, tile sweep.
    for q_tile, t_tile in [(1792, 4096), (1792, 8192), (896, 8192),
                           (1792, 16384), (1792, 32768)]:
        txp, _ = pad_axis_to_multiple(train.features, t_tile, axis=0)
        typ, _ = pad_axis_to_multiple(train.labels, t_tile, axis=0)
        txj, tyj = jnp.asarray(txp), jnp.asarray(typ)
        nv = jnp.asarray(n, jnp.int32)
        bufs = []
        for i in range(8):
            qp, _ = pad_axis_to_multiple(
                test.features + np.float32(i) * 1e-7, q_tile, axis=0)
            bufs.append(jnp.asarray(qp))
        jax.block_until_ready(bufs)

        def step_tiled(qb, txj=txj, tyj=tyj, nv=nv, q_tile=q_tile, t_tile=t_tile):
            return knn_forward_tiled(
                txj, tyj, qb, nv, k=K, num_classes=nc, precision="exact",
                query_tile=q_tile, train_tile=t_tile)

        report(f"tiled exact q={q_tile} t={t_tile}", step_tiled, bufs,
               np.asarray(step_tiled(bufs[0]))[:q])

    # 2b. Lane-striped Pallas exact kernel, block sweep (current headline).
    from knn_tpu.ops.pallas_knn import (
        knn_stripe_classify, stripe_prepare_train, stripe_prepare_queries,
    )

    for b_q, b_n in [(448, 2048), (448, 4096), (256, 2048), (896, 2048),
                     (224, 2048), (448, 1024)]:
        try:
            txT_h, d_pad = stripe_prepare_train(train.features, b_n)
            txT = jnp.asarray(txT_h)
            nv = jnp.asarray(n, jnp.int32)
            bufs = []
            for i in range(8):
                bufs.append(jnp.asarray(stripe_prepare_queries(
                    test.features + np.float32(i) * 1e-7, b_q, d_pad)))
            jax.block_until_ready(bufs)

            def step_stripe(qb, txT=txT, nv=nv, b_q=b_q, b_n=b_n):
                return knn_stripe_classify(
                    txT, ty, qb, nv, k=K, num_classes=nc,
                    block_q=b_q, block_n=b_n, d_true=d_true)

            p = np.asarray(step_stripe(bufs[0]))[:q]
        except Exception as e:
            print(f"stripe bq={b_q} bn={b_n}: FAILED {type(e).__name__}")
            continue
        report(f"pallas stripe exact bq={b_q} bn={b_n}", step_stripe, bufs, p)

    # 3. Pallas exact, block sweep.
    for b_q, b_n in [(256, 1024), (256, 4096), (896, 4096), (896, 8192),
                     (1792, 2048)]:
        txp, _ = pad_axis_to_multiple(train.features, b_n, axis=0)
        txp, _ = pad_axis_to_multiple(txp, 128, axis=1)
        txj = jnp.asarray(txp)
        bufs = []
        for i in range(8):
            qp, _ = pad_axis_to_multiple(
                test.features + np.float32(i) * 1e-7, b_q, axis=0)
            qp, _ = pad_axis_to_multiple(qp, 128, axis=1)
            bufs.append(jnp.asarray(qp))
        jax.block_until_ready(bufs)

        def step_pal(qb, txj=txj, b_q=b_q, b_n=b_n):
            return knn_pallas_candidates(
                txj, qb, n, K, block_q=b_q, block_n=b_n,
                d_true=d_true, precision="exact")

        def preds_of(qb, step=step_pal):
            _, idx = step(qb)
            idx = np.asarray(idx)[:q]
            return np.asarray(vote(ty[np.minimum(idx, n - 1)], nc))

        try:
            p = preds_of(bufs[0])
        except Exception as e:
            print(f"pallas exact bq={b_q} bn={b_n}: FAILED {type(e).__name__}: {e}")
            continue
        report(f"pallas exact bq={b_q} bn={b_n}", step_pal, bufs, p)


if __name__ == "__main__":
    main()
