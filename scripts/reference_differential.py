"""Differential harness against the REAL reference binary.

Builds the reference's serial backend from the read-only checkout
(/root/reference, or KNN_REFERENCE_DIR) into build/ref/, generates random
ARFF train/test pairs — comma-, whitespace-, and multi-line-tokenized, with
duplicate rows for dist==0 ties — and compares the complete canonical output
line (instance counts AND accuracy) of the reference against this
framework's oracle backend on the same files (the oracle is itself pinned
prediction-equal to every other backend by tests/ and make parity, so its
parity here transfers).

This validates the two things file-level tests cannot: that the parser
dialect matches the reference parser's on real inputs, and that the KNN
contract (tie semantics included) matches the reference kernel's.

Scope: all-NUMERIC unquoted data — deliberately, because that is the only
input class the reference can actually process end-to-end (probed against
the built binary: quoted data cells make it silently drop rows, '?' and
nominal feature values throw in its distance kernel). Comment lines are
included; the tokenization styles cover comma/whitespace/multi-line/
multi-row forms.

Usage: python scripts/reference_differential.py [trials]
"""

from __future__ import annotations

import contextlib
import os
import re
import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

REF_DIR = Path(os.environ.get("KNN_REFERENCE_DIR", "/root/reference"))
REF_BIN = REPO / "build" / "ref" / "main"


def build_reference() -> bool:
    if REF_BIN.exists():
        return True
    if not (REF_DIR / "main.cpp").exists():
        print("reference sources unavailable; skipping", file=sys.stderr)
        return False
    REF_BIN.parent.mkdir(parents=True, exist_ok=True)
    srcs = [str(REF_DIR / "main.cpp")] + [
        str(p) for p in sorted((REF_DIR / "libarff").glob("*.cpp"))
    ]
    proc = subprocess.run(
        ["g++", "-O2", "-o", str(REF_BIN), *srcs, f"-I{REF_DIR}/libarff"],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        print(f"reference build failed:\n{proc.stderr[:500]}", file=sys.stderr)
        return False
    return True


@contextlib.contextmanager
def _probe_file(body: str):
    """Write ``body`` to a temp ARFF under build/ and yield its path — the
    shared probe protocol for the load-differential checks."""
    with tempfile.TemporaryDirectory(dir=REPO / "build") as td:
        p = Path(td) / "probe.arff"
        p.write_text(body)
        yield p


def _run_reference(body: str) -> str:
    """Run the built reference binary on ``body`` (train == test, k=1);
    returns combined stdout+stderr."""
    with _probe_file(body) as p:
        r = subprocess.run(
            [str(REF_BIN), str(p), str(p), "1"],
            capture_output=True, text=True, timeout=60,
        )
        return r.stdout + r.stderr


def _load_ours(body: str):
    """Parse ``body`` with our loader."""
    from knn_tpu.data.arff import load_arff

    with _probe_file(body) as p:
        return load_arff(str(p))


def random_arff_pair(rng) -> tuple:
    d = int(rng.integers(1, 8))  # features (class col added on top)
    c = int(rng.integers(2, 6))
    n = int(rng.integers(c, 200))
    q = int(rng.integers(1, 40))
    hi = int(rng.integers(2, 5))

    def header():
        lines = [f"@relation r{int(rng.integers(1e6))}"]
        for j in range(d):
            lines.append(f"@attribute a{j} NUMERIC")
        lines.append("@attribute class NUMERIC")
        if rng.random() < 0.3:
            lines.append("% header comment")
        lines.append("@data")
        if rng.random() < 0.3:
            lines.append("% data comment")
        return lines

    def rows(mat, labels):
        out = []
        i = 0
        while i < len(mat):
            cells = [fmt(v) for v in mat[i]] + [str(int(labels[i]))]
            style = rng.random()
            if style < 0.5:
                out.append(",".join(cells))
            elif style < 0.7:
                out.append(" ".join(cells))  # whitespace-separated
            elif style < 0.85 and len(cells) > 1:
                cut = int(rng.integers(1, len(cells)))
                out.append(",".join(cells[:cut]) + ",")  # row spans lines
                out.append(",".join(cells[cut:]))
            elif i + 1 < len(mat):
                nxt = [fmt(v) for v in mat[i + 1]] + [str(int(labels[i + 1]))]
                out.append(",".join(cells) + " " + ",".join(nxt))  # 2 rows/line
                i += 1
            else:
                out.append(",".join(cells))
            i += 1
        return out

    def fmt(v):
        return str(int(v)) if float(v).is_integer() else f"{v:.6g}"

    train_x = rng.integers(0, hi, (n, d)).astype(np.float32)
    train_y = np.concatenate([np.arange(c), rng.integers(0, c, n - c)])
    dup = min(q // 2, n)
    test_x = np.concatenate([
        train_x[rng.choice(n, dup, replace=False)] if dup else
        np.empty((0, d), np.float32),
        rng.integers(0, hi, (q - dup, d)).astype(np.float32),
    ])
    test_y = rng.integers(0, c, q)
    train = "\n".join(header() + rows(train_x, train_y)) + "\n"
    test = "\n".join(header() + rows(test_x, test_y)) + "\n"
    return train, test, n, q


_LINE = re.compile(
    r"The (\d+)-NN classifier for (\d+) test instances on (\d+) train "
    r"instances required \d+ ms CPU time. Accuracy was ([0-9.]+)"
)


def canonical(out: str):
    m = _LINE.search(out)
    return m.groups() if m else None


def string_load_differential() -> int:
    """VERDICT r1 #2: the reference PARSES files with STRING data cells
    (arff_parser.cpp:145-147) and only aborts when its KNN kernel reads one
    as float ("operator float cannot work on type 'STRING'!",
    arff_value.cpp:121). Differential: run the real binary on such a file and
    assert its failure is that *conversion* error (proving the load
    succeeded, not a parse rejection); then assert our parser loads the same
    file and our CLI defers to a clean predict-time error."""
    import tempfile

    from knn_tpu.data.arff import load_arff

    body = (
        "@relation strcol\n"
        "@attribute host STRING\n"
        "@attribute x NUMERIC\n"
        "@attribute class NUMERIC\n"
        "@data\n"
        "web1,1,0\nweb2,2,1\nweb1,3,0\n"
    )
    with tempfile.TemporaryDirectory(dir=REPO / "build") as td:
        p = Path(td) / "s.arff"
        p.write_text(body)
        ref = subprocess.run(
            [str(REF_BIN), str(p), str(p), "1"],
            capture_output=True, text=True, timeout=60,
        )
        ref_out = ref.stdout + ref.stderr
        if "operator float cannot work" not in ref_out:
            print("FAIL string differential: reference did not reach the "
                  f"conversion error (rc={ref.returncode}): {ref_out[:200]}")
            return 1
        ds = load_arff(str(p))  # must load (interned codes)
        if ds.num_instances != 3 or ds.attributes[0].string_values != [
            "web1", "web2",
        ]:
            print(f"FAIL string differential: bad load "
                  f"(n={ds.num_instances}, table={ds.attributes[0].string_values})")
            return 1
        ours = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", str(p), str(p), "1",
             "--backend", "oracle"],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        if ours.returncode != 1 or "not" not in ours.stderr:
            print(f"FAIL string differential: expected clean predict-time "
                  f"error, got rc={ours.returncode}: {ours.stderr[:200]}")
            return 1
    print("string-column load differential: reference parses + aborts in-KNN; "
          "we parse + defer with a clean error — OK")
    return 0


def nominal_header_differential() -> int:
    """VERDICT r1 weak #6: header-level differentials over NOMINAL attribute
    declarations against the real binary. The reference cannot run KNN over
    nominal features (operator float throws, arff_value.cpp:121), but its
    header/data PARSING is still observable through which error it dies with.
    Pinned classes (each probed against the built binary):

    - bare ``{red,blue}`` declaration + declared data values: the reference
      parses header AND data, dying only at the kernel's float conversion
      (arff_value.cpp:121) — so our parser must load the same file (interned
      nominal codes; classifying on them is a documented liberal extension,
      PARITY.md).
    - undeclared data value: the reference dies in add_instance set
      validation (arff_data.cpp:148) — ours must reject with a located
      parse error, same classification.
    - QUOTED declaration value ``{'da rk',blue}``: the reference *lexer*
      derails (consumes to EOF, parse abort at arff_parser.cpp:114) — ours
      accepts quoted declaration values: deliberate liberal-superset
      deviation, asserted here so a dialect regression is caught.
    - unterminated value list: both sides reject at parse time.
    """
    def hdr(decl: str, *rows: str) -> str:
        return "\n".join(
            ["@relation n", decl, "@attribute x NUMERIC",
             "@attribute class NUMERIC", "@data", *rows]
        ) + "\n"

    failures = 0

    bare = hdr("@attribute color {red,blue}", "red,1,0", "blue,2,1")
    if "operator float cannot work" not in _run_reference(bare):
        print("FAIL nominal differential: reference did not reach the "
              "conversion error on a bare declaration (parse regressed?)")
        failures += 1
    try:
        ds = _load_ours(bare)
        ok = (ds.attributes[0].nominal_values == ["red", "blue"]
              and ds.features[:, 0].tolist() == [0.0, 1.0])
    except Exception as e:
        ds, ok = None, False
        print(f"FAIL nominal differential: bare declaration rejected: {e}")
    if ds is not None and not ok:
        print(f"FAIL nominal differential: bad load of bare declaration "
              f"({ds.attributes[0].nominal_values}, {ds.features[:, 0]})")
    if not ok:
        failures += 1

    undecl = hdr("@attribute color {red,blue}", "purple,1,0")
    if "not found" not in _run_reference(undecl):
        print("FAIL nominal differential: reference accepted an undeclared "
              "nominal value")
        failures += 1
    try:
        _load_ours(undecl)
        print("FAIL nominal differential: we accepted an undeclared "
              "nominal value")
        failures += 1
    except Exception as e:
        if "not in nominal set" not in str(e):
            print(f"FAIL nominal differential: wrong undeclared-value error: {e}")
            failures += 1

    quoted = hdr("@attribute color {'da rk',blue}", "'da rk',1,0", "blue,2,1")
    if "END_OF_FILE" not in _run_reference(quoted):
        print("FAIL nominal differential: reference now parses quoted "
              "declaration values — the pinned liberal-superset deviation "
              "no longer holds (reference dialect changed?)")
        failures += 1
    try:
        ds = _load_ours(quoted)  # liberal superset: must parse here
        if ds.attributes[0].nominal_values != ["da rk", "blue"]:
            print(f"FAIL nominal differential: quoted declaration mis-parsed "
                  f"({ds.attributes[0].nominal_values})")
            failures += 1
    except Exception as e:
        print(f"FAIL nominal differential: quoted declaration rejected: {e}")
        failures += 1

    unterm = hdr("@attribute color {red,blue", "red,1,0")
    if "_read_attr" not in _run_reference(unterm):
        print("FAIL nominal differential: reference accepted an "
              "unterminated value list")
        failures += 1
    try:
        _load_ours(unterm)
        print("FAIL nominal differential: we accepted an unterminated "
              "value list")
        failures += 1
    except Exception as e:
        if "unterminated nominal" not in str(e):
            print(f"FAIL nominal differential: wrong unterminated error: {e}")
            failures += 1

    if failures == 0:
        print("nominal-header differential: bare/undeclared/quoted/"
              "unterminated declaration classes all match the pinned "
              "reference behaviors — OK")
    return failures


def multiline_header_differential() -> int:
    """r3 (VERDICT r2 missing #1): header declarations spanning physical
    lines. Pinned against the built binary:

    - UNQUOTED nominal list continuing on the next line (``{red,\\n blue}``):
      the reference's token-stream reader treats the newline as ordinary
      whitespace (arff_lexer.cpp:93-97) and parses the header, dying only at
      the kernel's float conversion (arff_value.cpp:121) — so our parsers
      must load the same file with the same nominal table. This was the last
      documented dialect gap (both parsers were line-based before r3).
    - MULTI-LINE QUOTED declaration value (``{'re\\nd',blue}``): the
      reference lexer derails on the quote itself (same as the single-line
      quoted class above — parse abort at arff_parser.cpp:114); ours parses
      with the newline preserved inside the value (_read_str semantics,
      arff_lexer.cpp:159-188): the pinned liberal-superset deviation.
    """
    failures = 0

    unq = ("@relation n\n@attribute color {red,\n  blue}\n"
           "@attribute x NUMERIC\n@attribute class NUMERIC\n@data\n"
           "red,1,0\nblue,2,1\n")
    if "operator float cannot work" not in _run_reference(unq):
        print("FAIL multiline differential: reference did not parse an "
              "unquoted multi-line nominal list (dialect changed?)")
        failures += 1
    try:
        ds = _load_ours(unq)
        if (ds.attributes[0].nominal_values != ["red", "blue"]
                or ds.features[:, 0].tolist() != [0.0, 1.0]):
            print(f"FAIL multiline differential: bad load of multi-line list "
                  f"({ds.attributes[0].nominal_values}, {ds.features[:, 0]})")
            failures += 1
    except Exception as e:
        print(f"FAIL multiline differential: multi-line list rejected: {e}")
        failures += 1

    mlq = ("@relation n\n@attribute color {'re\nd',blue}\n"
           "@attribute x NUMERIC\n@attribute class NUMERIC\n@data\n"
           "blue,2,1\n")
    if "_read_attr" not in _run_reference(mlq):
        print("FAIL multiline differential: reference no longer derails on a "
              "multi-line quoted declaration value (dialect changed?)")
        failures += 1
    try:
        ds = _load_ours(mlq)
        if ds.attributes[0].nominal_values != ["re\nd", "blue"]:
            print(f"FAIL multiline differential: multi-line quoted value "
                  f"mis-parsed ({ds.attributes[0].nominal_values})")
            failures += 1
    except Exception as e:
        print(f"FAIL multiline differential: multi-line quoted value "
              f"rejected: {e}")
        failures += 1

    if failures == 0:
        print("multiline-header differential: unquoted-continuation and "
              "multi-line-quoted classes match the pinned reference "
              "behaviors — OK")
    return failures


def main(trials: int = 40) -> int:
    if not build_reference():
        return 0
    # Load-differential (string/nominal) failures are tracked separately so
    # they can't trip the random-trial abort below or inflate its summary.
    load_failures = (string_load_differential() + nominal_header_differential()
                     + multiline_header_differential())
    failures = 0
    rng = np.random.default_rng(314159)
    for t in range(trials):
        train_body, test_body, n, q = random_arff_pair(rng)
        k = int(rng.integers(1, min(n, 8) + 1))
        with tempfile.TemporaryDirectory(dir=REPO / "build") as td:
            tr, te = Path(td) / "train.arff", Path(td) / "test.arff"
            tr.write_text(train_body)
            te.write_text(test_body)
            ref = subprocess.run(
                [str(REF_BIN), str(tr), str(te), str(k)],
                capture_output=True, text=True, timeout=120,
            )
            ours = subprocess.run(
                [sys.executable, "-m", "knn_tpu.cli", str(tr), str(te), str(k),
                 "--backend", "oracle"],
                capture_output=True, text=True, timeout=300, cwd=REPO,
            )
            a, b = canonical(ref.stdout), canonical(ours.stdout)
            if a is None or b is None or a[:3] != b[:3] or a[3] != b[3]:
                failures += 1
                print(f"FAIL trial {t} (k={k}, n={n}, q={q}):")
                print(f"  reference: {ref.stdout.strip()[:100]} "
                      f"(rc={ref.returncode})")
                print(f"  ours:      {ours.stdout.strip()[:100]} "
                      f"(rc={ours.returncode})")
                if failures > 3:
                    break
        if (t + 1) % 10 == 0:
            print(f"{t + 1}/{trials} trials, {failures} divergences",
                  file=sys.stderr)
    print("reference differential:",
          "ALL IDENTICAL" if failures == 0 else f"{failures} DIVERGENCES",
          f"({trials} random dataset pairs, counts + accuracy)"
          + ("" if load_failures == 0
             else f"; {load_failures} load-differential failures above"))
    return 1 if failures or load_failures else 0


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 40))
