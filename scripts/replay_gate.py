"""Workload replay gate (`make replay-gate`).

Proves the whole capture → replay → what-if chain end to end, in one
process (docs/OBSERVABILITY.md §Workload capture & replay):

1. build a small index artifact and boot a MUTABLE in-process serving
   stack (micro-batcher + delta engine) with workload capture armed;
2. drive a seeded bursty open-loop mix of reads and inserts/deletes and
   finalize the capture window into a workload artifact;
3. replay the artifact against a PRISTINE twin of the serving stack
   (same artifact bytes copied before any mutation, hence the same
   ``index_version``) and assert the enforced promises:
   - zero read errors and zero mutation errors,
   - every replayed mutation lands on its captured ``mutation_seq``,
   - **zero answer divergences** wherever ``index_version`` and
     ``mutation_seq`` match the capture (bit-identical digests), with a
     non-trivial fraction of reads actually verified (a gate that
     skipped everything would prove nothing);
4. fit the replay's dispatch-cost model (obs/capacity.py) and run the
   what-if simulator (obs/whatif.py) for the LIVE policy over the
   captured arrival process: the predicted p50 must agree with the
   measured replay p50 within the documented band
   ``|predicted - measured| <= max(5 ms, 0.6 x measured)`` — generous
   because the simulator deliberately omits scheduler jitter and
   host-side bookkeeping, tight enough that a simulator modeling the
   wrong policy (or a fit in the wrong units) cannot pass;
5. record a small candidate-policy frontier in the verdict JSON (what
   the simulator exists for), reported, not asserted.

Exit 0 on success; 1 with a diagnosis otherwise. Run on CPU jax.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

READS = 150
INSERTS = 16
DELETES = 8
POLICY = {"max_batch": 16, "max_wait_ms": 1.0}
#: The replay side runs SHAPE-BUCKETED with the result cache armed: the
#: gate's zero-divergence assertion then pins that bucketed dispatch,
#: continuous batching, and cache hits stay bit-identical to the
#: capture-side answers through the live mutable tier.
BUCKETS = (2, 4, 8, 16)
RESULT_CACHE_ROWS = 512
MAX_QUEUE_ROWS = 4096
#: The documented predicted-vs-measured p50 agreement band.
BAND_ABS_MS = 5.0
BAND_REL = 0.6


def fail(msg: str) -> int:
    print(f"replay-gate: FAIL: {msg}", file=sys.stderr)
    return 1


def seed_capacity(capacity, model, max_batch: int) -> None:
    """The warmup seeding rule (ServeApp._seed_capacity_model): two
    post-compile timed dispatches give the affine fit its endpoints
    before replay traffic refines it."""
    from knn_tpu.data.dataset import Dataset

    train = model.train_
    for rows in sorted({1, max_batch}):
        feats = train.features[:rows]
        ds = Dataset(feats, np.zeros(rows, np.int32))
        best = None
        for _ in range(2):
            t0 = time.monotonic()
            model.kneighbors(ds)
            wall = (time.monotonic() - t0) * 1e3
            best = wall if best is None else min(best, wall)
        capacity.seed_dispatch_model(rows, best)


def drive_capture(batcher, capture, test, rng) -> None:
    """Seeded bursty open-loop traffic: reads + an interleaved mutation
    stream (inserts first, deletes only of already-inserted stable ids)."""
    d = test.features.shape[1]
    base_rows = batcher._model.train_.num_instances
    events = []  # ("read", kind, rows) | ("insert", rows, values) | ...
    inserted = 0
    deletable = []
    for i in range(READS):
        r = int(rng.integers(1, 5))
        start = int(rng.integers(0, test.features.shape[0] - r))
        kind = "kneighbors" if rng.random() < 0.25 else "predict"
        events.append(("read", kind, test.features[start:start + r]))
        if i % (READS // INSERTS) == 3 and inserted < INSERTS:
            rows = rng.normal(0.0, 2.0, (1, d)).astype(np.float32)
            values = [int(rng.integers(0, 4))]
            events.append(("insert", rows, values))
            deletable.append(base_rows + inserted)
            inserted += 1
        if i % (READS // DELETES) == 7 and deletable and len(deletable) > 2:
            sid = deletable.pop(0)
            events.append(("delete", [sid], None))
    capture.start(reason="gate")
    futures = []
    for ev in events:
        # Bursty pacing: the middle third arrives 3x faster.
        mean_ms = 4.0 if len(futures) % 3 == 1 else 10.0
        time.sleep(float(rng.exponential(mean_ms)) / 1e3)
        if ev[0] == "read":
            futures.append(batcher.submit(ev[2], ev[1]))
        elif ev[0] == "insert":
            futures.append(batcher.submit_mutation(
                "insert", {"rows": ev[1], "values": ev[2]}))
        else:
            futures.append(batcher.submit_mutation(
                "delete", {"ids": ev[1]}))
    for f in futures:
        f.result(timeout=60)


def main() -> int:
    import argparse
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args()

    from tests import fixtures
    from knn_tpu.models.knn import KNNClassifier
    from knn_tpu.mutable.engine import MutableEngine
    from knn_tpu.obs import whatif
    from knn_tpu.obs.capacity import CapacityTracker
    from knn_tpu.obs.replay import replay_workload
    from knn_tpu.obs.workload import WorkloadCapture, load_workload
    from knn_tpu.serve import artifact
    from knn_tpu.serve.batcher import MicroBatcher

    train, test = fixtures.load_pair("small")
    rng = np.random.default_rng(42)
    verdict: dict = {"policy": dict(POLICY)}

    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        dir_a, dir_b = tmp / "index-a", tmp / "index-b"
        artifact.save_index(KNNClassifier(k=3).fit(train), dir_a)
        shutil.copytree(dir_a, dir_b)  # the pristine twin: same bytes,
        # same manifest, hence the SAME index_version tag

        # -- capture side ---------------------------------------------------
        model_a = artifact.load_index(dir_a)
        version = artifact.index_version(artifact.read_manifest(dir_a))
        artifact.warmup(model_a, batch_sizes=(1, POLICY["max_batch"]),
                        kinds=("predict",))
        engine_a = MutableEngine(model_a, dir_a, version=version)
        capture = WorkloadCapture(
            tmp / "captures", num_features=train.num_features, k=3,
            policy=dict(POLICY), index_version=version,
        )
        batcher_a = MicroBatcher(
            model_a, max_batch=POLICY["max_batch"],
            max_wait_ms=POLICY["max_wait_ms"],
            max_queue_rows=MAX_QUEUE_ROWS, index_version=version,
            workload=capture, mutable=engine_a,
        )
        try:
            drive_capture(batcher_a, capture, test, rng)
            capture.drain(30)
            summary = capture.stop()
        finally:
            batcher_a.close()
            engine_a.close()
            capture.close()
        print(f"replay-gate: captured {summary['requests']} requests + "
              f"{summary['mutations']} mutations over "
              f"{summary['duration_ms']:.0f} ms (shed {summary['shed']})")
        if summary["requests"] < READS:
            return fail(f"capture lost reads: {summary['requests']} < "
                        f"{READS}")
        if summary["mutations"] < INSERTS:
            return fail(f"capture lost mutations: {summary['mutations']}")
        wl = load_workload(summary["path"])
        verdict["captured"] = {
            "requests": summary["requests"],
            "mutations": summary["mutations"],
            "duration_ms": summary["duration_ms"],
            **wl.captured_latency_summary(),
        }

        # -- replay side (the pristine twin) --------------------------------
        model_b = artifact.load_index(dir_b)
        version_b = artifact.index_version(artifact.read_manifest(dir_b))
        if version_b != version:
            return fail(f"twin artifact version {version_b} != {version} — "
                        f"the copy is not byte-faithful")
        engine_b = MutableEngine(model_b, dir_b, version=version_b)
        capacity = CapacityTracker(POLICY["max_batch"])
        from knn_tpu.models.knn import query_bucket_ladder

        with query_bucket_ladder(BUCKETS):
            # Warm EVERY bucket before the clock starts (the serve boot's
            # rule): a cold bucket's first-dispatch compile would land in
            # the measured replay AND poison the dispatch-cost fit the
            # what-if check rides.
            artifact.warmup(model_b, batch_sizes=(1,) + BUCKETS,
                            kinds=("predict",))
            seed_capacity(capacity, model_b, POLICY["max_batch"])
            batcher_b = MicroBatcher(
                model_b, max_batch=POLICY["max_batch"],
                max_wait_ms=POLICY["max_wait_ms"],
                max_queue_rows=MAX_QUEUE_ROWS, index_version=version_b,
                capacity=capacity, mutable=engine_b,
                buckets=BUCKETS, result_cache_rows=RESULT_CACHE_ROWS,
            )
            try:
                rv = replay_workload(wl, batcher=batcher_b, speed=1.0,
                                     verify="tag")
            finally:
                batcher_b.close()
                engine_b.close()
        cache_stats = batcher_b.cache.stats()
        verdict["result_cache"] = cache_stats
        print(f"replay-gate: bucketed replay (ladder {BUCKETS}) with "
              f"result cache: {cache_stats['hits']} hits / "
              f"{cache_stats['misses']} misses / "
              f"{cache_stats['evictions']} evictions")
        cap_doc = capacity.export()
        verdict["replay"] = rv
        verdict["replay_capacity"] = {
            k: cap_doc[k] for k in
            ("occupancy_mean", "padded_row_waste_ratio", "duty_cycle",
             "dispatch_model")
        }
        m, v, mu = rv["measured"], rv["verify"], rv["mutations"]
        print(f"replay-gate: replayed {m['requests']} reads p50 "
              f"{m['p50_ms']} ms / p99 {m['p99_ms']} ms; verified "
              f"{v['verified']}, divergences {v['divergences']}, "
              f"tag-skipped {v['skipped_tag_mismatch']}; mutations "
              f"{mu['ok']}/{mu['fired']} ok, {mu['seq_aligned']} "
              f"seq-aligned")
        if m["errors"] != 0:
            return fail(f"{m['errors']} replayed reads errored: "
                        f"{rv['error_samples']}")
        if mu["ok"] != mu["fired"] or mu["fired"] != summary["mutations"]:
            return fail(f"mutation replay incomplete: {mu}")
        if mu["seq_aligned"] != mu["fired"]:
            return fail(f"replayed mutations landed off their captured "
                        f"mutation_seq: {mu['seq_aligned']}/{mu['fired']} "
                        f"aligned — ordering broke")
        if v["divergences"] != 0:
            return fail(f"{v['divergences']} answer(s) diverged at "
                        f"matching index_version/mutation_seq: "
                        f"{v['divergence_samples']}")
        if v["verified"] < m["requests"] // 2:
            return fail(f"only {v['verified']}/{m['requests']} reads were "
                        f"verifiable at matching tags — the replay "
                        f"drifted too far off the captured mutation "
                        f"timeline to prove anything")

        # -- what-if prediction vs the measured replay ----------------------
        fit = cap_doc["dispatch_model"]
        if fit["a_ms"] is None:
            return fail(f"no dispatch-cost fit after replay: {fit}")
        sim = whatif.simulate(
            wl.arrivals(), max_batch=POLICY["max_batch"],
            max_wait_ms=POLICY["max_wait_ms"],
            a_ms=fit["a_ms"], b_ms_per_row=fit["b_ms_per_row"],
            buckets=BUCKETS,
        )
        band = max(BAND_ABS_MS, BAND_REL * m["p50_ms"])
        delta = abs(sim["p50_ms"] - m["p50_ms"])
        verdict["whatif"] = {
            "predicted": sim,
            "measured_p50_ms": m["p50_ms"],
            "delta_ms": round(delta, 3),
            "band_ms": round(band, 3),
            "band_rule": f"max({BAND_ABS_MS} ms, {BAND_REL} x measured)",
            "dispatch_model": fit,
        }
        print(f"replay-gate: what-if predicted p50 {sim['p50_ms']} ms vs "
              f"measured {m['p50_ms']} ms (delta {delta:.2f} ms, band "
              f"{band:.2f} ms, fit {fit['source']}: a={fit['a_ms']} "
              f"b={fit['b_ms_per_row']})")
        if delta > band:
            return fail(f"what-if p50 {sim['p50_ms']} ms disagrees with "
                        f"the measured replay p50 {m['p50_ms']} ms beyond "
                        f"the {band:.2f} ms band")

        # -- candidate frontier (reported, not asserted) --------------------
        candidates = [
            dict(POLICY),
            {"max_batch": POLICY["max_batch"],
             "max_wait_ms": POLICY["max_wait_ms"],
             "buckets": [1, 2, 4, 8, 16]},
            {"max_batch": 64, "max_wait_ms": 5.0},
            {"max_batch": 1, "max_wait_ms": 0.0},
        ]
        verdict["frontier"] = whatif.frontier(
            wl.arrivals(), candidates, a_ms=fit["a_ms"],
            b_ms_per_row=fit["b_ms_per_row"],
        )

    verdict["pass"] = True
    if args.json_out:
        out = Path(args.json_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(verdict, indent=2) + "\n")
        print(f"replay-gate: verdict written to {out}")
    print("replay-gate: PASS")
    return 0


if __name__ == "__main__":
    rc = main()
    sys.exit(rc)
