"""mpiexec-analogue launcher for the multi-process KNN worker.

Reference invocation (mpi.cpp:123)::

    mpiexec -np P ./mpi train.arff test.arff k

Equivalent here::

    python scripts/launch_multihost.py -np P train.arff test.arff k

Spawns P copies of ``knn_tpu.parallel.multihost`` on this machine, wires the
JAX distributed coordinator env vars (the launcher role mpiexec plays for
MPI_Init), and streams rank 0's output. Off-TPU each process gets
``--devices-per-proc`` virtual CPU devices, so a laptop can exercise the same
multi-controller code path a TPU pod runs; on a real pod, run one worker per
host with the same env vars instead (or rely on auto-detection).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> int:
    p = argparse.ArgumentParser(prog="launch_multihost")
    p.add_argument("-np", "--num-procs", type=int, default=2)
    p.add_argument("--devices-per-proc", type=int, default=2,
                   help="virtual CPU devices per process (ignored on TPU)")
    p.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("rest", nargs=argparse.REMAINDER,
                   help="worker args: train.arff test.arff k [flags]")
    args = p.parse_args()
    if not args.rest:
        p.error("missing worker args: train.arff test.arff k")

    port = free_port()
    procs = []
    for rank in range(args.num_procs):
        env = dict(
            os.environ,
            KNN_TPU_COORD_ADDR=f"127.0.0.1:{port}",
            KNN_TPU_NUM_PROCS=str(args.num_procs),
            KNN_TPU_PROC_ID=str(rank),
        )
        if args.platform == "cpu":
            # KNN_TPU_PLATFORM is the framework's own knob: init_from_env
            # applies it over a sitecustomize-forced platform. JAX_PLATFORMS
            # is deliberately NOT used for this — on the axon box the
            # tunnel exports JAX_PLATFORMS=axon ambiently, so honoring it
            # in-process trampled explicitly-set configs (r5).
            env["KNN_TPU_PLATFORM"] = "cpu"
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={args.devices_per_proc}"
            ).strip()
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "knn_tpu.parallel.multihost", *args.rest],
                env=env,
                cwd=REPO,
                stdout=None if rank == 0 else subprocess.DEVNULL,
                stderr=None if rank == 0 else subprocess.DEVNULL,
            )
        )
    rc = 0
    for proc in procs:
        rc = proc.wait() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
