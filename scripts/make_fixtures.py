"""Generate synthetic ARFF fixtures with the same shape characteristics as the
reference's dataset ladder (SURVEY.md §2.4): numeric attrs with the class as
the last column, sentinel rows labeled 0..9 pinning num_classes=10, and test
rows duplicated from train so dist==0 tie-breaking is exercised."""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

SIZES = {
    # name: (n_train, n_test, n_features)
    "small": (592, 80, 7),
    "medium": (7354, 370, 11),
    "large": (30803, 1718, 11),
}


def write_arff(path: Path, x: np.ndarray, y: np.ndarray, relation: str) -> None:
    d = x.shape[1]
    with open(path, "w") as f:
        f.write(f"@relation {relation}\n\n")
        for i in range(d):
            f.write(f"@attribute attr{i} NUMERIC\n")
        f.write("@attribute class NUMERIC\n\n@data\n")
        for row, label in zip(x, y):
            f.write(",".join(f"{v:.6g}" for v in row) + f",{int(label)}\n")


def make(size: str, out_dir: Path, seed: int = 0) -> None:
    n_train, n_test, d = SIZES[size]
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 5, size=(10, d))
    labels = rng.integers(0, 10, size=n_train)
    x = centers[labels] + rng.normal(0, 1.5, size=(n_train, d))
    # Sentinel rows 0..9 at the top (mirrors the reference datasets).
    labels[:10] = np.arange(10)
    x[:10] = centers[np.arange(10)] + rng.normal(0, 1.5, size=(10, d))
    x = x.astype(np.float32)

    # Half the test set duplicates train rows (dist==0 ties), half is fresh.
    n_dup = n_test // 2
    dup_idx = rng.choice(n_train, size=n_dup, replace=False)
    tl = rng.integers(0, 10, size=n_test - n_dup)
    tx = np.concatenate(
        [x[dup_idx], (centers[tl] + rng.normal(0, 1.5, size=(n_test - n_dup, d))).astype(np.float32)]
    )
    ty = np.concatenate([labels[dup_idx], tl])

    out_dir.mkdir(parents=True, exist_ok=True)
    write_arff(out_dir / f"{size}-train.arff", x, labels, f"{size}-train")
    write_arff(out_dir / f"{size}-test.arff", tx, ty, f"{size}-test")


def all_paths(out_dir: Path):
    return [
        out_dir / f"{size}-{part}.arff" for size in SIZES
        for part in ("train", "test")
    ]


def main():
    args = [a for a in sys.argv[1:] if a != "--if-stale"]
    if_stale = "--if-stale" in sys.argv[1:]
    out = Path(args[0]) if args else Path("build/fixtures")
    if if_stale:
        script_mtime = Path(__file__).stat().st_mtime
        if all(
            p.exists() and p.stat().st_mtime >= script_mtime
            for p in all_paths(out)
        ):
            print(f"fixtures in {out} are up to date")
            return
    for size in SIZES:
        make(size, out)
    print(f"fixtures written to {out}")


if __name__ == "__main__":
    main()
