"""Chaos-soak gate (`make chaos-soak`): the serving stack under sustained,
seeded fault injection with concurrent closed-loop clients — the
self-healing acceptance run (docs/SERVING.md §Ops runbook).

What it does:

1. build a fixture index (`knn_tpu save-index`, small-train.arff, k=3);
2. boot `knn_tpu serve` as a subprocess with a seeded fault plan armed
   (``KNN_TPU_FAULTS=serve.dispatch=<N>`` — the first N fast-rung
   dispatches fail) and tight breaker knobs so the whole
   closed→open→half-open→closed cycle fits the soak window;
3. run C concurrent closed-loop clients POSTing /predict for the window,
   while a poller samples /healthz (breaker state, draining flag);
4. assert the invariants:
   - every request gets exactly ONE terminal outcome — an HTTP status or
     (only after SIGTERM) a refused connection; a client thread that
     never returns is a hang and fails the gate;
   - every 200 body is **bit-identical to the synchronous oracle**
     (`knn_oracle` on the same rows) and carries ``index_version``;
   - every terminal response (200/429/503/504) carries a ``request_id``,
     and every request_id a client saw **resolves to exactly one
     flight-recorder timeline** (``/debug/requests``) whose phases are
     all closed and sum to within tolerance of its ``request_ms``;
   - the SLO burn rate (the ``fast_rung`` objective — requests served by
     a degradation rung spend its budget) RISES during the fault burst
     and RECOVERS to ~0 after the breaker re-closes;
   - no response body ever contains a traceback;
   - zero 500s: in-loop degradation must absorb the whole fault burst;
   - the breaker OPENS under the burst and RE-CLOSES after it clears,
     with a steady probe of sequential requests all answering 200
     (availability back to 100%);
   - the declarative alert loop closes end-to-end: a ``burn_rate`` rule
     on the ``fast_rung`` objective FIRES during the burst, its
     ``capture`` action self-arms a workload window (artifact reason
     ``alert:fast-rung-burn``), and the alert RESOLVES after the breaker
     re-closes — fire + resolve both land as an audit pair in
     ``alerts.jsonl`` under the history dir;
   - a final SIGTERM under load drains cleanly: exit code 0 within
     ``--drain-timeout-s`` + grace, in-flight requests answered;
5. after the drain, run the post-mortem path against the dead server's
   history dir: ``knn_tpu report`` must stitch the metrics history,
   the alert pair, and the alert-armed capture into one incident
   report (``build/chaos-soak-incident.{md,json}``); the alert audit
   trail and capture artifact are copied to ``build/`` too — CI
   uploads all of them as workflow artifacts;
6. emit a BENCH-style availability / error-budget JSON on stdout, and
   (``--perfetto-out``) save the per-request Perfetto trace of the soak's
   recorded timelines — CI uploads it as a workflow artifact.

Exit 0 when every invariant holds; 1 with a diagnosis otherwise.
stdlib-only (urllib) — the gate must not depend on host tools.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import procgroup  # noqa: E402 — scripts-dir sibling (process-group
# spawn + atexit kill sweep: a failed assertion can never strand a server)

READY_RE = re.compile(r"ready on (http://[\d.]+:\d+)")
BOOT_TIMEOUT_S = 120  # first-call compile on a cold cache can be slow
TRACEBACK_MARKER = "Traceback (most recent call last)"


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--short", action="store_true",
                   help="CI preset: ~20 s wall (6 s soak window)")
    p.add_argument("--window-s", type=float, default=None,
                   help="soak window under concurrent clients")
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--faults", type=int, default=None,
                   help="KNN_TPU_FAULTS=serve.dispatch=<N> burst size")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--drain-timeout-s", type=float, default=5.0)
    p.add_argument("--json-out", default=None, metavar="FILE")
    p.add_argument("--perfetto-out", default=None, metavar="FILE",
                   help="save the soak's per-request Perfetto trace "
                   "(/debug/requests?format=perfetto) here")
    args = p.parse_args()
    if args.window_s is None:
        args.window_s = 6.0 if args.short else 20.0
    if args.faults is None:
        args.faults = 12 if args.short else 25
    return args


def fail(msg: str, proc=None) -> int:
    print(f"chaos-soak: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    return 1


def http(base: str, path: str, payload=None, timeout=30):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"} if payload else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class Soak:
    """Shared state between client/poller threads and the main assertions."""

    def __init__(self, base, want, test_rows, sigterm_sent):
        self.base = base
        self.want = want  # oracle predictions for every test row
        self.test_rows = test_rows
        self.sigterm_sent = sigterm_sent  # threading.Event
        self.stop = threading.Event()
        self.lock = threading.Lock()
        self.outcomes: dict = {}  # status/str -> count
        self.violations: list = []
        self.ok_bit_identical = 0
        self.states_seen: set = set()
        self.draining_seen = False
        self.request_ids: set = set()  # ids carried by terminal responses
        self.max_fast_rung_burn = 0.0  # peak SLO burn seen by the poller

    def record(self, outcome: str) -> None:
        with self.lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1

    def violate(self, msg: str) -> None:
        with self.lock:
            if len(self.violations) < 20:  # enough to diagnose
                self.violations.append(msg)

    def client_loop(self, cid: int) -> None:
        q = len(self.test_rows)
        i = cid  # stagger the row windows per client
        while not self.stop.is_set():
            lo = (3 * i) % (q - 2)
            rows = self.test_rows[lo:lo + 2]
            i += 1
            try:
                st, body = http(self.base, "/predict",
                                {"instances": rows.tolist()})
            except Exception as e:  # noqa: BLE001 — classified below
                if self.sigterm_sent.is_set():
                    self.record("refused_during_shutdown")
                    return  # the listener is gone; the soak is over
                self.violate(f"client {cid}: transport error before "
                             f"SIGTERM: {type(e).__name__}: {e}")
                self.record("transport_error")
                continue
            self.record(str(st))
            if TRACEBACK_MARKER in body:
                self.violate(f"client {cid}: TRACEBACK in a response body "
                             f"(status {st}): {body[:200]}")
                continue
            try:
                doc = json.loads(body)
            except ValueError:
                self.violate(f"client {cid}: non-JSON body (status {st}): "
                             f"{body[:120]}")
                continue
            # Tracing invariant: EVERY terminal response carries a
            # request_id (resolved against /debug/requests later).
            rid = doc.get("request_id")
            if st in (200, 429, 503, 504):
                if not rid:
                    self.violate(f"client {cid}: status {st} response "
                                 f"without request_id: {body[:160]}")
                else:
                    with self.lock:
                        self.request_ids.add(rid)
            if st == 200:
                expect = self.want[lo:lo + 2].tolist()
                if doc.get("predictions") != expect:
                    self.violate(
                        f"client {cid}: rows [{lo}:{lo + 2}] NOT "
                        f"bit-identical to the oracle: got "
                        f"{doc.get('predictions')}, want {expect}"
                    )
                elif "index_version" not in doc:
                    self.violate(f"client {cid}: 200 without index_version")
                else:
                    with self.lock:
                        self.ok_bit_identical += 1
            elif st == 500:
                self.violate(f"client {cid}: 500 — the degradation ladder "
                             f"failed to absorb a fault: {body[:200]}")
            elif st not in (429, 503, 504):
                self.violate(f"client {cid}: unexpected status {st}: "
                             f"{body[:200]}")

    def poll_health(self) -> None:
        while not self.stop.is_set():
            try:
                _, body = http(self.base, "/healthz", timeout=5)
                doc = json.loads(body)
                burns = (doc.get("slo") or {}).get("burn_rates") or {}
                fast = max(
                    (v for v in (burns.get("fast_rung") or {}).values()),
                    default=0.0,
                )
                with self.lock:
                    self.states_seen.add(doc.get("breaker"))
                    if doc.get("draining"):
                        self.draining_seen = True
                    self.max_fast_rung_burn = max(
                        self.max_fast_rung_burn, fast)
            except Exception:  # noqa: BLE001 — the server may be gone
                if self.sigterm_sent.is_set():
                    return
            time.sleep(0.05)


def main() -> int:
    args = parse_args()
    from tests import fixtures  # noqa: E402 — repo-root import

    d = fixtures.datasets_dir()
    train_arff = str(d / "small-train.arff")
    test_arff = str(d / "small-test.arff")

    # The synchronous oracle every 200 must be bit-identical to.
    from knn_tpu.backends.oracle import knn_oracle
    from knn_tpu.data.arff import load_arff

    train, test = load_arff(train_arff), load_arff(test_arff)
    want = knn_oracle(train.features, train.labels, test.features, 3,
                      train.num_classes)

    fault_plan = f"serve.dispatch={args.faults}:device"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KNN_TPU_RETRY_BASE_MS="0",
        KNN_TPU_FAULTS=fault_plan,
        KNN_TPU_FAULT_SEED=str(args.seed),
        # Tight breaker so the full open -> half-open -> closed cycle fits
        # the soak window: opens after 3 fast-rung failures, probes every
        # 400 ms, one good probe re-closes.
        KNN_TPU_BREAKER_WINDOW="8",
        KNN_TPU_BREAKER_THRESHOLD="3",
        KNN_TPU_BREAKER_COOLDOWN_MS="400",
        KNN_TPU_BREAKER_PROBES="1",
    )

    with tempfile.TemporaryDirectory() as tmp:
        index = os.path.join(tmp, "index")
        build = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "save-index", train_arff,
             index, "--k", "3"],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, cwd=REPO,
        )
        if build.returncode != 0:
            return fail(f"save-index rc={build.returncode}: {build.stderr}")
        print(f"chaos-soak: {build.stdout.strip()}")
        print(f"chaos-soak: fault plan {fault_plan} (seed {args.seed}), "
              f"{args.clients} clients, {args.window_s:.0f} s window")

        # The declarative alert under test: the fast_rung burn already
        # asserted by phase 3.5, restated as a rules.json the operator
        # would actually ship. Its capture action must self-arm a
        # workload window at fire time — the closed forensics loop.
        history_dir = os.path.join(tmp, "history")
        capture_dir = os.path.join(tmp, "captures")
        access_log = os.path.join(tmp, "access.jsonl")
        rules_path = os.path.join(tmp, "rules.json")
        Path(rules_path).write_text(json.dumps([{
            "name": "fast-rung-burn",
            "type": "burn_rate",
            "objective": "fast_rung",
            "windows": ["5s"],
            "threshold": 0.5,
            "for_s": 0.5,
            "resolve_for_s": 1.0,
            "severity": "page",
            "actions": [{"do": "capture", "window_s": 4.0}],
        }], indent=1) + "\n")

        proc = procgroup.popen_group(
            [sys.executable, "-m", "knn_tpu.cli", "serve", index,
             "--port", "0", "--max-batch", "8", "--max-wait-ms", "1",
             "--drain-timeout-s", str(args.drain_timeout_s),
             # Tracing invariants: a recorder big enough to hold EVERY
             # soak request (so all request_ids resolve), and SLO windows
             # short enough that burn both rises during the burst and
             # visibly recovers within the soak.
             "--flight-recorder-size", "16384", "--slo-windows", "5,60",
             # Observability-history invariants: a snapshot cadence fast
             # enough that the alert engine sees the burst, plus the
             # capture + access-log machinery the incident report stitches.
             "--history-dir", history_dir, "--history-interval-s", "0.5",
             "--history-retention-s", "600",
             "--alert-rules", rules_path,
             "--capture-dir", capture_dir,
             "--access-log", access_log],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        import queue

        lines: "queue.Queue[str]" = queue.Queue()
        threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],
            daemon=True,
        ).start()
        base = None
        deadline = time.monotonic() + BOOT_TIMEOUT_S
        while time.monotonic() < deadline:
            try:
                line = lines.get(timeout=min(1.0, max(
                    0.01, deadline - time.monotonic())))
            except queue.Empty:
                if proc.poll() is not None:
                    return fail(
                        f"server exited rc={proc.poll()} before ready", proc)
                continue
            m = READY_RE.search(line)
            if m:
                print(f"chaos-soak: server: {line.rstrip()}")
                base = m.group(1)
                break
        if base is None:
            return fail("no ready banner within the boot timeout", proc)

        sigterm_sent = threading.Event()
        soak = Soak(base, want, test.features, sigterm_sent)
        clients = [
            threading.Thread(target=soak.client_loop, args=(cid,),
                             daemon=True)
            for cid in range(args.clients)
        ]
        poller = threading.Thread(target=soak.poll_health, daemon=True)
        t_soak0 = time.monotonic()
        poller.start()
        for t in clients:
            t.start()

        # -- phase 1: the fault burst + recovery, under load ---------------
        time.sleep(args.window_s)
        with soak.lock:
            opened = "open" in soak.states_seen
        if not opened:
            soak.stop.set()
            return fail(
                f"breaker never observed open during the {args.window_s:.0f}"
                f" s window (states seen: {sorted(map(str, soak.states_seen))}"
                f") — the fault burst did not trip it", proc)

        # -- phase 2: the burst is bounded; wait for re-close --------------
        reclose_deadline = time.monotonic() + 30
        breaker_state = None
        while time.monotonic() < reclose_deadline:
            try:
                _, body = http(base, "/healthz", timeout=5)
                breaker_state = json.loads(body).get("breaker")
                if breaker_state == "closed":
                    break
            except Exception:  # noqa: BLE001 — keep polling
                pass
            time.sleep(0.1)
        if breaker_state != "closed":
            soak.stop.set()
            return fail(f"breaker did not re-close after the fault burst "
                        f"(state: {breaker_state})", proc)
        print("chaos-soak: breaker cycle observed: closed -> open -> closed")

        # -- phase 3: steady probe — availability back to 100% -------------
        steady_ok = 0
        for i in range(15):
            lo = (2 * i) % (len(test.features) - 2)
            st, body = http(base, "/predict",
                            {"instances": test.features[lo:lo + 2].tolist()})
            doc = json.loads(body)
            if st != 200:
                soak.stop.set()
                return fail(f"steady probe {i}: status {st} after recovery "
                            f"({body[:200]})", proc)
            if doc["predictions"] != want[lo:lo + 2].tolist():
                soak.stop.set()
                return fail(f"steady probe {i}: not bit-identical after "
                            f"recovery", proc)
            steady_ok += 1
        print(f"chaos-soak: steady probe {steady_ok}/15 ok "
              f"(availability 100%, bit-identical)")

        # -- phase 3.5: SLO burn rose during the burst, recovers to ~0 -----
        with soak.lock:
            max_burn = soak.max_fast_rung_burn
        if max_burn <= 0.5:
            soak.stop.set()
            return fail(
                f"knn_slo_burn_rate{{objective=fast_rung}} never rose "
                f"during the fault burst (max seen: {max_burn}) — degraded "
                f"responses are not spending the fast-rung budget", proc)
        final_burn = None
        recover_deadline = time.monotonic() + 30
        while time.monotonic() < recover_deadline:
            try:
                _, body = http(base, "/healthz", timeout=5)
                burns = (json.loads(body).get("slo") or {}) \
                    .get("burn_rates") or {}
                final_burn = (burns.get("fast_rung") or {}).get("5s")
                if final_burn is not None and final_burn < 0.5:
                    break
            except Exception:  # noqa: BLE001 — keep polling
                pass
            time.sleep(0.25)
        if final_burn is None or final_burn >= 0.5:
            soak.stop.set()
            return fail(f"fast_rung burn rate did not recover to ~0 after "
                        f"the breaker re-closed (5s window: {final_burn}, "
                        f"peak {round(max_burn, 2)})", proc)
        print(f"chaos-soak: SLO burn cycle observed (fast_rung peak "
              f"{round(max_burn, 2)} -> {final_burn} after recovery)")

        # -- phase 3.55: the alert loop closes — fire during the burst,
        # capture self-armed, resolve after the breaker re-closes -------
        alert_rule = None
        alert_deadline = time.monotonic() + 30
        while time.monotonic() < alert_deadline:
            try:
                st, body = http(base, "/debug/alerts", timeout=5)
                doc = json.loads(body)
                alert_rule = next(
                    (r for r in doc.get("rules", ())
                     if r["name"] == "fast-rung-burn"), None)
                if (alert_rule and alert_rule["fires"] >= 1
                        and alert_rule["state"] == "ok"
                        and alert_rule["last_resolve"] is not None):
                    break
            except Exception:  # noqa: BLE001 — keep polling
                pass
            time.sleep(0.25)
        if alert_rule is None:
            soak.stop.set()
            return fail("/debug/alerts never listed the fast-rung-burn "
                        "rule", proc)
        if alert_rule["fires"] < 1:
            soak.stop.set()
            return fail("alert fast-rung-burn never FIRED during the "
                        f"fault burst (state: {alert_rule['state']})", proc)
        if alert_rule["state"] != "ok" or alert_rule["last_resolve"] is None:
            soak.stop.set()
            return fail(f"alert fast-rung-burn did not RESOLVE after the "
                        f"breaker re-closed (state: {alert_rule['state']})",
                        proc)
        print(f"chaos-soak: alert cycle observed: fast-rung-burn fired "
              f"x{alert_rule['fires']} and resolved")

        # The capture action armed a 4 s window at fire time; by resolve
        # (+history-cadence finalization at worst) its artifact must be
        # on disk with the alert's reason in the manifest.
        capture_manifest = None
        capture_deadline = time.monotonic() + 20
        while time.monotonic() < capture_deadline:
            for mf in sorted(Path(capture_dir).glob("workload-*/manifest.json")):
                man = json.loads(mf.read_text())
                if man.get("reason") == "alert:fast-rung-burn":
                    capture_manifest = mf
                    break
            if capture_manifest is not None:
                break
            time.sleep(0.25)
        if capture_manifest is None:
            soak.stop.set()
            return fail("the alert's capture action never produced a "
                        "workload artifact with reason "
                        "alert:fast-rung-burn under --capture-dir", proc)
        print(f"chaos-soak: alert-armed capture artifact: "
              f"{capture_manifest.parent.name} "
              f"({json.loads(capture_manifest.read_text()).get('records')} "
              f"records)")

        # -- phase 3.6: every request_id resolves to a consistent timeline -
        with soak.lock:
            seen_ids = set(soak.request_ids)
        st, body = http(base, "/debug/requests?n=20000", timeout=30)
        if st != 200:
            soak.stop.set()
            return fail(f"/debug/requests: status {st}: {body[:200]}", proc)
        doc = json.loads(body)
        timelines = doc.get("requests", [])
        recorded_ids = set()
        for tl in timelines:
            rid = tl.get("request_id")
            if rid in recorded_ids:
                soak.stop.set()
                return fail(f"request_id {rid} maps to more than one "
                            f"flight-recorder timeline", proc)
            recorded_ids.add(rid)
            if tl.get("outcome") is None:
                soak.stop.set()
                return fail(f"unfinished timeline in /debug/requests: "
                            f"{json.dumps(tl)[:200]}", proc)
            open_phases = [p["phase"] for p in tl.get("phases", ())
                           if p.get("ms") is None]
            if open_phases:
                soak.stop.set()
                return fail(f"timeline {rid} has unclosed phase(s) "
                            f"{open_phases} after its terminal outcome",
                            proc)
            phase_sum = sum(p["ms"] for p in tl.get("phases", ()))
            req_ms = tl.get("request_ms") or 0.0
            if phase_sum > req_ms * 1.05 + 2.0:
                soak.stop.set()
                return fail(f"timeline {rid}: phases sum {phase_sum:.2f} ms "
                            f"exceeds request_ms {req_ms:.2f} ms", proc)
        unresolved = seen_ids - recorded_ids
        if unresolved:
            soak.stop.set()
            return fail(f"{len(unresolved)} request_id(s) carried by "
                        f"terminal responses do not resolve in the flight "
                        f"recorder (first: {sorted(unresolved)[:3]})", proc)
        print(f"chaos-soak: {len(seen_ids)} request_ids all resolve to "
              f"consistent flight-recorder timelines "
              f"({len(timelines)} recorded)")

        if args.perfetto_out:
            st, body = http(base, "/debug/requests?format=perfetto&n=2000",
                            timeout=30)
            if st != 200:
                soak.stop.set()
                return fail(f"perfetto export: status {st}", proc)
            ev = json.loads(body).get("traceEvents", [])
            b = sum(1 for e in ev if e.get("ph") == "B")
            e_ = sum(1 for e in ev if e.get("ph") == "E")
            if b != e_:
                soak.stop.set()
                return fail(f"perfetto export misnested: {b} B vs {e_} E "
                            f"events", proc)
            Path(args.perfetto_out).parent.mkdir(parents=True, exist_ok=True)
            Path(args.perfetto_out).write_text(body)
            print(f"chaos-soak: per-request Perfetto trace -> "
                  f"{args.perfetto_out} ({len(ev)} events)")

        # -- phase 4: SIGTERM under load — graceful drain ------------------
        t_drain0 = time.monotonic()
        sigterm_sent.set()
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=args.drain_timeout_s + 15)
        except subprocess.TimeoutExpired:
            soak.stop.set()
            return fail("server did not exit after SIGTERM within the "
                        "drain window + grace", proc)
        drain_ms = (time.monotonic() - t_drain0) * 1e3
        soak.stop.set()
        for t in clients:
            t.join(timeout=35)
            if t.is_alive():
                return fail("a client thread never finished its request — "
                            "a request HUNG with no terminal outcome")
        poller.join(timeout=5)
        if rc != 0:
            return fail(f"server exited rc={rc} after SIGTERM (graceful "
                        f"drain must exit 0)")

        # -- phase 5: post-mortem — the incident report path against the
        # DEAD server's history dir (the 3am answer, docs/SERVING.md) ----
        build_dir = REPO / "build"
        build_dir.mkdir(exist_ok=True)
        audit_src = Path(history_dir) / "alerts.jsonl"
        if not audit_src.exists():
            return fail("alerts.jsonl missing under the history dir after "
                        "shutdown")
        audit = [json.loads(ln) for ln in
                 audit_src.read_text().splitlines() if ln.strip()]
        fires = [e for e in audit if e.get("event") == "fire"
                 and e.get("alert") == "fast-rung-burn"]
        resolves = [e for e in audit if e.get("event") == "resolve"
                    and e.get("alert") == "fast-rung-burn"]
        if not fires or not resolves:
            return fail(f"alerts.jsonl lacks the fire/resolve audit pair "
                        f"({len(fires)} fires, {len(resolves)} resolves)")
        if not any(e.get("event") == "action" and e.get("action") == "capture"
                   and e.get("outcome") == "ok" for e in audit):
            return fail("alerts.jsonl has no successful capture-action "
                        "audit entry")
        import shutil
        shutil.copy(audit_src, build_dir / "chaos-soak-alerts.jsonl")
        cap_dst = build_dir / "chaos-soak-capture"
        if cap_dst.exists():
            shutil.rmtree(cap_dst)
        shutil.copytree(capture_manifest.parent, cap_dst)

        report_cmd = subprocess.run(
            [sys.executable, "-m", "knn_tpu.cli", "report",
             "--history", history_dir,
             "--access-log", access_log,
             "--captures", capture_dir,
             "--out", str(build_dir / "chaos-soak-incident.md"),
             "--json-out", str(build_dir / "chaos-soak-incident.json")],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, cwd=REPO,
        )
        if report_cmd.returncode != 0:
            return fail(f"knn_tpu report rc={report_cmd.returncode}: "
                        f"{report_cmd.stderr[:400]}")
        incident = json.loads(
            (build_dir / "chaos-soak-incident.json").read_text())
        kinds = {e["kind"] for e in incident.get("timeline", ())}
        if not {"alert-fire", "alert-resolve"} <= kinds:
            return fail(f"incident timeline lacks the alert fire/resolve "
                        f"pair (kinds: {sorted(kinds)})")
        if not any(e["kind"] == "capture"
                   and e.get("reason") == "alert:fast-rung-burn"
                   for e in incident.get("timeline", ())):
            return fail("incident timeline does not reference the "
                        "alert-armed capture")
        print(f"chaos-soak: incident report stitched "
              f"({len(incident['timeline'])} timeline entries, "
              f"{incident['history']['samples']} history samples) -> "
              f"{build_dir / 'chaos-soak-incident.md'}")

        # -- verdict -------------------------------------------------------
        if soak.violations:
            for v in soak.violations:
                print(f"chaos-soak: VIOLATION: {v}", file=sys.stderr)
            return fail(f"{len(soak.violations)} invariant violation(s)")

        total = sum(soak.outcomes.values())
        ok = soak.outcomes.get("200", 0)
        report = {
            "chaos_soak": {
                "window_s": args.window_s,
                "clients": args.clients,
                "fault_plan": fault_plan,
                "seed": args.seed,
                "soak_wall_s": round(time.monotonic() - t_soak0, 2),
            },
            "requests_total": total,
            "outcomes": dict(sorted(soak.outcomes.items())),
            "availability": round(ok / total, 4) if total else None,
            "bit_identical_ok": soak.ok_bit_identical,
            "error_budget": {
                "traceback_bodies": 0,
                "untyped_500s": soak.outcomes.get("500", 0),
                "hung_requests": 0,
            },
            "breaker": {
                "opened": True,
                "reclosed": True,
                "states_seen": sorted(
                    s for s in soak.states_seen if s is not None),
            },
            "slo": {
                "fast_rung_burn_peak": round(max_burn, 3),
                "fast_rung_burn_recovered": final_burn,
            },
            "alerts": {
                "fires": len(fires),
                "resolves": len(resolves),
                "capture_artifact": capture_manifest.parent.name,
                "incident_timeline_entries": len(incident["timeline"]),
            },
            "tracing": {
                "request_ids_resolved": len(seen_ids),
                "timelines_recorded": len(timelines),
            },
            "steady_probe": {"ok": steady_ok, "of": 15},
            "drain": {
                "exit_code": rc,
                "wall_ms": round(drain_ms, 1),
                "draining_observed": soak.draining_seen,
            },
        }
        doc = json.dumps(report, indent=2)
        print(doc)
        if args.json_out:
            Path(args.json_out).write_text(doc + "\n")
        print("chaos-soak: PASS")
        return 0


if __name__ == "__main__":
    sys.exit(main())
