"""Regenerate the SURVEY.md §6 accuracy table across backends and assert
prediction-level parity (SURVEY.md §7 step 8).

Runs every requested backend over the dataset ladder x k grid, checks exact
prediction equality against the oracle (stronger than the reference's
accuracy-equality grading, SURVEY.md §4), and prints a markdown table with
golden-accuracy checkmarks.

Usage:
  python scripts/parity_report.py [--backends tpu,tpu-pallas,...] [--large]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

GOLDEN = {
    ("small", 1): 0.8500, ("small", 5): 0.8625,
    ("medium", 5): 0.3081,
    ("large", 1): 0.9919, ("large", 5): 0.9948, ("large", 10): 0.7538,
}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--backends", default="oracle,native,native-mt,tpu,tpu-pallas")
    p.add_argument("--large", action="store_true",
                   help="include the large dataset (slow off-TPU)")
    args = p.parse_args()

    from knn_tpu.backends import available_backends, get_backend
    from knn_tpu.utils.evaluate import confusion_matrix, accuracy
    from tests.fixtures import load_pair, using_reference_datasets

    configs = [("small", 1), ("small", 5), ("medium", 5)]
    if args.large:
        configs += [("large", 1), ("large", 5), ("large", 10)]
    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    missing = [b for b in backends if b not in available_backends()]
    if missing:
        print(f"note: skipping unavailable backends {missing}", file=sys.stderr)
        backends = [b for b in backends if b not in missing]

    is_ref = using_reference_datasets()
    rows = []
    failures = 0
    for size, k in configs:
        train, test = load_pair(size)
        golden = None
        for name in backends:
            t0 = time.monotonic()
            preds = get_backend(name)(train, test, k)
            ms = (time.monotonic() - t0) * 1e3
            acc = accuracy(confusion_matrix(preds, test.labels, test.num_classes))
            if golden is None:
                golden = preds
                parity = "oracle"
            else:
                parity = "==" if np.array_equal(preds, golden) else "DIVERGED"
                if parity == "DIVERGED":
                    failures += 1
            gold_ok = ""
            if is_ref and (size, k) in GOLDEN:
                gold_ok = " ✓" if round(acc, 4) == GOLDEN[(size, k)] else " ✗GOLDEN"
                if "✗" in gold_ok:
                    failures += 1
            rows.append((size, k, name, acc, ms, parity + gold_ok))

    print(f"| dataset | k | backend | accuracy | ms | parity |")
    print(f"|---|---|---|---|---|---|")
    for size, k, name, acc, ms, parity in rows:
        print(f"| {size} | {k} | {name} | {acc:.4f} | {ms:.0f} | {parity} |")
    if failures:
        print(f"\n{failures} PARITY FAILURE(S)", file=sys.stderr)
        return 1
    print(f"\nall {len(rows)} runs prediction-identical"
          + (" and golden-accurate" if is_ref else " (synthetic fixtures)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
