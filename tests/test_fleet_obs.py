"""Fleet observability plane contract tests (ISSUE 16).

The load-bearing claims:

1. **Cross-tier tracing**: the router records its own timeline per
   forwarded request (``route`` + ``dispatch`` phases, one attempt per
   replica tried) and ``GET /debug/requests?id=`` stitches it LIVE with
   the answering replicas' timelines — one document, no orphans, even on
   the racy paths (retry after demotion, a hedge where both attempts
   complete, a 502-INDETERMINATE write).
2. **Audit events**: health transitions, promotions, hedges, reloads and
   the failover window land in the append-only event log, stamped with
   the triggering request_id where one exists.
3. **Replication SLIs**: follower lag in seqs AND milliseconds, read
   staleness annotated on lagging-follower responses, and the
   failover-window histogram measured 503-onset -> first post-promote 200.
4. **Federation**: router ``/metrics`` merges per-replica registry
   snapshots under a ``{replica=…}`` label (obs/aggregate.py — never a
   lossy pre-sum).

The end-to-end kill-the-primary forensics leg lives in
``scripts/fleet_soak.py``; these tests pin the contracts tier-1 fast.
"""

import json
import threading
import time

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.fleet.events import FleetEventLog
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs import reqtrace
from knn_tpu.resilience import faults

from tests.test_fleet import _Replica, _artifact, _http, _problem


def _local_rng():
    # Deliberately NOT the session-scoped ``rng`` fixture: that generator is
    # shared and stateful, so drawing from it here would shift the random
    # stream seen by every test module collected after this one.
    return np.random.default_rng(1016)


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


def _router(urls, **kw):
    from knn_tpu.fleet.router import RouterApp, make_router_server

    kw.setdefault("health_interval_s", 0.1)
    app = RouterApp(urls, **kw)
    server = make_router_server(app)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    host, port = server.server_address[:2]
    return app, server, f"http://{host}:{port}"


def _close_router(app, server):
    server.shutdown()
    server.server_close()
    app.close()


def _rid_of(app):
    """The newest router timeline's request_id."""
    recent = app.recorder.recent(1)
    assert recent, "the router recorded no timeline"
    return recent[0]["request_id"]


# -- 1. the event log --------------------------------------------------------


class TestFleetEventLog:
    def test_ring_file_and_taxonomy(self, tmp_path):
        path = tmp_path / "events.jsonl"
        log = FleetEventLog(str(path), capacity=4)
        try:
            log.emit("demote", replica="http://r1", role="primary")
            log.emit("promote", request_id="abc", replica="http://r2")
            for i in range(5):
                log.emit("hedge-fired", hop=i)
        finally:
            log.close()
        # The ring keeps only the newest `capacity`; the FILE keeps all.
        assert log.export()["emitted"] == 7
        assert log.export()["retained"] == 4
        recs = log.recent()
        assert [r["event"] for r in recs] == ["hedge-fired"] * 4
        assert log.recent(2)[-1]["hop"] == 4  # newest-n, chronological
        lines = [json.loads(ln) for ln in
                 path.read_text().strip().splitlines()]
        assert len(lines) == 7
        assert lines[0]["event"] == "demote"
        assert lines[1]["request_id"] == "abc"
        assert all("ts" in ln for ln in lines)

    def test_no_path_is_ring_only(self):
        log = FleetEventLog(None)
        log.emit("rejoin", replica="http://r1")
        assert log.find("rejoin")[0]["replica"] == "http://r1"
        assert log.export()["path"] is None
        log.close()


# -- 2. cross-tier stitching (pure export math) ------------------------------


def _fake_timeline(rid, start_unix, ms, phases=(), attempts=()):
    return {
        "request_id": rid, "kind": "kneighbors", "rows": 1,
        "start_unix": start_unix, "outcome": "ok", "request_ms": ms,
        "phases": [dict(p) for p in phases],
        "attempts": [dict(a) for a in attempts], "events": [],
    }


class TestStitching:
    def test_one_process_per_tier_shared_epoch(self):
        router_tl = _fake_timeline(
            "r1", 100.0, 5.0,
            phases=({"phase": "route", "start_ms": 0.0, "ms": 0.1},
                    {"phase": "dispatch", "start_ms": 0.1, "ms": 4.8}),
            attempts=({"rung": "http://a", "ok": True, "ms": 4.7},))
        replica_tl = _fake_timeline("r1", 100.001, 4.0)
        doc = reqtrace.stitch_chrome_trace(
            [("router", [router_tl]), ("http://a", [replica_tl])])
        assert doc["otherData"]["tiers"] == ["router", "http://a"]
        procs = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("name") == "process_name"}
        assert procs == {1: "router", 2: "http://a"}
        # Shared epoch: the replica's envelope begin is offset by the
        # wall-clock delta (1 ms = 1000 us), not re-zeroed.
        rep_begin = [e for e in doc["traceEvents"]
                     if e["pid"] == 2 and e["ph"] == "B"
                     and e["name"].startswith("request:")]
        assert rep_begin and abs(rep_begin[0]["ts"] - 1000.0) < 1e-6

    def test_missing_replica_timeline_is_skipped_not_an_orphan(self):
        router_tl = _fake_timeline("r2", 50.0, 3.0)
        doc = reqtrace.stitch_chrome_trace(
            [("router", [router_tl]), ("http://dead", [None])])
        pids = {e["pid"] for e in doc["traceEvents"]
                if e.get("cat") == "knn_tpu.request"}
        assert pids == {1}  # only the router tier has slices
        assert doc["otherData"]["tiers"] == ["router", "http://dead"]

    def test_empty_stitch_is_empty(self):
        assert reqtrace.stitch_trace_events([("router", [])]) == []


# -- 3. the router's request timelines + /debug surfaces ---------------------


class TestRouterTracing:
    @pytest.fixture
    def plain_pair(self, tmp_path, obs_on):
        import shutil

        model = KNNClassifier(k=3, engine="xla").fit(_problem(_local_rng()))
        a_dir = _artifact(model, tmp_path, "a")
        b_dir = tmp_path / "b"
        shutil.copytree(a_dir, b_dir)
        from knn_tpu.serve.artifact import index_version, read_manifest

        version = index_version(read_manifest(a_dir))
        a = _Replica(model, a_dir, index_version=version)
        b = _Replica(model, b_dir, index_version=version)
        yield a, b, model
        a.close()
        b.close()

    def test_retry_after_demotion_one_timeline_no_orphans(
            self, plain_pair):
        """Racy path #1: the first replica dies mid-fleet; the read
        retries on the survivor. EXACTLY one router timeline with both
        attempts; the stitched document links the survivor's timeline
        (carrying the retry's hop number) and reports the dead replica
        as absent — not an orphan, not an error."""
        a, b, model = plain_pair
        # Freeze the poller (boot poll marked both healthy) so the DEAD
        # replica is still routed to — the per-request retry is on trial
        # here, not the health loop.
        app, server, url = _router([a.url, b.url], event_log=True,
                                   health_interval_s=3600.0)
        try:
            q = model.train_.features[:1].tolist()
            a.kill()
            app._rr = 1  # next read starts its walk at a (the corpse)
            st, doc = _http(url, "/kneighbors", {"instances": q})
            assert st == 200, doc
            rid = _rid_of(app)
            # Exactly one router timeline for this id.
            assert sum(1 for t in app.recorder.recent()
                       if t["request_id"] == rid) == 1
            tl = app.recorder.find(rid)
            assert tl["outcome"] == "ok"
            phases = {p["phase"] for p in tl["phases"]}
            assert phases == {"route", "dispatch"}
            # Attempt 1 failed on the dead replica, attempt 2 answered.
            assert [a_["ok"] for a_ in tl["attempts"]] == [False, True]
            assert tl["attempts"][0]["rung"] == a.url
            assert tl["attempts"][1]["rung"] == b.url
            assert [a_["hop"] for a_ in tl["attempts"]] == [1, 2]
            # The stitched doc: survivor linked with the right hop,
            # dead replica explicitly None.
            st, stitched = _http(url, f"/debug/requests?id={rid}")
            assert st == 200
            assert stitched["router"]["request_id"] == rid
            assert stitched["replicas"][a.url] is None
            rep = stitched["replicas"][b.url]
            assert rep["request_id"] == rid
            assert rep["upstream_attempt"] == 2
            # The passive demotion was audited with this request's id.
            demotes = app.events.find("passive-demote")
            assert demotes and demotes[0]["request_id"] == rid
            assert demotes[0]["replica"] == a.url
            # Perfetto render carries both tiers.
            st, trace = _http(url,
                              f"/debug/requests?id={rid}&format=perfetto")
            assert st == 200
            assert trace["otherData"]["tiers"] == ["router", a.url, b.url]
        finally:
            _close_router(app, server)
            a.app.close()

    def test_hedge_both_complete_loser_drained_and_counted(
            self, plain_pair, monkeypatch, obs_on):
        """Racy path #2: the hedge fires and BOTH attempts complete. One
        router timeline records hedge-fired + hedge-won; the loser is
        drained (counted ``knn_fleet_hedge_wasted_total``, never
        silently discarded) and BOTH replica timelines stitch in."""
        a, b, model = plain_pair
        from knn_tpu.fleet import router as router_mod

        q = model.train_.features[:1].tolist()
        # Warm both replicas' compile caches directly (bypassing the
        # router) so the race below is decided by the injected delay,
        # not by whoever compiles first.
        for rep in (a, b):
            st, _doc = _http(rep.url, "/kneighbors", {"instances": q})
            assert st == 200
        real_fb = router_mod.forward_bytes
        slow_url = a.url

        def delayed(method, url, body, timeout, headers):
            if url.startswith(slow_url):
                time.sleep(0.25)
            return real_fb(method, url, body, timeout, headers)

        monkeypatch.setattr(router_mod, "forward_bytes", delayed)
        app, server, url = _router([a.url, b.url], hedge="40",
                                   event_log=True)
        try:
            # Pin the round-robin start so candidates[0] is the slow one.
            app._rr = 1
            st, doc = _http(url, "/kneighbors", {"instances": q})
            assert st == 200, doc
            rid = _rid_of(app)
            tl = app.recorder.find(rid)
            ev = [e["event"] for e in tl["events"]]
            assert "hedge-fired" in ev and "hedge-won" in ev
            fired = app.events.find("hedge-fired")
            assert fired and fired[0]["request_id"] == rid
            assert fired[0]["slow_replica"] == a.url
            # Wait for the slow loser to complete, then: it was drained
            # and counted, not dropped.
            deadline = time.monotonic() + 5
            wasted = None
            while time.monotonic() < deadline:
                wasted = [i for i in obs.registry().instruments()
                          if i.name == "knn_fleet_hedge_wasted_total"]
                if wasted:
                    break
                time.sleep(0.02)
            assert wasted, "the hedge loser was never counted"
            assert dict(wasted[0].labels)["outcome"] == "completed"
            # Both replicas served it -> both stitch in, hop-tagged.
            st, stitched = _http(url, f"/debug/requests?id={rid}")
            assert st == 200
            reps = stitched["replicas"]
            assert reps[a.url]["upstream_attempt"] == 1
            assert reps[b.url]["upstream_attempt"] == 2
            # Still exactly one router timeline (the hedge is attempts
            # WITHIN one request, not a second request).
            assert sum(1 for t in app.recorder.recent()
                       if t["request_id"] == rid) == 1
        finally:
            _close_router(app, server)

    def test_write_indeterminate_502_no_replica_orphan(
            self, tmp_path, obs_on):
        """Racy path #3: a write fails mid-flight (injected io fault at
        the fleet.forward point — BEFORE the wire, so the primary never
        saw it). The router answers the typed 502 INDETERMINATE with one
        failed-attempt timeline; the primary's recorder has NO entry for
        the id — the stitched doc shows that, rather than inventing an
        orphan."""
        model = KNNClassifier(k=3, engine="xla").fit(_problem(_local_rng()))
        f = _Replica(model, _artifact(model, tmp_path, "f"),
                     mutable=True, follower_of="http://127.0.0.1:9",
                     replicate_ack="none")
        p = _Replica(model, _artifact(model, tmp_path, "p"),
                     mutable=True, replicate_to=[f.url])
        app, server, url = _router([f.url, p.url], event_log=True)
        try:
            with faults.inject("fleet.forward=once:io"):
                st, doc = _http(url, "/insert",
                                {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 502 and "INDETERMINATE" in doc["error"]
            rid = _rid_of(app)
            tl = app.recorder.find(rid)
            assert tl["outcome"] == "http_502"
            assert len(tl["attempts"]) == 1
            assert tl["attempts"][0]["ok"] is False
            assert tl["attempts"][0]["rung"] == p.url
            # The fault fired before the wire: the primary never traced
            # this id (checked in-process AND via the stitched fetch).
            assert p.app.recorder.find(rid) is None
            st, stitched = _http(url, f"/debug/requests?id={rid}")
            assert st == 200
            assert stitched["replicas"][p.url] is None
            # The passive demotion is audited with the write's id.
            demotes = app.events.find("passive-demote")
            assert demotes and demotes[-1]["request_id"] == rid
        finally:
            _close_router(app, server)
            p.app.close()
            f.close()

    def test_debug_requests_listing_and_disabled_404(self, plain_pair):
        a, b, model = plain_pair
        app, server, url = _router([a.url, b.url])
        try:
            q = model.train_.features[:1].tolist()
            for _ in range(3):
                st, _doc = _http(url, "/kneighbors", {"instances": q})
                assert st == 200
            st, doc = _http(url, "/debug/requests?n=2")
            assert st == 200 and len(doc["requests"]) == 2
            assert doc["completed"] >= 3
            st, doc = _http(url, "/debug/requests?id=nope")
            assert st == 404
            st, doc = _http(url, "/debug/events")
            assert st == 404  # no --event-log -> typed 404, not []
        finally:
            _close_router(app, server)
        app2, server2, url2 = _router([a.url], flight_recorder_size=0)
        try:
            st, doc = _http(url2, "/debug/requests")
            assert st == 404 and "disabled" in doc["error"]
        finally:
            _close_router(app2, server2)

    def test_access_log_one_line_per_routed_request(self, plain_pair,
                                                    tmp_path):
        a, b, model = plain_pair
        log_path = tmp_path / "router-access.jsonl"
        app, server, url = _router([a.url, b.url],
                                   access_log=str(log_path),
                                   health_interval_s=3600.0)
        try:
            q = model.train_.features[:1].tolist()
            st, _doc = _http(url, "/kneighbors", {"instances": q})
            assert st == 200
            a.kill()
            app._rr = 1  # the retry walk starts at the corpse
            st, _doc = _http(url, "/kneighbors", {"instances": q})
            assert st == 200
        finally:
            _close_router(app, server)
            a.app.close()
        # The handler writes its line AFTER the response goes out — poll
        # (bounded) rather than reading once.
        lines = []
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            lines = [json.loads(ln) for ln in
                     log_path.read_text().strip().splitlines() if ln]
            if len(lines) >= 2:
                break
            time.sleep(0.01)
        assert len(lines) == 2
        for ln in lines:
            assert ln["kind"] == "kneighbors" and ln["status"] == 200
            assert ln["replica"] in (a.url, b.url)
            assert ln["request_id"]
            assert "dispatch" in ln["phases"]
        # The retried request shows both attempts in its line.
        retried = lines[1]
        assert retried["replicas_tried"] == 2
        assert len(retried["attempts"]) == 2


# -- 4. federation + fleet debug ---------------------------------------------


class TestFederation:
    @pytest.fixture
    def pair_router(self, tmp_path, obs_on):
        import shutil

        model = KNNClassifier(k=3, engine="xla").fit(_problem(_local_rng()))
        a_dir = _artifact(model, tmp_path, "a")
        b_dir = tmp_path / "b"
        shutil.copytree(a_dir, b_dir)
        a = _Replica(model, a_dir)
        b = _Replica(model, b_dir)
        app, server, url = _router([a.url, b.url])
        yield a, b, model, app, url
        _close_router(app, server)
        a.close()
        b.close()

    def test_metrics_json_snapshot_shape(self, pair_router):
        a, _b, model, _app, _url = pair_router
        q = model.train_.features[:1].tolist()
        st, _doc = _http(a.url, "/kneighbors", {"instances": q})
        assert st == 200
        st, doc = _http(a.url, "/metrics?format=json")
        assert st == 200 and isinstance(doc["snapshot"], list)
        names = {r["name"] for r in doc["snapshot"]}
        assert "knn_serve_requests_total" in names
        hist = next(r for r in doc["snapshot"]
                    if r["kind"] == "histogram")
        assert {"buckets", "counts", "sum", "count"} <= set(hist)

    def test_router_metrics_federate_with_replica_label(self,
                                                        pair_router):
        a, b, model, _app, url = pair_router
        q = model.train_.features[:1].tolist()
        st, _doc = _http(url, "/kneighbors", {"instances": q})
        assert st == 200
        import urllib.request

        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        # Per-replica attribution survives the merge...
        assert f'replica="{a.url}"' in text
        assert f'replica="{b.url}"' in text
        # ...the router's own instruments overlay unlabeled...
        assert "knn_fleet_router_requests_total" in text
        # ...and the scrape self-reports.
        assert 'knn_fleet_scrape_total{' in text

    def test_debug_fleet_joins_live_documents_and_events(self,
                                                         tmp_path,
                                                         obs_on):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(_local_rng()))
        a = _Replica(model, _artifact(model, tmp_path, "a"))
        app, server, url = _router([a.url], event_log=True)
        try:
            app.events.emit("demote", replica=a.url, role=None)
            st, doc = _http(url, "/debug/fleet")
            assert st == 200
            live = doc["live"][a.url]
            assert live["healthz"]["ready"] is True
            assert "mutable" in live["capacity"]
            assert "enabled" in live["quality"]
            assert doc["events"][-1]["event"] == "demote"
            assert doc["event_log"]["emitted"] >= 1
            assert doc["flight_recorder"]["capacity"] == 256
        finally:
            _close_router(app, server)
            a.close()


# -- 5. replication-lag + staleness + failover-window SLIs -------------------


class TestReplicationSLIs:
    @pytest.fixture
    def fleet(self, tmp_path, obs_on):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(_local_rng()))
        f = _Replica(model, _artifact(model, tmp_path, "f"),
                     mutable=True, follower_of="http://127.0.0.1:9",
                     replicate_ack="none")
        p = _Replica(model, _artifact(model, tmp_path, "p"),
                     mutable=True, replicate_to=[f.url])
        yield p, f, model
        p.app.close()
        f.close()

    def test_lag_clock_and_gauges(self, fleet):
        p, f, _model = fleet
        st, doc = _http(p.url, "/insert",
                        {"rows": [[1.0] * 4], "labels": [0]})
        assert st == 200 and doc["seq"] == 1
        # The semi-sync ack confirmed seq 1 -> the primary holds a
        # wall-clock lag for this follower, and exports it.
        deadline = time.monotonic() + 5
        while (time.monotonic() < deadline
               and p.app.fleet.follower_lag_ms(f.url) is None):
            time.sleep(0.02)
        lag = p.app.fleet.follower_lag_ms(f.url)
        assert lag is not None and 0.0 <= lag < 5000.0
        shipper = next(iter(p.app.fleet._shippers.values()))
        assert shipper.export()["lag_ms"] == lag
        gauges = {i.name for i in obs.registry().instruments()}
        assert "knn_fleet_replication_lag_ms" in gauges

    def test_follower_staleness_annotates_reads(self, fleet):
        p, f, model = fleet
        st, doc = _http(p.url, "/insert",
                        {"rows": [[1.0] * 4], "labels": [0]})
        assert st == 200
        deadline = time.monotonic() + 5
        while (time.monotonic() < deadline
               and f.app.fleet.engine.seq < 1):
            time.sleep(0.02)
        q = model.train_.features[:1].tolist()
        # Caught up: no staleness field.
        st, doc = _http(f.url, "/kneighbors", {"instances": q})
        assert st == 200 and "staleness_seq" not in doc
        assert f.app.fleet.staleness_seq() == 0
        # The follower has SEEN primary seq 4 but only applied 1: its
        # answers are 3 writes behind and must say so.
        f.app.fleet.primary_seq_seen = 4
        assert f.app.fleet.staleness_seq() == 3
        st, doc = _http(f.url, "/kneighbors", {"instances": q})
        assert st == 200 and doc["staleness_seq"] == 3
        tl = f.app.recorder.recent(1)[0]
        assert tl["staleness_seq"] == 3
        # A primary never reports staleness.
        st, doc = _http(p.url, "/kneighbors", {"instances": q})
        assert st == 200 and "staleness_seq" not in doc

    def test_failover_window_measured_and_audited(self, fleet):
        p, f, _model = fleet
        app, server, url = _router([f.url, p.url], event_log=True)
        try:
            st, doc = _http(url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 200
            p.kill()
            app.set.poll_once()
            st, doc = _http(url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 503  # the onset
            onset_rid = _rid_of(app)
            st, doc = _http(url, "/admin/promote", {})
            assert st == 200
            st, doc = _http(url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 200  # closes the window
            wins = app.events.find("failover-window")
            assert len(wins) == 1
            assert wins[0]["window_ms"] > 0
            assert wins[0]["onset_request_id"] == onset_rid
            promotes = app.events.find("promote")
            assert promotes and promotes[0]["replica"] == f.url
            hists = [i for i in obs.registry().instruments()
                     if i.name == "knn_fleet_failover_window_ms"]
            assert hists and hists[0].count == 1
            # A second healthy write does NOT observe another window.
            st, doc = _http(url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 200
            assert hists[0].count == 1
        finally:
            _close_router(app, server)
