"""Pallas kernel tests — interpret mode on the CPU mesh (SURVEY.md §4).

Parity fixtures use integer-grid features so the matmul distance expansion
(|q|^2 - 2 q·t + |t|^2) is exact in float32 and predictions must match the
oracle bit-for-bit, including dist==0 duplicate-row ties.
"""

import numpy as np
import pytest

from knn_tpu.backends.oracle import knn_oracle
from knn_tpu.ops.pallas_knn import knn_pallas_candidates, predict_pallas


def _int_grid_problem(rng, n=700, q=90, d=9, c=10, hi=6):
    train_x = rng.integers(0, hi, (n, d)).astype(np.float32)
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, hi, (q - q // 2, d)).astype(np.float32)]
    )
    return train_x, train_y, test_x, c


class TestPallasKernel:
    @pytest.mark.parametrize("k", [1, 5])
    def test_parity_with_oracle(self, rng, k):
        train_x, train_y, test_x, c = _int_grid_problem(rng)
        want = knn_oracle(train_x, train_y, test_x, k, c)
        got = predict_pallas(
            train_x, train_y, test_x, k, c,
            block_q=32, block_n=128, interpret=True,
        )
        np.testing.assert_array_equal(got, want)

    def test_duplicate_rows_tie_stability(self, rng):
        # Exact-duplicate rows straddling train-tile boundaries: kept
        # candidate must be the lowest global index (SURVEY.md §7 (b)).
        base = rng.integers(0, 3, (64, 4)).astype(np.float32)
        train_x = np.tile(base, (8, 1))  # every row repeated 8x, 512 rows
        train_y = rng.integers(0, 5, 512).astype(np.int32)
        test_x = base[:16]
        want = knn_oracle(train_x, train_y, test_x, 9, 5)
        got = predict_pallas(
            train_x, train_y, test_x, 9, 5,
            block_q=8, block_n=128, interpret=True,
        )
        np.testing.assert_array_equal(got, want)

    def test_candidates_sorted_and_padded_masked(self, rng):
        # Raw kernel output: sorted by (dist, index), no padded-row indices.
        train_x = rng.integers(0, 4, (130, 5)).astype(np.float32)  # pads to 256
        test_x = rng.integers(0, 4, (17, 5)).astype(np.float32)  # pads to 32
        k = 7
        import jax.numpy as jnp

        from knn_tpu.utils.padding import pad_axis_to_multiple

        tx, _ = pad_axis_to_multiple(train_x, 128, axis=0)
        qx, _ = pad_axis_to_multiple(test_x, 32, axis=0)
        tx, _ = pad_axis_to_multiple(tx, 128, axis=1)
        qx, _ = pad_axis_to_multiple(qx, 128, axis=1)
        d, i = knn_pallas_candidates(
            jnp.asarray(tx), jnp.asarray(qx), 130, k,
            block_q=32, block_n=128, interpret=True,
        )
        d, i = np.asarray(d)[:17], np.asarray(i)[:17]
        assert (i < 130).all(), "padded train rows leaked into candidates"
        assert np.isfinite(d).all()
        # Lexicographic (dist, index) ascending along k.
        assert (d[:, :-1] <= d[:, 1:]).all()
        same = d[:, :-1] == d[:, 1:]
        assert (i[:, :-1][same] < i[:, 1:][same]).all()
        # Distances match brute force.
        bruteforce = ((test_x[:, None, :] - train_x[None, :130, :]) ** 2).sum(-1)
        np.testing.assert_allclose(d, np.sort(bruteforce, axis=1)[:, :k], rtol=1e-5)

    def test_nan_features_match_oracle(self):
        train_x = np.array([[1.0], [2.0], [3.0]], np.float32)
        train_y = np.array([2, 2, 1], np.int32)
        test_x = np.array([[np.nan], [2.0]], np.float32)
        want = knn_oracle(train_x, train_y, test_x, 2, 3)
        got = predict_pallas(
            train_x, train_y, test_x, 2, 3,
            block_q=8, block_n=8, interpret=True,
        )
        np.testing.assert_array_equal(got, want)

    def test_all_inf_candidates_are_distinct(self):
        # Regression: when every distance is +inf (NaN query), retiring a
        # selected candidate only on the distance key re-selects the same
        # train index k times. Labels are distinct so a duplicated index
        # flips the vote: oracle admits inf candidates in index order
        # (neighbors 0,1,2 -> labels 0,1,1 -> vote 1).
        train_x = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
        train_y = np.array([0, 1, 1, 2], np.int32)
        test_x = np.array([[np.nan]], np.float32)
        want = knn_oracle(train_x, train_y, test_x, 3, 3)
        got = predict_pallas(
            train_x, train_y, test_x, 3, 3,
            block_q=8, block_n=8, interpret=True,
        )
        np.testing.assert_array_equal(got, want)

    def test_wide_feature_exact_stripe(self, rng):
        # stripe_auto_eligible admits exact problems up to d=128 (measured
        # 1.3-2.25x over the XLA formulations on v5e); pin correctness of the
        # wide unroll — the random-shape fuzz only reaches d=13.
        from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

        d, n, q, k = 128, 300, 12, 6
        train_x = rng.integers(0, 3, (n, d)).astype(np.float32)
        test_x = np.concatenate(
            [train_x[:4], rng.integers(0, 3, (q - 4, d)).astype(np.float32)]
        )
        dists, idx = stripe_candidates_arrays(
            train_x, test_x, k, block_q=8, block_n=128, interpret=True
        )
        bruteforce = ((test_x[:, None, :] - train_x[None, :, :]) ** 2).sum(-1)
        for qi in range(q):
            order = np.lexsort((np.arange(n), bruteforce[qi]))[:k]
            np.testing.assert_array_equal(idx[qi], order)

    def test_lite_rounds_starved_lanes_match_brute_force(self, rng):
        # Finite inputs pass the stripe_inputs_finite gate, enabling the
        # index-retirement-free rounds: lanes whose stripe runs out of valid
        # elements before level k re-select the same stale index with an
        # (inf, i) key. With >= k finite candidates globally those
        # duplicates must never surface: n=70 over 128 lanes starves every
        # lane (0-1 valid elements each) at k=5.
        from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

        train_x = rng.integers(0, 5, (70, 4)).astype(np.float32)
        test_x = rng.integers(0, 5, (9, 4)).astype(np.float32)
        k = 5
        d, i = stripe_candidates_arrays(
            train_x, test_x, k, block_q=8, block_n=128, interpret=True
        )
        assert (i < 70).all() and np.isfinite(d).all()
        bruteforce = ((test_x[:, None, :] - train_x[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(d, np.sort(bruteforce, axis=1)[:, :k], rtol=1e-5)

    def test_same_lane_finite_rows_nan_rest_full_retirement(self):
        # Regression (r2 review): with index retirement skipped, a retired
        # finite element's STALE index can hijack the inf tail — finite rows
        # 0 and 128 share a lane, everything else NaN, k=3 at the origin
        # gives [0, 128, 0] under lite rounds instead of the correct
        # [0, 128, 1]. stripe_inputs_finite must detect the NaNs and route
        # to full retirement.
        from knn_tpu.ops.pallas_knn import (
            stripe_candidates_arrays, stripe_inputs_finite,
        )

        n, d, k = 140, 3, 3
        train_x = np.full((n, d), np.nan, np.float32)
        train_x[0] = 1.0
        train_x[128] = 2.0  # same 128-lane as row 0
        test_x = np.zeros((2, d), np.float32)
        assert not stripe_inputs_finite(train_x, test_x)
        dists, idx = stripe_candidates_arrays(
            train_x, test_x, k, block_q=8, block_n=128, interpret=True
        )
        for qi in range(2):
            np.testing.assert_array_equal(idx[qi], [0, 128, 1])
            assert np.isinf(dists[qi][2])

    def test_stripe_inputs_finite_gate(self):
        from knn_tpu.ops.pallas_knn import stripe_inputs_finite

        ok = np.ones((5, 4), np.float32)
        assert stripe_inputs_finite(ok, ok)
        bad = ok.copy()
        bad[2, 1] = np.nan
        assert not stripe_inputs_finite(ok, bad)
        huge = ok * np.float32(1e19)  # squared distances overflow f32
        assert not stripe_inputs_finite(huge, ok)
        # Boundary: values at the no-rounding-headroom bound sqrt(FLT_MAX/4d)
        # can overflow through f32 accumulation rounding at wide d — the gate
        # must reject them (r2 review, reproduced at d=784).
        d = 784
        at_bound = np.full(
            (4, d), np.sqrt(np.finfo(np.float32).max / (4 * d)), np.float32
        )
        assert not stripe_inputs_finite(at_bound, -at_bound)

    def test_nan_heavy_inf_tail_is_index_ordered(self):
        # NaN inputs fail the stripe_inputs_finite gate, so the kernel runs
        # full index retirement and the inf tail must be the smallest
        # NaN-row indices in index order, per the SURVEY.md §3.5.5 NaN
        # policy: 300 rows over >2 lane planes, only two finite rows, k=5.
        from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

        n, d, k = 300, 3, 5
        train_x = np.full((n, d), np.nan, np.float32)
        train_x[10] = 7.0
        train_x[200] = 1.0
        test_x = np.zeros((3, d), np.float32)
        test_x[2] = np.nan  # all-inf query row
        dists, idx = stripe_candidates_arrays(
            train_x, test_x, k, block_q=8, block_n=128, interpret=True
        )
        # Query 0/1 at origin: row 200 (d=3) before row 10 (d=147), then the
        # smallest NaN-row indices 0, 1, 2 with +inf distance.
        for qi in (0, 1):
            np.testing.assert_array_equal(idx[qi], [200, 10, 0, 1, 2])
            assert np.isinf(dists[qi][2:]).all()
        # NaN query: everything inf; tail = indices 0..k-1.
        np.testing.assert_array_equal(idx[2], [0, 1, 2, 3, 4])
        assert np.isinf(dists[2]).all()

    @pytest.mark.parametrize("engine", ["stripe", "merge"])
    def test_engines_match_oracle(self, rng, engine):
        train_x, train_y, test_x, c = _int_grid_problem(rng, n=300, q=40, d=6)
        want = knn_oracle(train_x, train_y, test_x, 4, c)
        got = predict_pallas(
            train_x, train_y, test_x, 4, c,
            block_q=16, block_n=128, interpret=True, engine=engine,
        )
        np.testing.assert_array_equal(got, want)

    def test_stripe_candidates_sorted_and_padded_masked(self, rng):
        # Raw stripe-kernel output contract: sorted by (dist, index), padded
        # train rows never surface, distances match brute force.
        from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

        train_x = rng.integers(0, 4, (130, 5)).astype(np.float32)
        test_x = rng.integers(0, 4, (17, 5)).astype(np.float32)
        k = 7
        d, i = stripe_candidates_arrays(
            train_x, test_x, k, block_q=16, block_n=128, interpret=True
        )
        assert (i < 130).all(), "padded train rows leaked into candidates"
        assert np.isfinite(d).all()
        assert (d[:, :-1] <= d[:, 1:]).all()
        same = d[:, :-1] == d[:, 1:]
        assert (i[:, :-1][same] < i[:, 1:][same]).all()
        bruteforce = ((test_x[:, None, :] - train_x[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(d, np.sort(bruteforce, axis=1)[:, :k], rtol=1e-5)

    @pytest.mark.parametrize("k", [1, 3, 7])
    def test_selection_formulations_identical(self, rng, k):
        # Both selection formulations (merge network / min-extraction
        # rounds) must be bit-identical on a tie-heavy problem — the
        # routing knob picks per (g, k) cost, so whichever is off-route
        # would otherwise rot silently.
        from knn_tpu.ops.pallas_knn import (
            knn_pallas_stripe_candidates, stripe_prepare_queries,
            stripe_prepare_train,
        )
        import jax.numpy as jnp

        train_x = rng.integers(0, 3, (300, 5)).astype(np.float32)
        test_x = rng.integers(0, 3, (40, 5)).astype(np.float32)
        txT, d_pad = stripe_prepare_train(train_x, 128)
        qx = jnp.asarray(stripe_prepare_queries(test_x, 8, d_pad))
        outs = {}
        for sel in ("rounds", "net"):
            d, i = knn_pallas_stripe_candidates(
                jnp.asarray(txT), qx, 300, k, block_q=8, block_n=128,
                d_true=5, interpret=True, select=sel,
            )
            outs[sel] = (np.asarray(d), np.asarray(i))
        np.testing.assert_array_equal(outs["rounds"][0], outs["net"][0])
        np.testing.assert_array_equal(outs["rounds"][1], outs["net"][1])

    def test_auto_route_rule(self):
        # THE routing rule, pinned per (precision, d): narrow exact and
        # any-width bf16 since r3; wide "fast" added r4 (hoisted norms +
        # the 64 MB vmem budget made the wide f32 distance buffer fit,
        # ~1.6x the merge kernel measured interleaved). Narrow fast stays
        # off the stripe kernel (no measurement says it wins there), and
        # k beyond the stripe limit routes away regardless.
        from knn_tpu.ops.pallas_knn import stripe_route_ok

        assert stripe_route_ok("exact", 11, 5)
        assert stripe_route_ok("exact", 128, 5)
        assert not stripe_route_ok("exact", 300, 5)
        assert stripe_route_ok("bf16", 11, 5)
        assert stripe_route_ok("bf16", 784, 5)
        assert stripe_route_ok("fast", 300, 5)
        assert stripe_route_ok("fast", 784, 16)
        assert not stripe_route_ok("fast", 64, 5)
        assert not stripe_route_ok("exact", 11, 17)
        # Extreme widths decline the route (ADVICE r4): past ~24k features
        # (f32 fast) / ~33k (bf16) no block shape fits the 64 MB kernel
        # budget even at the floor train tile, and the no-fallback dispatch
        # points would hard-fail in Mosaic. The threshold tracks the bf16
        # operand's half-width store.
        assert stripe_route_ok("fast", 16000, 5)
        assert not stripe_route_ok("fast", 40000, 5)
        assert stripe_route_ok("bf16", 30000, 5)
        assert not stripe_route_ok("bf16", 40000, 5)

    def test_wide_fast_auto_matches_oracle(self, rng):
        # End-to-end pin for the r4 wide-fast stripe route: small-integer
        # grids make the matmul distance form exact, so the auto-routed
        # prediction must equal the oracle bit-for-bit (interpret mode).
        train_x = rng.integers(0, 6, (300, 200)).astype(np.float32)
        train_y = rng.integers(0, 5, 300).astype(np.int32)
        test_x = np.concatenate([
            train_x[rng.choice(300, 20, replace=False)],
            rng.integers(0, 6, (23, 200)).astype(np.float32),
        ])
        want = knn_oracle(train_x, train_y, test_x, 5, 5)
        got = predict_pallas(
            train_x, train_y, test_x, 5, 5,
            precision="fast", engine="auto", interpret=True,
        )
        np.testing.assert_array_equal(got, want)

    def test_very_wide_fast_blocks_fit_budget(self):
        # stripe_block_sizes must shrink block_n for very wide features so
        # the double-buffered train tile stays within the kernel budget —
        # the auto paths outside predict_pallas have no merge fallback.
        from knn_tpu.ops.pallas_knn import stripe_block_sizes

        bq, bn = stripe_block_sizes(None, None, 1024, 5, d_pad=8192,
                                    precision="fast")
        assert 2 * bn * 8192 * 4 <= (16 << 20)
        assert bq >= 256 and bn >= 128
        bq, bn = stripe_block_sizes(None, None, 1024, 5, d_pad=8192,
                                    precision="bf16")
        assert 2 * bn * 8192 * 2 <= (16 << 20)

    def test_stripe_candidates_chunked_matches_unchunked(self, rng):
        # The windowed host entry (VERDICT r3 #3) must return exactly what
        # one monolithic dispatch returns: chunk_rows=200 makes q=650 span
        # four chunks including a ragged last one (padded up to the shared
        # chunk shape so every chunk reuses one compiled executable).
        from knn_tpu.ops.pallas_knn import (
            knn_pallas_stripe_candidates, stripe_candidates_arrays,
            stripe_prepare_queries, stripe_prepare_train,
        )

        train_x = rng.integers(0, 4, (200, 6)).astype(np.float32)
        test_x = rng.integers(0, 4, (650, 6)).astype(np.float32)
        k, bq, bn = 16, 8, 128
        d, i = stripe_candidates_arrays(
            train_x, test_x, k, block_q=bq, block_n=bn, interpret=True,
            chunk_rows=200,
        )
        assert d.shape == (650, k)
        txT, d_pad = stripe_prepare_train(train_x, bn)
        import jax.numpy as jnp

        dm, im = knn_pallas_stripe_candidates(
            jnp.asarray(txT),
            jnp.asarray(stripe_prepare_queries(test_x, bq, d_pad)),
            200, k, block_q=bq, block_n=bn, interpret=True, d_true=6,
        )
        np.testing.assert_array_equal(d, np.asarray(dm)[:650])
        np.testing.assert_array_equal(i, np.asarray(im)[:650])

    def test_stripe_duplicate_rows_across_tiles(self, rng):
        # Duplicates landing in the same lane stripe across different train
        # tiles AND in different lanes: merge must keep lowest global index.
        base = rng.integers(0, 3, (64, 4)).astype(np.float32)
        train_x = np.tile(base, (8, 1))  # dup every 64 rows; block_n=128
        train_y = rng.integers(0, 5, 512).astype(np.int32)
        test_x = base[:16]
        want = knn_oracle(train_x, train_y, test_x, 9, 5)
        got = predict_pallas(
            train_x, train_y, test_x, 9, 5,
            block_q=8, block_n=128, interpret=True, engine="stripe",
        )
        np.testing.assert_array_equal(got, want)

    def test_stripe_fuzz_random_shapes_match_oracle(self, rng):
        # Randomized shapes exercise every padding boundary: n below/above
        # block_n, q not a block_q multiple, d=1..13, k up to n.
        from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

        for trial in range(12):
            n = int(rng.integers(3, 400))
            q = int(rng.integers(1, 60))
            d = int(rng.integers(1, 14))
            k = int(rng.integers(1, min(n, 12) + 1))
            train_x = rng.integers(0, 3, (n, d)).astype(np.float32)
            test_x = rng.integers(0, 3, (q, d)).astype(np.float32)
            dists, idx = stripe_candidates_arrays(
                train_x, test_x, k, block_q=32, block_n=128, interpret=True
            )
            bf = ((test_x[:, None, :] - train_x[None, :, :]) ** 2).sum(-1)
            order = np.lexsort(
                (np.broadcast_to(np.arange(n), bf.shape), bf), axis=1
            )[:, :k]
            np.testing.assert_array_equal(
                idx, order, err_msg=f"trial {trial}: n={n} q={q} d={d} k={k}"
            )

    @pytest.mark.parametrize("precision", ["fast", "bf16"])
    def test_stripe_mxu_forms_match_oracle_on_01_grid(self, rng, precision):
        # 0/1 features: the matmul expansion and bf16 casts are exact, so the
        # stripe kernel's MXU distance modes must match the oracle bit-for-bit.
        train_x = rng.integers(0, 2, (300, 33)).astype(np.float32)
        train_y = rng.integers(0, 6, 300).astype(np.int32)
        test_x = np.concatenate(
            [train_x[:16], rng.integers(0, 2, (16, 33)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, 5, 6)
        got = predict_pallas(
            train_x, train_y, test_x, 5, 6,
            block_q=32, block_n=128, interpret=True,
            engine="stripe", precision=precision,
        )
        np.testing.assert_array_equal(got, want)

    def test_backend_registered(self, small):
        from knn_tpu.models.knn import KNNClassifier

        train, test = small
        want = knn_oracle(
            train.features, train.labels, test.features, 1, train.num_classes
        )
        model = KNNClassifier(k=1, backend="tpu-pallas").fit(train)
        got = model.predict(test)
        np.testing.assert_array_equal(got, want)

    def test_bf16_precision_parity_on_small_ints(self, rng):
        # bfloat16 represents small integers exactly, so on a 0/1 grid the
        # bf16 MXU path must match the oracle bit-for-bit.
        train_x = rng.integers(0, 2, (300, 32)).astype(np.float32)
        train_y = rng.integers(0, 6, 300).astype(np.int32)
        test_x = np.concatenate(
            [train_x[:10], rng.integers(0, 2, (14, 32)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, 3, 6)
        got = predict_pallas(
            train_x, train_y, test_x, 3, 6,
            block_q=8, block_n=128, interpret=True, precision="bf16",
        )
        np.testing.assert_array_equal(got, want)

    def test_wide_features_bf16_stripe_store(self, rng):
        # The wide-feature bf16 flagship (r3): engine auto routes
        # precision="bf16" to the stripe kernel with the train operand
        # STORED bf16. 0/1 grid => bf16 rounding is exact, so predictions
        # must still match the oracle bit-for-bit.
        train_x = rng.integers(0, 2, (300, 784)).astype(np.float32)
        train_y = rng.integers(0, 10, 300).astype(np.int32)
        test_x = np.concatenate(
            [train_x[:10], rng.integers(0, 2, (6, 784)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, 5, 10)
        got = predict_pallas(
            train_x, train_y, test_x, 5, 10,
            block_q=16, block_n=128, interpret=True, precision="bf16",
        )
        np.testing.assert_array_equal(got, want)

    def test_auto_engine_falls_back_to_merge_on_stripe_failure(
        self, rng, monkeypatch
    ):
        # ADVICE r2: a Mosaic compile failure on an auto-routed stripe
        # dispatch must fall back to the merge kernel, not error out; a
        # FORCED stripe engine must still propagate the failure.
        import knn_tpu.ops.pallas_knn as pk

        train_x, train_y, test_x, c = _int_grid_problem(rng, n=260, q=20)
        want = knn_oracle(train_x, train_y, test_x, 5, c)

        def boom(*a, **kw):
            raise RuntimeError("synthetic Mosaic compile failure")

        monkeypatch.setattr(pk, "stripe_candidates_arrays", boom)
        got = predict_pallas(
            train_x, train_y, test_x, 5, c,
            block_q=16, block_n=128, interpret=True, precision="exact",
        )
        np.testing.assert_array_equal(got, want)
        with pytest.raises(RuntimeError, match="synthetic"):
            predict_pallas(
                train_x, train_y, test_x, 5, c,
                block_q=16, block_n=128, interpret=True,
                precision="exact", engine="stripe",
            )

    def test_wide_features_mnist_shaped(self, rng):
        # BASELINE config-5 shape class: D=784 (pads to 896 lanes), parity on
        # an integer grid where the matmul expansion is exact.
        train_x = rng.integers(0, 2, (600, 784)).astype(np.float32)
        train_y = rng.integers(0, 10, 600).astype(np.int32)
        test_x = np.concatenate(
            [train_x[:20], rng.integers(0, 2, (12, 784)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, 5, 10)
        got = predict_pallas(
            train_x, train_y, test_x, 5, 10,
            block_q=32, block_n=256, interpret=True,
        )
        np.testing.assert_array_equal(got, want)
